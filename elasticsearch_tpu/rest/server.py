"""HTTP JSON REST API.

Reference analog: rest/ (RestController.java PathTrie dispatch :48-162,
handlers under rest/action/*) + http/netty/NettyHttpServerTransport.java.
Route shapes follow rest-api-spec/api/*.json so existing ES clients and
the YAML conformance suites can drive this server.

Implementation: stdlib ThreadingHTTPServer — the control plane is
host-side Python; the device does the heavy lifting, so a native event
loop buys nothing until multi-host RPC lands (transport/).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs, unquote

from ..node import Node
from ..utils.errors import (ElasticsearchTpuError, IllegalArgumentError,
                            IndexNotFoundError)
from .. import __version__


class Route:
    def __init__(self, method: str, pattern: str, handler):
        self.method = method
        self.handler = handler
        parts = pattern.strip("/").split("/")
        regex = []
        self.params: list[str] = []
        for p in parts:
            if p.startswith("{"):
                name = p[1:-1]
                self.params.append(name)
                regex.append(r"(?P<%s>[^/]+)" % name)
            else:
                regex.append(re.escape(p))
        self.regex = re.compile("^/" + "/".join(regex) + "/?$")
        # literal segments outrank {param} segments position-by-position
        # (ref: RestController PathTrie wildcard fallback); lexicographic
        # comparison of this key picks the most-literal matching route
        self.spec_key = tuple(1 if p.startswith("{") else 0 for p in parts)

    def match(self, method: str, path: str):
        if method != self.method:
            return None
        m = self.regex.match(path)
        if m is None:
            return None
        # decode AFTER segment split so %2F inside an id stays one
        # segment (the reference's PathTrie decodes per part too)
        return {k: unquote(v) for k, v in m.groupdict().items()}


class RestDispatcher:
    """Method+path -> handler registry (ref: RestController PathTrie)."""

    def __init__(self, node: Node):
        self.node = node
        self.routes: list[Route] = []
        register_routes(self)
        # plugin routes register last so they can't shadow core routes
        # (ref: plugins contribute RestHandlers via onModule(RestModule))
        plugins = getattr(node, "plugins", None)
        if plugins is not None:
            plugins.apply_rest_hooks(self)

    def route(self, method: str, pattern: str):
        def deco(fn):
            self.routes.append(Route(method, pattern, fn))
            return fn
        return deco

    def dispatch(self, method: str, path: str, params: dict, body):
        effective = "GET" if method == "HEAD" else method
        if method == "HEAD":
            # a few handlers differ between GET and exists-style HEAD
            # (e.g. alias exists -> 404); expose the real verb
            params = dict(params, __method="HEAD")
        best = None
        for r in self.routes:
            kw = r.match(effective, path)
            if kw is not None and (best is None
                                   or r.spec_key < best[0].spec_key):
                best = (r, kw)
        if best is not None:
            return best[0].handler(self.node, params, body, **best[1])
        raise IllegalArgumentError(
            f"no handler found for uri [{path}] and method [{method}]")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _truthy(params: dict, key: str) -> bool:
    """REST boolean params accept true/1/'' (bare flag) — ref:
    rest/RestRequest.paramAsBoolean."""
    return params.get(key) in ("true", "1", "", "wait_for")


class RestStatus:
    """Wrap a payload with an explicit HTTP status (e.g. 404 delete)."""

    def __init__(self, status: int, payload):
        self.status = status
        self.payload = payload


def _search_body(params: dict, body) -> dict:
    """Search body + URL params every search route honors (ref:
    RestSearchAction parseSearchRequest: queryCache -> the shard request
    cache override)."""
    b = _body_query(params, body)
    if params.get("query_cache") is not None:
        b = dict(b)
        b["query_cache"] = params["query_cache"]
    # failure-semantics controls (ref: RestSearchAction: request.timeout
    # + allow_partial_search_results); the URL param wins over the body
    if params.get("timeout") is not None:
        b = dict(b)
        b["timeout"] = params["timeout"]
    if params.get("allow_partial_search_results") is not None:
        b = dict(b)
        b["allow_partial_search_results"] = _truthy(
            params, "allow_partial_search_results")
    return b


def _body_query(params: dict, body) -> dict:
    """Merge URI params (q, size, from, sort) into a search body.
    Ref: RestSearchAction.parseSearchRequest."""
    body = dict(body or {})
    q = params.get("q")
    if q and "query" not in body:
        body["query"] = {"query_string": {"query": q}}
    for key in ("size", "from"):
        if key in params:
            body[key] = int(params[key])
    if "sort" in params and "sort" not in body:
        entries = []
        for part in params["sort"].split(","):
            if ":" in part:
                f, o = part.split(":", 1)
                entries.append({f: o})
            else:
                entries.append({part: "asc"})
        body["sort"] = entries
    # URI-level source filtering overrides the body's _source (ref:
    # RestSearchAction.parseSearchSource fetchSource handling)
    inc = params.get("_source_include") or params.get("_source_includes")
    exc = params.get("_source_exclude") or params.get("_source_excludes")
    if inc or exc:
        body["_source"] = {"includes": inc.split(",") if inc else [],
                           "excludes": exc.split(",") if exc else []}
    elif "_source" in params:
        v = params["_source"]
        body["_source"] = (True if v == "true" else
                           False if v == "false" else v.split(","))
    return body


# Column schemas per _cat endpoint: (name, alias, description).
# Ref: each rest/action/cat/Rest*Action.getTableWithHeader — the help
# listing and column aliases come from these, independent of row data.
CAT_COLUMNS: dict[str, list[tuple[str, str, str]]] = {
    "aliases": [("alias", "a", "alias name"),
                ("index", "i", "index the alias points to"),
                ("filter", "fi", "filter"),
                ("routing.index", "ri", "index routing"),
                ("routing.search", "rs", "search routing")],
    "allocation": [("shards", "s", "number of shards on node"),
                   ("disk.used", "du", "disk used (total, not just ES)"),
                   ("disk.avail", "da", "disk available"),
                   ("disk.total", "dt", "total capacity of all volumes"),
                   ("disk.percent", "dp", "percent disk used"),
                   ("host", "h", "host of node"),
                   ("ip", "", "ip of node"),
                   ("node", "n", "name of node")],
    "count": [("epoch", "t", "seconds since 1970-01-01 00:00:00"),
              ("timestamp", "ts", "time in HH:MM:SS"),
              ("count", "dc", "the document count")],
    "fielddata": [("id", "", "node id"),
                  ("host", "h", "host of node"),
                  ("ip", "", "ip of node"),
                  ("node", "n", "name of node"),
                  ("total", "", "total field data usage")],
    "health": [("epoch", "t", "seconds since 1970-01-01 00:00:00"),
               ("timestamp", "ts", "time in HH:MM:SS"),
               ("cluster", "cl", "cluster name"),
               ("status", "st", "health status"),
               ("node.total", "nt", "total number of nodes"),
               ("node.data", "nd", "number of nodes that can store data"),
               ("shards", "t", "total number of shards"),
               ("pri", "p", "number of primary shards"),
               ("relo", "r", "number of relocating nodes"),
               ("init", "i", "number of initializing nodes"),
               ("unassign", "u", "number of unassigned shards"),
               ("pending_tasks", "pt", "number of pending tasks")],
    "indices": [("health", "h", "current health status"),
                ("status", "s", "open/close status"),
                ("index", "i", "index name"),
                ("pri", "p", "number of primary shards"),
                ("rep", "r", "number of replica shards"),
                ("docs.count", "dc", "available docs"),
                ("docs.deleted", "dd", "deleted docs"),
                ("store.size", "ss", "store size of primaries & replicas"),
                ("pri.store.size", "", "store size of primaries")],
    "master": [("id", "", "node id"),
               ("host", "h", "host name"),
               ("ip", "", "ip address"),
               ("node", "n", "node name")],
    "nodes": [("host", "h", "host name"),
              ("ip", "i", "ip address"),
              ("heap.current", "hc", "used heap", False),
              ("heap.percent", "hp", "used heap ratio"),
              ("heap.max", "hm", "max configured heap", False),
              ("ram.percent", "rp", "used machine memory ratio"),
              ("file_desc.current", "fdc",
               "used file descriptors", False),
              ("file_desc.percent", "fdp",
               "used file descriptor ratio", False),
              ("file_desc.max", "fdm", "max file descriptors", False),
              ("load", "l", "most recent load avg"),
              ("node.role", "r", "d:data node, c:client node"),
              ("master", "m", "m:master-eligible, *:current master"),
              ("name", "n", "node name")],
    "plugins": [("id", "", "unique node id"),
                ("name", "n", "node name"),
                ("component", "c", "component name"),
                ("version", "v", "component version"),
                ("type", "t", "plugin type"),
                ("url", "u", "url for site plugins"),
                ("description", "d", "plugin details")],
    "recovery": [("index", "i", "index name"),
                 ("shard", "s", "shard name"),
                 ("time", "t", "recovery time"),
                 ("type", "ty", "recovery type"),
                 ("stage", "st", "recovery stage"),
                 ("source_host", "shost", "source host"),
                 ("target_host", "thost", "target host"),
                 ("repository", "rep", "repository"),
                 ("snapshot", "snap", "snapshot"),
                 ("files", "f", "number of files to recover"),
                 ("files_percent", "fp", "percent of files recovered"),
                 ("bytes", "b", "size to recover in bytes"),
                 ("bytes_percent", "bp", "percent of bytes recovered"),
                 ("total_files", "tf", "total number of files"),
                 ("total_bytes", "tb", "total number of bytes"),
                 ("translog", "tr", "translog operations recovered"),
                 ("translog_percent", "trp",
                  "percent of translog recovery"),
                 ("total_translog", "trt",
                  "current number of translog operations")],
    "segments": [("index", "i", "index name"),
                 ("shard", "s", "shard name"),
                 ("prirep", "p", "primary or replica"),
                 ("ip", "", "ip of node where it lives"),
                 ("id", "", "unique id of node where it lives", False),
                 ("segment", "seg", "segment name"),
                 ("generation", "g", "segment generation"),
                 ("docs.count", "dc", "number of docs in segment"),
                 ("docs.deleted", "dd", "number of deleted docs"),
                 ("size", "si", "segment size in bytes"),
                 ("size.memory", "sm", "segment memory in bytes"),
                 ("committed", "ic", "is segment committed"),
                 ("searchable", "is", "is segment searched"),
                 ("version", "v", "version"),
                 ("compound", "ico", "is segment compound")],
    "shards": [("index", "i", "index name"),
               ("shard", "s", "shard name"),
               ("prirep", "p", "primary or replica"),
               ("state", "st", "shard state"),
               ("docs", "d", "number of docs"),
               ("store", "sto", "store size"),
               ("ip", "", "ip of node"),
               ("id", "", "unique id of node", False),
               ("node", "n", "name of node")],
    "thread_pool": [("pid", "p", "process id", False),
                    ("id", "nodeId", "unique node id", False),
                    ("host", "h", "host name"),
                    ("ip", "i", "ip address"),
                    ("port", "po", "bound transport port", False),
                    ("bulk.active", "ba", "number of active bulk threads"),
                    ("bulk.queue", "bq", "number of bulk threads in queue"),
                    ("bulk.rejected", "br",
                     "number of rejected bulk threads"),
                    ("index.active", "ia",
                     "number of active index threads"),
                    ("index.queue", "iq",
                     "number of index threads in queue"),
                    ("index.rejected", "ir",
                     "number of rejected index threads"),
                    ("search.active", "sa",
                     "number of active search threads"),
                    ("search.queue", "sq",
                     "number of search threads in queue"),
                    ("search.rejected", "sr",
                     "number of rejected search threads")],
}

# thread pools: every pool exposes hidden active/queue/rejected columns
# selectable by alias (ref: RestThreadPoolAction SUPPORTED_NAMES/ALIASES)
_POOL_ALIASES = [("bulk", "b"), ("flush", "f"), ("generic", "ge"),
                 ("get", "g"), ("index", "i"), ("listener", "li"),
                 ("management", "ma"), ("optimize", "o"),
                 ("percolate", "p"), ("refresh", "r"), ("search", "s"),
                 ("snapshot", "sn"), ("suggest", "su"), ("warmer", "w")]
_DEFAULT_POOLS = {"bulk", "index", "search"}
for _pool, _pa in _POOL_ALIASES:
    for _suffix, _sa in (("active", "a"), ("queue", "q"),
                         ("rejected", "r")):
        _shown = _pool in _DEFAULT_POOLS
        _entry = (f"{_pool}.{_suffix}", f"{_pa}{_sa}",
                  f"number of {_suffix} {_pool} threads", _shown)
        if not any(e[0] == _entry[0]
                   for e in CAT_COLUMNS["thread_pool"]):
            CAT_COLUMNS["thread_pool"].append(_entry)

# cat.shards exposes the full per-shard stats column set (hidden by
# default) — ref: RestShardsAction.getTableWithHeader
CAT_COLUMNS["shards"] += [
    (n, "", d, False) for n, d in [
        ("completion.size", "size of completion"),
        ("fielddata.memory_size", "used fielddata cache"),
        ("fielddata.evictions", "fielddata evictions"),
        ("filter_cache.memory_size", "used filter cache"),
        ("filter_cache.evictions", "filter cache evictions"),
        ("flush.total", "number of flushes"),
        ("flush.total_time", "time spent in flush"),
        ("get.current", "number of current get ops"),
        ("get.time", "time spent in get"),
        ("get.total", "number of get ops"),
        ("get.exists_time", "time spent in successful gets"),
        ("get.exists_total", "number of successful gets"),
        ("get.missing_time", "time spent in failed gets"),
        ("get.missing_total", "number of failed gets"),
        ("id_cache.memory_size", "used id cache"),
        ("indexing.delete_current", "number of current deletions"),
        ("indexing.delete_time", "time spent in deletions"),
        ("indexing.delete_total", "number of delete ops"),
        ("indexing.index_current", "number of current indexing ops"),
        ("indexing.index_time", "time spent in indexing"),
        ("indexing.index_total", "number of indexing ops"),
        ("merges.current", "number of current merges"),
        ("merges.current_docs", "number of current merging docs"),
        ("merges.current_size", "size of current merges"),
        ("merges.total", "number of completed merge ops"),
        ("merges.total_docs", "docs merged"),
        ("merges.total_size", "size merged"),
        ("merges.total_time", "time spent in merges"),
        ("percolate.current", "number of current percolations"),
        ("percolate.memory_size", "memory used by percolator"),
        ("percolate.queries", "number of registered percolation queries"),
        ("percolate.time", "time spent percolating"),
        ("percolate.total", "total percolations"),
        ("refresh.total", "total refreshes"),
        ("refresh.time", "time spent in refreshes"),
        ("search.fetch_current", "current fetch phase ops"),
        ("search.fetch_time", "time spent in fetch phase"),
        ("search.fetch_total", "total fetch ops"),
        ("search.open_contexts", "open search contexts"),
        ("search.query_current", "current query phase ops"),
        ("search.query_time", "time spent in query phase"),
        ("search.query_total", "total query phase ops"),
        ("segments.count", "number of segments"),
        ("segments.memory", "memory used by segments"),
        ("segments.index_writer_memory", "memory used by index writer"),
        ("segments.index_writer_max_memory",
         "maximum memory index writer may use"),
        ("segments.version_map_memory", "memory used by version map"),
        ("segments.fixed_bitset_memory",
         "memory used by fixed bit sets"),
        ("warmer.current", "current warmer ops"),
        ("warmer.total", "total warmer ops"),
        ("warmer.total_time", "time spent in warmers"),
    ]]

# byte-valued columns (raw ints in rows) per endpoint: rendered human
# by default, or scaled by the ?bytes= unit (ref: RestTable byte cells)
CAT_BYTE_COLS: dict[str, set] = {
    "allocation": {"disk.used", "disk.avail", "disk.total"},
    "indices": {"store.size", "pri.store.size"},
    "shards": {"store"},
    "segments": {"size"},
    "nodes": {"heap.current", "heap.max"},
    "fielddata": "ALL_BUT_META",   # every per-field column + total
}
_BYTE_UNITS_CAT = {"b": 1, "k": 1024, "kb": 1024, "m": 1024 ** 2,
                   "mb": 1024 ** 2, "g": 1024 ** 3, "gb": 1024 ** 3,
                   "t": 1024 ** 4, "tb": 1024 ** 4}
_NUMERIC_CELL_RE = re.compile(
    r"^-?\d+(\.\d+)?([kmgtp]?b|%)?$")


def _cat_node_id(name: str) -> str:
    """Stable 4-char node id for _cat rows (md5, not the per-process
    randomized str hash, so ids match across endpoints and restarts)."""
    import hashlib
    return hashlib.md5(name.encode()).hexdigest()[:4]


def _human_bytes(n: int) -> str:
    """ES ByteSizeValue.toString: one decimal, trailing .0 dropped."""
    n = int(n)
    for unit, div in (("gb", 1024 ** 3), ("mb", 1024 ** 2),
                      ("kb", 1024)):
        if n >= div:
            v = n / div
            s = f"{v:.1f}"
            if s.endswith(".0"):
                s = s[:-2]
            return s + unit
    return f"{n}b"


def _cat_text(rows, params: dict, endpoint: str | None = None) -> str:
    """Render a _cat result as the aligned text table the reference's
    RestTable produces: every cell padded to the column width plus one
    trailing space, numeric columns right-justified. Supports v (header
    row), h (column select incl. aliases), help (column listing), bytes
    (byte-unit scaling)."""
    if not isinstance(rows, list):
        return str(rows)
    spec = [(e[0], e[1], e[2], e[3] if len(e) > 3 else True)
            for e in CAT_COLUMNS.get(endpoint or "", [])]
    if params.get("help") in ("true", ""):
        if spec:
            w_n = max(len(n) for n, _a, _d, _s in spec)
            w_a = max((len(a) for _n, a, _d, _s in spec), default=0)
            return "".join(
                f"{n.ljust(w_n)} | {a.ljust(w_a)} | {d}\n"
                for n, a, d, _s in spec)
        cols: list[str] = []
        for r in rows:
            for k in r:
                if k not in cols:
                    cols.append(k)
        return "".join(f"{c} | | \n" for c in cols) or "\n"
    # column order: schema order (default-visible) when declared, else
    # first-row insertion order
    if spec:
        columns = [n for n, _a, _d, shown in spec if shown]
        alias_map = {a: n for n, a, _d, _s in spec if a}
    else:
        columns = []
        for r in rows:
            for k in r:
                if k not in columns:
                    columns.append(k)
        alias_map = {}
    labels = None
    if params.get("h"):
        # header shows the REQUESTED token (alias text included); value
        # lookup resolves through the alias map. Unknown tokens are
        # dropped silently (ref: RestTable display headers)
        spec_names = {n for n, _a, _d, _s in spec}
        row_keys = {k for r in rows for k in r}
        columns, labels = [], []
        for tok in params["h"].split(","):
            resolved = alias_map.get(tok, tok)
            if resolved in spec_names or resolved in row_keys:
                columns.append(resolved)
                labels.append(tok)
    if not rows:
        return "\n"
    # byte-valued cells: human units by default, ?bytes= scales
    byte_cols = CAT_BYTE_COLS.get(endpoint or "")
    unit = _BYTE_UNITS_CAT.get(str(params.get("bytes", "")).lower())

    def fmt(col: str, v) -> str:
        if v is None:
            return ""
        is_bytes = byte_cols is not None and (
            byte_cols == "ALL_BUT_META"
            and col not in ("id", "host", "ip", "node")
            or isinstance(byte_cols, set) and col in byte_cols)
        if is_bytes and isinstance(v, (int, float)):
            if unit:
                return str(int(v) // unit)
            return _human_bytes(int(v))
        if isinstance(v, bool):
            return "true" if v else "false"
        return str(v)

    cells = [[fmt(c, r.get(c)) for c in columns] for r in rows]
    header = ([list(labels or columns)]
              if params.get("v") in ("true", "") else [])
    table = header + cells
    widths = [max(len(row[i]) for row in table)
              for i in range(len(columns))]
    # a column whose every non-empty DATA cell is numeric/size/percent
    # right-justifies (ref: RestTable alignment by cell type)
    right = []
    for i in range(len(columns)):
        vals = [row[i] for row in cells if row[i] != ""]
        right.append(bool(vals) and all(
            _NUMERIC_CELL_RE.match(v) for v in vals))
    lines = []
    for ri, row in enumerate(table):
        is_header = header and ri == 0
        # RestTable pads every cell (also the last) and separates with
        # one space, leaving trailing whitespace the YAML regexes expect
        lines.append(" ".join(
            (cell.ljust(widths[i]) if is_header or not right[i]
             else cell.rjust(widths[i]))
            for i, cell in enumerate(row)) + " ")
    return "\n".join(lines) + "\n"


def register_routes(d: RestDispatcher) -> None:
    @d.route("GET", "/")
    def root(node, params, body):
        return {
            "name": node.name,
            "cluster_name": node.cluster_name,
            "version": {"number": __version__,
                        "build_flavor": "tpu-native",
                        # jax stands where lucene stood in the reference
                        "lucene_version": "5.1.0-jax"},
            "tagline": "You Know, for (TPU) Search",
        }

    # -- cluster ----------------------------------------------------------
    @d.route("GET", "/_cluster/health")
    @d.route("GET", "/_cluster/health/{index}")
    def cluster_health(node, params, body, index=None):
        return node.cluster_health(level=params.get("level"), index=index)

    @d.route("GET", "/_cluster/stats")
    def cluster_stats(node, params, body):
        return node.stats()

    @d.route("GET", "/_nodes/stats")
    @d.route("GET", "/_nodes/stats/{metric}")
    @d.route("GET", "/_nodes/{node_id}/stats")
    @d.route("GET", "/_nodes/{node_id}/stats/{metric}")
    def nodes_stats(node, params, body, metric=None, node_id=None):
        r = node.nodes_stats()
        if metric:
            keep = {m.strip() for m in metric.split(",")}
            for nid, stats in r.get("nodes", {}).items():
                base = {k: stats[k] for k in ("name", "timestamp")
                        if k in stats}
                base.update({k: v for k, v in stats.items() if k in keep})
                r["nodes"][nid] = base
        return r

    @d.route("GET", "/_nodes")
    def nodes_info(node, params, body):
        return node.nodes_info()

    # literal /_nodes/X routes MUST register before /_nodes/{metric}:
    # dispatch is first-match, so the wildcard would shadow them
    @d.route("GET", "/_nodes/hot_threads")
    @d.route("GET", "/_nodes/{node_id}/hot_threads")
    def hot_threads(node, params, body, node_id=None):
        from ..node import parse_time_value
        n = int(params.get("threads", 3))
        ms = parse_time_value(params.get("interval", "500ms"), 500)
        return node.hot_threads(n, ms)

    @d.route("GET", "/_nodes/{metric}")
    @d.route("GET", "/_nodes/{node_id}/info/{metric}")
    def nodes_info_filtered(node, params, body, metric, node_id=None):
        r = node.nodes_info()
        keep = {m.strip() for m in metric.split(",")}
        for nid, info in r.get("nodes", {}).items():
            base = {k: info[k] for k in ("name", "version", "roles")
                    if k in info}
            base.update({k: v for k, v in info.items() if k in keep})
            r["nodes"][nid] = base
        return r

    @d.route("GET", "/_cluster/pending_tasks")
    def pending_tasks(node, params, body):
        return {"tasks": getattr(node, "pending_cluster_tasks", lambda: [])()}

    # -- device profiler (ref: hot_threads-class ops tooling; the hot
    # time here is on the DEVICE, so the capture is a jax.profiler
    # trace of live traffic) -------------------------------------------
    @d.route("POST", "/_nodes/profiler/start")
    def profiler_start(node, params, body):
        import os as _os
        from ..utils import profiler
        path = (body or {}).get("path") or params.get("path")
        if not path:
            raise IllegalArgumentError(
                "profiler start requires [path] (trace output dir)")
        # REST callers must not write trace artifact trees to arbitrary
        # node directories: the dir is resolved UNDER data_path, with
        # absolute and parent-escaping paths rejected
        path = str(path)
        if not node.data_path:
            raise IllegalArgumentError(
                "profiler start requires a node [path.data] to resolve "
                "the trace dir under")
        if _os.path.isabs(path) or ".." in path.split(_os.sep):
            raise IllegalArgumentError(
                f"profiler [path] must be relative to the node data "
                f"path (no absolute or '..' components): [{path}]")
        base = _os.path.realpath(node.data_path)
        target = _os.path.realpath(_os.path.join(base, path))
        if target != base and not target.startswith(base + _os.sep):
            raise IllegalArgumentError(
                f"profiler [path] escapes the node data path: [{path}]")
        return profiler.start(target)

    @d.route("POST", "/_nodes/profiler/stop")
    def profiler_stop(node, params, body):
        from ..utils import profiler
        return profiler.stop()

    @d.route("GET", "/_nodes/profiler")
    def profiler_status(node, params, body):
        from ..utils import profiler
        return profiler.status()

    @d.route("GET", "/_cluster/allocation/explain")
    @d.route("POST", "/_cluster/allocation/explain")
    def allocation_explain(node, params, body):
        """Per-node, per-decider allocation decisions for one shard
        copy. The embedded node mirrors itself into a one-node
        ClusterState and runs the REAL deciders; multi-node clusters
        answer through ClusterNode.allocation_explain."""
        from ..cluster.allocation import AllocationService
        from ..cluster.state import (ClusterState, DiscoveryNode,
                                     DiscoveryNodes, IndexMetadata,
                                     IndexRoutingTable, Metadata,
                                     RoutingTable, ShardState)
        body = body or {}
        index = body.get("index", params.get("index"))
        if index is None:
            if not node.indices:
                raise IllegalArgumentError(
                    "no unassigned shard to explain; specify index/"
                    "shard/primary")
            index = next(iter(node.indices))
        svc = node._index(str(index))
        shard_id = int(body.get("shard", params.get("shard", 0)))
        primary = str(body.get("primary",
                               params.get("primary", True))).lower() \
            not in ("false", "0")
        local = DiscoveryNode(node_id=node.name or "local")
        tbl = IndexRoutingTable.new(str(index), svc.num_shards, 0)
        started = IndexRoutingTable(
            str(index), tuple(
                type(g)(g.index, g.shard, tuple(
                    c.initialize(local.node_id).start()
                    for c in g.copies))
                for g in tbl.shards))
        state = ClusterState(
            nodes=DiscoveryNodes(nodes={local.node_id: local},
                                 master_node_id=local.node_id,
                                 local_node_id=local.node_id),
            metadata=Metadata(indices={str(index): IndexMetadata(
                index=str(index), number_of_shards=svc.num_shards,
                number_of_replicas=0)}),
            routing_table=RoutingTable(
                indices={str(index): started}))
        return AllocationService().explain_shard(state, str(index),
                                                 shard_id, primary)

    @d.route("POST", "/_cluster/reroute")
    def cluster_reroute(node, params, body):
        # single-node: commands validated and acked; allocation is
        # identity (ref: action/admin/cluster/reroute/ +
        # RoutingExplanations when ?explain)
        out: dict = {"acknowledged": True,
                     "state": {"cluster_name": node.cluster_name}}
        metric = params.get("metric")
        if metric:
            state = node.cluster_state(metric)
            state.pop("cluster_name", None)
            out["state"].update(state)
        if _truthy(params, "explain"):
            explanations = []
            for cmd in (body or {}).get("commands") or []:
                name, args = next(iter(cmd.items()))
                args = dict(args or {})
                if name == "cancel":
                    args.setdefault("allow_primary", False)
                    decision = {
                        "decider": "cancel_allocation_command",
                        "decision": "NO",
                        "explanation":
                            f"can't cancel [{args.get('shard')}] on "
                            f"node [{args.get('node')}]: shard not "
                            f"found or not cancellable"}
                else:
                    decision = {"decider": f"{name}_allocation_command",
                                "decision": "NO",
                                "explanation": f"single-node cluster "
                                               f"cannot [{name}]"}
                explanations.append({"command": name,
                                     "parameters": args,
                                     "decisions": [decision]})
            out["explanations"] = explanations
        return out

    @d.route("GET", "/_cat/thread_pool")
    def cat_thread_pool(node, params, body):
        import os as _os
        st = node.thread_pool.stats()

        def pool(name):
            s = st.get(name, {})
            return (s.get("active", 0), s.get("queue", 0),
                    s.get("rejected", 0))
        row = {"pid": _os.getpid(), "id": _cat_node_id(node.name),
               "host": "127.0.0.1", "ip": "127.0.0.1", "port": "-"}
        for pname, _alias in _POOL_ALIASES:
            a, q, rj = pool(pname)
            row[f"{pname}.active"] = a
            row[f"{pname}.queue"] = q
            row[f"{pname}.rejected"] = rj
            row[f"{pname}.type"] = "fixed"
            row[f"{pname}.size"] = 4
            row[f"{pname}.queueSize"] = ""
            row[f"{pname}.largest"] = a
            row[f"{pname}.completed"] = 0
            row[f"{pname}.min"] = ""
            row[f"{pname}.max"] = ""
            row[f"{pname}.keepAlive"] = ""
        return [row]

    @d.route("GET", "/_cat/allocation")
    @d.route("GET", "/_cat/allocation/{node_id}")
    def cat_allocation(node, params, body, node_id=None):
        if node_id is not None and node_id not in (
                "_master", "_local", node.name, "*"):
            return []
        shards = sum(len(s.shards) for s in node.indices.values())
        used = sum(seg.nbytes() for svc in node.indices.values()
                   for eng in svc.shards.values()
                   for seg in eng.segments)
        avail = 1 << 30
        total = used + avail
        return [{"shards": shards, "disk.used": used,
                 "disk.avail": avail, "disk.total": total,
                 "disk.percent": int(used * 100 / total),
                 "host": "127.0.0.1", "ip": "127.0.0.1",
                 "node": node.name}]

    @d.route("GET", "/_cat/pending_tasks")
    def cat_pending_tasks(node, params, body):
        return []

    @d.route("GET", "/_cat/plugins")
    def cat_plugins(node, params, body):
        return [{"id": _cat_node_id(node.name), "name": node.name,
                 "component": p["name"], "version": p["version"],
                 "type": "j", "url": "",
                 "description": p["description"]}
                for p in node.plugins.info()]

    @d.route("GET", "/_cat/nodeattrs")
    def cat_nodeattrs(node, params, body):
        return [{"node": node.name, "attr": "accelerator",
                 "value": "tpu"}]

    @d.route("GET", "/_cat/fielddata")
    @d.route("GET", "/_cat/fielddata/{fields}")
    def cat_fielddata(node, params, body, fields=None):
        # one row per node: total + one byte column per loaded field
        # (ref: RestFielddataAction)
        per_field: dict[str, int] = {}
        for name, svc in sorted(node.indices.items()):
            for sid, eng in svc.shards.items():
                for seg in eng.segments:
                    for col in (*seg.keywords.values(),
                                *seg.numerics.values()):
                        fname = col.name
                        if fname.endswith(".keyword") \
                                and fname[:-8] in seg.text:
                            # dynamic keyword twin: fielddata loaded on
                            # behalf of the parent text field
                            fname = fname[:-8]
                        per_field[fname] = (
                            per_field.get(fname, 0) + col.nbytes())
        want = (params.get("fields") or fields)
        if want:
            sel = [f.strip() for f in want.split(",")]
            shown = {f: per_field.get(f, 0) for f in sel
                     if f in per_field}
        else:
            shown = per_field
        row = {"id": _cat_node_id(node.name),
               "host": "127.0.0.1", "ip": "127.0.0.1",
               "node": node.name,
               "total": sum(per_field.values())}
        row.update(sorted(shown.items()))
        return [row]

    @d.route("GET", "/_cat/recovery")
    @d.route("GET", "/_cat/recovery/{index}")
    def cat_recovery(node, params, body, index=None):
        out = []
        for name, svc in sorted(node.indices.items()):
            if index and name != index:
                continue
            for sid, eng in svc.shards.items():
                size = eng.segment_stats()["memory_in_bytes"]
                nfiles = len(eng.segments)
                out.append({
                    "index": name, "shard": sid, "time": 0,
                    "type": "gateway",
                    # a corrupt-contained shard surfaces here too
                    # (recovery_status carries the structured reason)
                    "stage": ("failed" if eng.failed is not None
                              else "done"),
                    "source_host": "127.0.0.1",
                    "target_host": "127.0.0.1",
                    "repository": "n/a", "snapshot": "n/a",
                    "files": nfiles, "files_percent": "100.0%",
                    "bytes": size, "bytes_percent": "100.0%",
                    "total_files": nfiles, "total_bytes": size,
                    "translog": 0, "translog_percent": "100.0%",
                    "total_translog": 0})
        return out

    @d.route("GET", "/_cat/repositories")
    def cat_repositories(node, params, body):
        repos = getattr(node.snapshots, "repositories", {})
        return [{"id": rid, "type": "fs"} for rid in sorted(repos)]

    @d.route("GET", "/_cat/snapshots/{repo}")
    def cat_snapshots(node, params, body, repo):
        r = node.snapshots.repositories.get(repo)
        if r is None:
            return []
        return [{"id": sid, "status": "SUCCESS"}
                for sid in r.list_snapshots()]

    def _stats_params(params):
        def _csv(key):
            return params[key].split(",") if params.get(key) else None
        return {
            "level": params.get("level", "indices"),
            "types": _csv("types"),
            "groups": _csv("groups"),
            "fields": _csv("fields"),
            "fielddata_fields": _csv("fielddata_fields"),
            "completion_fields": _csv("completion_fields"),
        }

    @d.route("GET", "/_stats")
    @d.route("GET", "/_stats/{metric}")
    def stats(node, params, body, metric=None):
        return node.indices_stats(None, metric, **_stats_params(params))

    @d.route("GET", "/_cat/indices")
    def cat_indices(node, params, body):
        return node.cat_indices()

    @d.route("GET", "/_cat/health")
    def cat_health(node, params, body):
        import datetime
        h = node.cluster_health()
        now = datetime.datetime.now(datetime.timezone.utc)
        row = {}
        if params.get("ts") != "false":
            row["epoch"] = int(now.timestamp())
            row["timestamp"] = now.strftime("%H:%M:%S")
        row.update({
            "cluster": h["cluster_name"], "status": h["status"],
            "node.total": h["number_of_nodes"],
            "node.data": h.get("number_of_data_nodes",
                               h["number_of_nodes"]),
            "shards": h["active_shards"],
            "pri": h.get("active_primary_shards", h["active_shards"]),
            "relo": h.get("relocating_shards", 0),
            "init": h.get("initializing_shards", 0),
            "unassign": h.get("unassigned_shards", 0),
            "pending_tasks": h.get("number_of_pending_tasks", 0)})
        return [row]

    # -- search (order matters: register before /{index} wildcards) -------
    @d.route("GET", "/_search")
    @d.route("POST", "/_search")
    def search_all(node, params, body):
        return node.search(None, _search_body(params, body),
                           scroll=params.get("scroll"),
                           search_type=params.get("search_type"),
                           tenant=params.get("tenant_id"))

    @d.route("GET", "/{index}/_search")
    @d.route("POST", "/{index}/_search")
    def search(node, params, body, index):
        return node.search(index, _search_body(params, body),
                           scroll=params.get("scroll"),
                           search_type=params.get("search_type"),
                           tenant=params.get("tenant_id"))

    # indexed search templates (ref: RestPutSearchTemplateAction — ES 2.0
    # stored them in the .scripts index under lang `mustache`)
    @d.route("PUT", "/_search/template/{id}")
    @d.route("POST", "/_search/template/{id}")
    def put_indexed_template(node, params, body, id):
        body = body or {}
        src = body.get("template", body)
        if isinstance(src, dict):
            # compact separators: the stored form is matched by regex in
            # clients/tests (query\S\S\S\Smatch_all)
            src = json.dumps(src, separators=(",", ":"))
        src = str(src)
        if "{{}}" in src:
            # ref: MustacheScriptEngineService compile failure on an
            # empty mustache tag
            raise IllegalArgumentError(
                f"Unable to parse template [{src[:80]}]")
        node.put_stored_script(f"__template__{id}", src)
        return {"acknowledged": True, "_id": id, "created": True,
                "_version": 1}

    @d.route("GET", "/_search/template/{id}")
    def get_indexed_template(node, params, body, id):
        from ..script import ScriptService
        try:
            src = ScriptService.instance().get_stored(f"__template__{id}")
        except ElasticsearchTpuError:
            return RestStatus(404, {"_index": ".scripts", "_id": id,
                                    "found": False, "lang": "mustache"})
        return {"_index": ".scripts", "_id": id, "found": True,
                "lang": "mustache", "template": src, "_version": 1}

    @d.route("DELETE", "/_search/template/{id}")
    def delete_indexed_template(node, params, body, id):
        found = node.delete_stored_script(f"__template__{id}")
        if not found:
            return RestStatus(404, {"found": False,
                                    "_index": ".scripts", "_id": id,
                                    "_version": 1})
        return {"found": True, "_index": ".scripts", "_id": id,
                "_version": 2, "acknowledged": True}

    @d.route("GET", "/_search/template")
    @d.route("POST", "/_search/template")
    def search_template_all(node, params, body):
        return node.search_template(None, body)

    @d.route("GET", "/{index}/_search/template")
    @d.route("POST", "/{index}/_search/template")
    def search_template(node, params, body, index):
        return node.search_template(index, body)

    @d.route("GET", "/_render/template")
    @d.route("POST", "/_render/template")
    def render_template(node, params, body):
        return node.render_template(body)

    def _tv_body(params, body):
        body = dict(body or {})
        for flag in ("term_statistics", "field_statistics", "positions",
                     "offsets", "payloads", "realtime"):
            if flag in params and flag not in body:
                body[flag] = params[flag] in ("true", "1", "", "True")
        return body

    @d.route("GET", "/{index}/_termvectors/{id}")
    @d.route("POST", "/{index}/_termvectors/{id}")
    def termvectors(node, params, body, index, id):
        fields = params.get("fields")
        return node.term_vectors(index, id, _tv_body(params, body),
                                 fields.split(",") if fields else None)

    @d.route("GET", "/{index}/{type}/{id}/_termvectors")
    @d.route("POST", "/{index}/{type}/{id}/_termvectors")
    @d.route("GET", "/{index}/{type}/{id}/_termvector")
    @d.route("POST", "/{index}/{type}/{id}/_termvector")
    def termvectors_typed(node, params, body, index, type, id):
        fields = params.get("fields")
        r = node.term_vectors(index, id, _tv_body(params, body),
                              fields.split(",") if fields else None)
        r["_type"] = type
        return r

    @d.route("GET", "/_mtermvectors")
    @d.route("POST", "/_mtermvectors")
    @d.route("GET", "/{index}/_mtermvectors")
    @d.route("POST", "/{index}/_mtermvectors")
    @d.route("GET", "/{index}/{type}/_mtermvectors")
    @d.route("POST", "/{index}/{type}/_mtermvectors")
    def mtermvectors(node, params, body, index=None, type=None):
        if body is None and params.get("ids"):
            body = {"docs": [{"_id": i}
                             for i in params["ids"].split(",")]}
        body = dict(body or {})
        defaults = _tv_body(params, {})
        if defaults and body.get("docs"):
            body["docs"] = [{**defaults, **spec}
                            for spec in body["docs"]]
        return node.mtermvectors(index, body)

    @d.route("POST", "/_msearch")
    @d.route("POST", "/{index}/_msearch")
    def msearch(node, params, body, index=None):
        # body is a list of (header, body) pairs from ndjson. The whole
        # batch rides ONE dispatch-scheduler pass (node.msearch):
        # identical-plan items coalesce into one batched device program,
        # the rest pipeline their tunnel round trips; items answer with
        # their own took/status. Headers may carry a per-item
        # search_type (ref: RestMultiSearchAction header parsing).
        requests = []
        lines = body if isinstance(body, list) else []
        for i in range(0, len(lines) - 1, 2):
            header, search_body = lines[i] or {}, lines[i + 1]
            requests.append((header.get("index", index), search_body,
                             header.get("search_type",
                                        params.get("search_type"))))
        return node.msearch(requests, tenant=params.get("tenant_id"))

    @d.route("GET", "/_count")
    @d.route("POST", "/_count")
    def count_all(node, params, body):
        return node.count(None, _body_query(params, body))

    @d.route("GET", "/{index}/_count")
    @d.route("POST", "/{index}/_count")
    def count(node, params, body, index):
        return node.count(index, _body_query(params, body))

    # -- bulk -------------------------------------------------------------
    @d.route("POST", "/_bulk")
    @d.route("PUT", "/_bulk")
    @d.route("POST", "/{index}/_bulk")
    def bulk(node, params, body, index=None, type=None):
        lines = body if isinstance(body, list) else []
        ops = []
        i = 0
        while i < len(lines):
            action_line = lines[i]
            action, meta = next(iter(action_line.items()))
            meta = meta or {}
            did = meta.get("_id")
            payload = {"_index": meta.get("_index", index),
                       "_id": str(did) if did is not None else None,
                       "_type": meta.get("_type", type),
                       "_routing": meta.get("_routing",
                                            meta.get("routing"))}
            if action in ("index", "create", "update"):
                i += 1
                payload["doc"] = lines[i] if i < len(lines) else {}
            ops.append((action, payload))
            i += 1
        refresh = params.get("refresh") in ("true", "", "wait_for")
        return node.bulk(ops, refresh=refresh)

    @d.route("POST", "/{index}/{type}/_bulk")
    @d.route("PUT", "/{index}/{type}/_bulk")
    def bulk_typed(node, params, body, index, type):
        return bulk(node, params, body, index, type)

    # -- maintenance ------------------------------------------------------
    @d.route("POST", "/_refresh")
    @d.route("POST", "/{index}/_refresh")
    @d.route("GET", "/{index}/_refresh")
    def refresh(node, params, body, index=None):
        return node.refresh(index)

    @d.route("POST", "/_flush")
    @d.route("POST", "/{index}/_flush")
    def flush(node, params, body, index=None):
        return node.flush(index)

    @d.route("POST", "/{index}/_forcemerge")
    @d.route("POST", "/{index}/_optimize")  # legacy 2.x name
    def forcemerge(node, params, body, index):
        return node.force_merge(index,
                                int(params.get("max_num_segments", 1)))

    # -- mappings / settings ----------------------------------------------
    @d.route("GET", "/_mapping")
    def get_mapping_all(node, params, body):
        return node.get_mapping(
            None, expand_wildcards=params.get("expand_wildcards", "open"))

    @d.route("GET", "/{index}/_mapping")
    def get_mapping(node, params, body, index):
        return node.get_mapping(
            index, expand_wildcards=params.get("expand_wildcards", "open"))

    @d.route("PUT", "/{index}/_mapping")
    @d.route("POST", "/{index}/_mapping")
    def put_mapping(node, params, body, index):
        return node.put_mapping(index, body or {})

    @d.route("GET", "/_settings")
    @d.route("GET", "/{index}/_settings")
    @d.route("GET", "/_settings/{name}")
    @d.route("GET", "/{index}/_settings/{name}")
    def get_settings(node, params, body, index=None, name=None):
        return node.get_settings(
            index, flat=params.get("flat_settings") in ("true", ""),
            name=name,
            expand_wildcards=params.get("expand_wildcards", "open"))

    # -- documents --------------------------------------------------------
    @d.route("POST", "/{index}/_doc")
    def index_auto_id(node, params, body, index):
        return node.index_doc(index, None, body or {},
                              refresh=params.get("refresh") == "true")

    @d.route("PUT", "/{index}/_create/{id}")
    @d.route("POST", "/{index}/_create/{id}")
    def create_doc(node, params, body, index, id):
        params = {**params, "op_type": "create"}
        return index_doc(node, params, body, index, id)

    @d.route("PUT", "/{index}/_doc/{id}")
    @d.route("POST", "/{index}/_doc/{id}")
    def index_doc(node, params, body, index, id, doc_type=None):
        version = params.get("version")
        vt = params.get("version_type", "internal")
        if params.get("op_type") == "create":
            # op_type=create fails on ANY existing doc, independent of
            # version type (ref: TransportIndexAction autogenerate/
            # create → DocumentAlreadyExistsException)
            from ..utils.errors import VersionConflictError
            exists = True
            try:
                node.get_doc(index, id,
                             routing=params.get("routing")
                             or params.get("parent"))
            except ElasticsearchTpuError:
                exists = False
            if exists:
                raise VersionConflictError(index, id, -1, -1)
        return node.index_doc(index, id, body or {},
                              version=int(version) if version else None,
                              routing=params.get("routing"),
                              refresh=_truthy(params, "refresh"),
                              ttl=params.get("ttl"),
                              doc_type=doc_type,
                              version_type=vt,
                              parent=params.get("parent"),
                              timestamp=params.get("timestamp"))

    @d.route("GET", "/{index}/_doc/{id}")
    def get_doc(node, params, body, index, id, doc_type=None):
        realtime = params.get("realtime") not in ("false", "0")
        if _truthy(params, "refresh"):
            node.refresh(index)   # refresh-before-read (ref: GetRequest.refresh)
        r = node.get_doc(index, id, routing=params.get("routing"),
                         doc_type=doc_type, realtime=realtime,
                         parent=params.get("parent"))
        want_version = params.get("version")
        # internal/external/external_gte all require equality on reads;
        # force skips the check (ref: common/lucene/uid/Versions +
        # VersionType read-conflict rules)
        if want_version and params.get("version_type") != "force" \
                and int(want_version) != r.get("_version"):
            # ref: get API version check → VersionConflictEngineException
            from ..utils.errors import VersionConflictError
            raise VersionConflictError(index, id, r.get("_version", -1),
                                       int(want_version))
        src = r.get("_source")
        obj = (json.loads(src) if isinstance(src, (bytes, str))
               else (src or {}))
        field_list = ([f.strip() for f in str(params["fields"]).split(",")]
                      if params.get("fields") else None)
        if field_list is not None:
            flds = {}
            for f in field_list:
                if f in ("_routing", "_parent"):
                    if f in r:
                        flds[f] = r[f]
                elif f == "_timestamp":
                    ts = node._index(index).doc_ts.get(id)
                    if ts is not None:
                        flds[f] = ts
                elif f == "_ttl":
                    # remaining ttl ms from the stored expiry column
                    # (ref: TTLFieldMapper value = expiry - now)
                    try:
                        svc = node._index(index)
                        raw = svc.shard_for(
                            id, r.get("_routing")).get(id)
                        rob = raw.get("_source")
                        rob = (json.loads(rob)
                               if isinstance(rob, (bytes, str)) else rob)
                        exp = (rob or {}).get("_ttl_expiry")
                        if exp:
                            import time as _t
                            flds[f] = int(exp - _t.time() * 1000)
                    except ElasticsearchTpuError:
                        pass
                elif f in obj:
                    v = obj[f]
                    flds[f] = v if isinstance(v, list) else [v]
            if flds:
                r["fields"] = flds
            # an explicit fields list suppresses _source unless requested
            if "_source" not in field_list and "_source" not in params:
                r.pop("_source", None)
                return r
        # GET-level source filtering (ref: RestGetAction fetchSource)
        from ..search.shard_searcher import filter_source
        inc = params.get("_source_include") or params.get("_source_includes")
        exc = params.get("_source_exclude") or params.get("_source_excludes")
        sparam = params.get("_source")
        if inc or exc:
            obj = filter_source(obj, {
                "includes": inc.split(",") if inc else [],
                "excludes": exc.split(",") if exc else []})
        elif sparam == "false":
            r.pop("_source", None)
            return r
        elif sparam and sparam != "true":
            obj = filter_source(obj, sparam.split(","))
        r["_source"] = obj
        return r

    @d.route("DELETE", "/{index}/_doc/{id}")
    def delete_doc(node, params, body, index, id, doc_type=None):
        version = params.get("version")
        r = node.delete_doc(index, id,
                            version=int(version) if version else None,
                            routing=params.get("routing"),
                            refresh=_truthy(params, "refresh"),
                            doc_type=doc_type,
                            version_type=params.get("version_type",
                                                    "internal"),
                            parent=params.get("parent"))
        if not r.get("found"):
            # delete of a missing doc is a 404 with found:false
            # (ref: RestDeleteAction status mapping)
            return RestStatus(404, {**r, "found": False})
        return r

    @d.route("POST", "/{index}/_update/{id}")
    def update_doc(node, params, body, index, id, doc_type=None):
        vt = params.get("version_type", "internal")
        if vt not in ("internal", "force"):
            # ref: UpdateRequest.validate — external versioning is not
            # supported by the update API
            raise IllegalArgumentError(
                "Validation Failed: 1: version type [" + vt +
                "] is not supported by the update API;")
        version = params.get("version")
        fields = params.get("fields")
        body = dict(body or {})
        # 1.x accepted script/lang as URL params (ref: RestUpdateAction
        # request.param("script")); a body script wins over the URL one
        if params.get("script") is not None and body.get("script") is None:
            body["script"] = params["script"]
        if params.get("lang") is not None and body.get("lang") is None:
            body["lang"] = params["lang"]
        return node.update_doc(index, id, body or {},
                               refresh=_truthy(params, "refresh"),
                               doc_type=doc_type,
                               routing=params.get("routing"),
                               parent=params.get("parent"),
                               version=int(version) if version else None,
                               fields=(fields.split(",") if fields
                                       else None),
                               ttl=params.get("ttl"),
                               timestamp=params.get("timestamp"))

    # -- stored scripts (ref: RestPutIndexedScriptAction; ES 2.0 kept
    # these in the .scripts index) -------------------------------------
    @d.route("PUT", "/_scripts/{id}")
    @d.route("POST", "/_scripts/{id}")
    def put_script(node, params, body, id):
        # accepts expression scripts AND mustache search templates, with
        # string or object sources (ref: RestPutStoredScriptAction)
        body = body or {}
        spec = body.get("script", body)
        if isinstance(spec, dict):
            src = spec.get("source", spec.get("inline"))
        else:
            src = spec
        if src is None:
            raise IllegalArgumentError("stored script requires [source]")
        if isinstance(src, dict):
            src = json.dumps(src)
        node.put_stored_script(id, str(src))
        return {"acknowledged": True, "_id": id}

    @d.route("GET", "/_scripts/{id}")
    def get_script(node, params, body, id):
        from ..script import ScriptService
        # get_stored raises ScriptMissingError (404) when absent
        src = ScriptService.instance().get_stored(id)
        return {"_id": id, "found": True,
                "script": {"lang": "expression", "source": src}}

    @d.route("DELETE", "/_scripts/{id}")
    def delete_script(node, params, body, id):
        found = node.delete_stored_script(id)
        return {"acknowledged": found, "found": found}

    # -- lang-scoped indexed scripts (the 1.x .scripts-index API shape;
    # ref: RestPutIndexedScriptAction + ScriptService indexed scripts,
    # full index/get/delete version semantics) -------------------------
    def _script_version_params(params):
        v = params.get("version")
        return (int(v) if v is not None else None,
                params.get("version_type", "internal"))

    @d.route("PUT", "/_scripts/{lang}/{id}")
    @d.route("POST", "/_scripts/{lang}/{id}")
    def put_script_lang(node, params, body, lang, id):
        body = body or {}
        spec = body.get("script", body)
        if isinstance(spec, dict):
            src = spec.get("source") or spec.get("inline")
        else:
            src = spec
        if src is None:
            raise IllegalArgumentError("stored script requires [script]")
        if isinstance(src, dict):
            src = json.dumps(src)
        version, vtype = _script_version_params(params)
        v, created = node.put_stored_script_versioned(id, str(src),
                                                      lang=lang,
                                                      version=version,
                                                      version_type=vtype)
        return {"acknowledged": True, "_index": ".scripts", "_type": lang,
                "_id": id, "_version": v, "created": created}

    @d.route("GET", "/_scripts/{lang}/{id}")
    def get_script_lang(node, params, body, lang, id):
        from ..script import ScriptService
        svc = ScriptService.instance()
        meta = svc.get_meta(id)
        # indexed scripts are keyed (lang, id): .scripts stores lang as
        # the doc _type, so a different lang is a different document
        if meta is None or meta["lang"] != lang:
            return RestStatus(404, {"found": False, "lang": lang,
                                    "_index": ".scripts", "_id": id})
        version, vtype = _script_version_params(params)
        svc.check_read_version(id, version, vtype)
        return {"found": True, "lang": meta["lang"], "_index": ".scripts",
                "_id": id, "_version": meta["version"],
                "script": meta["source"]}

    @d.route("DELETE", "/_scripts/{lang}/{id}")
    def delete_script_lang(node, params, body, lang, id):
        from ..script import ScriptService
        meta = ScriptService.instance().get_meta(id)
        version, vtype = _script_version_params(params)
        if meta is not None and meta["lang"] != lang:
            meta = None  # other-lang doc: this (lang, id) is absent
        v = (node.delete_stored_script_versioned(id, version=version,
                                                 version_type=vtype)
             if meta is not None else None)
        if v is None:
            # ES deletes of missing docs answer version 1
            return RestStatus(404, {"found": False, "_index": ".scripts",
                                    "_type": lang, "_id": id,
                                    "_version": 1})
        return {"found": True, "_index": ".scripts", "_type": lang,
                "_id": id, "_version": v}

    @d.route("POST", "/_mget")
    @d.route("GET", "/_mget")
    @d.route("POST", "/{index}/_mget")
    def mget(node, params, body, index=None, type=None):
        body = body or {}
        specs = body.get("docs")
        if specs is None and "ids" in body:
            specs = [{"_id": i} for i in body["ids"]]
        if not specs:
            raise IllegalArgumentError(
                "ActionRequestValidationException: Validation Failed: "
                "1: no documents to get;")
        realtime = params.get("realtime") not in ("false", "0")
        if _truthy(params, "refresh"):
            node.refresh(index)
        url_source = params.get("_source")
        url_inc = (params.get("_source_include")
                   or params.get("_source_includes"))
        url_exc = (params.get("_source_exclude")
                   or params.get("_source_excludes"))
        url_fields = (params["fields"].split(",")
                      if params.get("fields") else None)
        docs = []
        for spec in specs:
            idx = spec.get("_index", index)
            typ = spec.get("_type", type)
            did = spec.get("_id")
            if idx is None or did is None:
                raise IllegalArgumentError(
                    "ActionRequestValidationException: Validation "
                    "Failed: 1: index is missing;"
                    if idx is None else
                    "ActionRequestValidationException: Validation "
                    "Failed: 1: id is missing;")
            did = str(did)
            routing = spec.get("routing", spec.get("_routing"))
            parent = spec.get("parent", spec.get("_parent"))
            try:
                r = node.get_doc(
                    idx, did, doc_type=typ,
                    routing=str(routing) if routing is not None else None,
                    parent=str(parent) if parent is not None else None,
                    realtime=realtime)
                if not r.get("found", True):
                    docs.append({"_index": idx, "_type": typ or "_doc",
                                 "_id": did, "found": False})
                    continue
                src = r["_source"]
                obj = (json.loads(src)
                       if isinstance(src, (bytes, str)) else src)
                r["_index"] = idx
                if typ is not None:
                    r["_type"] = typ
                want_fields = spec.get("fields", spec.get("_fields",
                                                          url_fields))
                src_spec = spec.get("_source")
                if src_spec is None and (url_inc or url_exc):
                    src_spec = {
                        "includes": url_inc.split(",") if url_inc else [],
                        "excludes": url_exc.split(",") if url_exc else []}
                if src_spec is None and url_source is not None:
                    src_spec = (True if url_source == "true" else
                                False if url_source == "false" else
                                url_source.split(","))
                if want_fields:
                    if isinstance(want_fields, str):
                        want_fields = [want_fields]
                    flds = {}
                    for f in want_fields:
                        if f in ("_routing", "_parent"):
                            if f in r:
                                flds[f] = r[f]
                        elif f in obj:
                            v = obj[f]
                            flds[f] = v if isinstance(v, list) else [v]
                    if flds:
                        r["fields"] = flds
                    if "_source" in want_fields:
                        r["_source"] = obj
                    else:
                        r.pop("_source", None)
                elif src_spec is not None:
                    from ..search.shard_searcher import filter_source
                    filtered = filter_source(obj, src_spec)
                    if filtered is None:
                        r.pop("_source", None)
                    else:
                        r["_source"] = filtered
                else:
                    r["_source"] = obj
                docs.append(r)
            except ElasticsearchTpuError:
                docs.append({"_index": idx, "_type": typ or "_doc",
                             "_id": did, "found": False})
        return {"docs": docs}

    @d.route("POST", "/{index}/{type}/_mget")
    @d.route("GET", "/{index}/{type}/_mget")
    def mget_typed(node, params, body, index, type):
        return mget(node, params, body, index, type)

    @d.route("POST", "/{index}/_analyze")
    @d.route("GET", "/{index}/_analyze")
    @d.route("POST", "/_analyze")
    @d.route("GET", "/_analyze")
    def analyze(node, params, body, index=None):
        body = body or {}
        text = body.get("text") or params.get("text") or ""
        field = body.get("field") or params.get("field")
        tokenizer_name = body.get("tokenizer") or params.get("tokenizer")
        filter_names = body.get("filters") or params.get("filters") \
            or body.get("filter") or params.get("filter")
        svc = node.indices.get(index) if index is not None else None
        if field is not None and svc is not None:
            # analyze with the FIELD's own analyzer (ref:
            # TransportAnalyzeAction field resolution)
            analyzer = svc.mappers.search_analyzer_for(field)
            fm = svc.mappers.field(field)
            if fm is not None and fm.type == "text":
                analyzer = svc.mappers.analysis.analyzer(fm.analyzer)
        elif tokenizer_name is not None:
            # ad-hoc tokenizer + filter chain (ref:
            # TransportAnalyzeAction custom analyzer assembly)
            from ..index.analysis import (Analyzer, TOKENIZER_FACTORIES,
                                          TOKEN_FILTERS)
            from ..utils.settings import Settings as _S
            tk = TOKENIZER_FACTORIES.get(tokenizer_name)
            if tk is None:
                raise IllegalArgumentError(
                    f"failed to find tokenizer [{tokenizer_name}]")
            if isinstance(filter_names, str):
                filter_names = filter_names.split(",")
            filters = []
            for fn in filter_names or []:
                f = TOKEN_FILTERS.get(fn)
                if f is None:
                    raise IllegalArgumentError(
                        f"failed to find token filter [{fn}]")
                filters.append(f)
            analyzer = Analyzer("_custom_", tk(_S.EMPTY), filters)
        else:
            name = (body.get("analyzer") or params.get("analyzer")
                    or "standard")
            if svc is not None:
                analyzer = svc.mappers.analysis.analyzer(name)
            else:
                from ..index.analysis import AnalysisService
                analyzer = AnalysisService().analyzer(name)
        texts = text if isinstance(text, list) else [text]
        tokens = []
        pos = 0
        for t in texts:
            for tok in analyzer.analyze(str(t)):
                tokens.append({"token": tok, "position": pos})
                pos += 1
        return {"tokens": tokens}

    # -- scroll (ref: RestSearchScrollAction/RestClearScrollAction) -------
    @d.route("POST", "/_search/scroll")
    @d.route("GET", "/_search/scroll")
    def scroll(node, params, body, **kw):
        body = body or {}
        sid = body.get("scroll_id") or params.get("scroll_id")
        keepalive = body.get("scroll") or params.get("scroll")
        return node.scroll(sid, keepalive,
                           tenant=params.get("tenant_id"))

    @d.route("DELETE", "/_search/scroll")
    def clear_scroll(node, params, body, **kw):
        ids = (body or {}).get("scroll_id")
        if isinstance(ids, str):
            ids = [ids]
        r = node.clear_scroll(ids)
        if r.pop("_missing", False):
            return RestStatus(404, r)
        return r

    # -- validate / explain / segments ------------------------------------
    @d.route("GET", "/_validate/query")
    @d.route("POST", "/_validate/query")
    @d.route("GET", "/{index}/_validate/query")
    @d.route("POST", "/{index}/_validate/query")
    def validate_query(node, params, body, index=None):
        return node.validate_query(index, _body_query(params, body),
                                   explain=params.get("explain") == "true")

    @d.route("GET", "/_search_shards")
    @d.route("POST", "/_search_shards")
    @d.route("GET", "/{index}/_search_shards")
    @d.route("POST", "/{index}/_search_shards")
    def search_shards(node, params, body, index=None):
        # ref: action/admin/cluster/shards/ClusterSearchShardsAction —
        # which shard copies a search against `index` would touch
        nid = node.name
        shards = []
        for svc in node._resolve(index):
            for sid in sorted(svc.shards):
                shards.append([{"index": svc.name, "node": nid,
                                "shard": sid, "primary": True,
                                "state": "STARTED",
                                "relocating_node": None}])
        return {"nodes": {nid: {"name": nid,
                                "transport_address": "local"}},
                "shards": shards}

    @d.route("GET", "/{index}/_explain/{id}")
    @d.route("POST", "/{index}/_explain/{id}")
    def explain(node, params, body, index, id):
        return node.explain_doc(index, id, _body_query(params, body))

    @d.route("GET", "/_segments")
    @d.route("GET", "/{index}/_segments")
    def segments(node, params, body, index=None):
        return node.segments(
            index,
            ignore_unavailable=_truthy(params, "ignore_unavailable"),
            allow_no_indices=params.get("allow_no_indices") != "false")

    # -- aliases ----------------------------------------------------------
    @d.route("POST", "/_aliases")
    def update_aliases(node, params, body, **kw):
        return node.update_aliases((body or {}).get("actions") or [])

    @d.route("PUT", "/{index}/_alias/{alias}")
    @d.route("POST", "/{index}/_alias/{alias}")
    @d.route("PUT", "/{index}/_aliases/{alias}")
    @d.route("POST", "/{index}/_aliases/{alias}")
    def put_alias(node, params, body, index, alias):
        return node.put_alias(index, alias, body)

    @d.route("PUT", "/_alias/{alias}")
    @d.route("POST", "/_alias/{alias}")
    def put_alias_noindex(node, params, body, alias):
        # ref: IndicesAliasesRequest.validate — add requires an index
        raise IllegalArgumentError("alias action requires an [index]")

    @d.route("DELETE", "/{index}/_alias/{alias}")
    @d.route("DELETE", "/{index}/_aliases/{alias}")
    def delete_alias(node, params, body, index, alias):
        return node.delete_alias(index, alias)

    @d.route("GET", "/_alias")
    @d.route("GET", "/{index}/_alias")
    def get_alias_all(node, params, body, index=None):
        return node.get_aliases(index, include_empty=True)

    @d.route("GET", "/_aliases")
    @d.route("GET", "/{index}/_aliases")
    @d.route("GET", "/_aliases/{name}")
    @d.route("GET", "/{index}/_aliases/{name}")
    def get_aliases(node, params, body, index=None, name=None):
        # /_aliases always lists every resolved index (empty map when
        # no alias matches) — ref: RestGetIndicesAliasesAction
        return node.get_aliases(index, name=name, include_empty=True)

    @d.route("GET", "/_alias/{name}")
    @d.route("GET", "/{index}/_alias/{name}")
    def get_alias_by_name(node, params, body, name, index=None):
        r = node.get_aliases(index, name=name)
        if not any(v.get("aliases") for v in r.values()):
            # exists_alias (HEAD) needs the 404, as does a cluster-wide
            # GET for an absent alias; an index-scoped GET returns the
            # empty body with 200 (ref: RestAliasesExistAction vs
            # RestGetAliasesAction missing-alias handling)
            if params.get("__method") == "HEAD" or index is None:
                return RestStatus(404, r)
        return r

    # -- templates --------------------------------------------------------
    @d.route("PUT", "/_template/{name}")
    @d.route("POST", "/_template/{name}")
    def put_template(node, params, body, name):
        return node.put_template(name, body or {},
                                 create=_truthy(params, "create"))

    @d.route("GET", "/_template")
    @d.route("GET", "/_template/{name}")
    def get_template(node, params, body, name=None):
        return node.get_templates(
            name, flat=_truthy(params, "flat_settings"))

    @d.route("DELETE", "/_template/{name}")
    def delete_template(node, params, body, name):
        return node.delete_template(name)

    # -- open/close -------------------------------------------------------
    @d.route("POST", "/{index}/_close")
    def close_index(node, params, body, index):
        return node.close_index(index)

    @d.route("POST", "/{index}/_open")
    def open_index(node, params, body, index):
        return node.open_index(index)

    # -- snapshots (ref: rest/action/admin/cluster/snapshots/) ------------
    @d.route("PUT", "/_snapshot/{repo}")
    @d.route("POST", "/_snapshot/{repo}")
    def put_repository(node, params, body, repo):
        body = body or {}
        return node.snapshots.put_repository(
            repo, body.get("type", "fs"), body.get("settings") or {})

    @d.route("PUT", "/_snapshot/{repo}/{snap}")
    def create_snapshot(node, params, body, repo, snap):
        return node.snapshots.create_snapshot(
            repo, snap, (body or {}).get("indices"))

    @d.route("GET", "/_snapshot")
    @d.route("GET", "/_snapshot/{repo}")
    def get_repository(node, params, body, repo=None):
        return node.snapshots.get_repositories(repo)

    @d.route("POST", "/_snapshot/{repo}/_verify")
    def verify_repository(node, params, body, repo):
        return node.snapshots.verify_repository(repo)

    @d.route("GET", "/_snapshot/{repo}/{snap}")
    def get_snapshots(node, params, body, repo, snap):
        return node.snapshots.get_snapshots(repo, snap)

    @d.route("DELETE", "/_snapshot/{repo}/{snap}")
    def delete_snapshot(node, params, body, repo, snap):
        return node.snapshots.delete_snapshot(repo, snap)

    @d.route("POST", "/_snapshot/{repo}/{snap}/_restore")
    def restore_snapshot(node, params, body, repo, snap):
        body = body or {}
        return node.snapshots.restore_snapshot(
            repo, snap, body.get("indices"),
            body.get("rename_pattern"), body.get("rename_replacement"))

    # -- cluster state / settings / cat -----------------------------------
    @d.route("GET", "/_cluster/state")
    def cluster_state(node, params, body):
        return node.cluster_state()

    @d.route("GET", "/_cluster/state/{metrics}")
    @d.route("GET", "/_cluster/state/{metrics}/{index}")
    def cluster_state_filtered(node, params, body, metrics, index=None):
        return node.cluster_state(
            metrics, index,
            expand_wildcards=params.get("expand_wildcards", "open"),
            ignore_unavailable=_truthy(params, "ignore_unavailable"),
            allow_no_indices=params.get("allow_no_indices") != "false")

    @d.route("GET", "/_cluster/settings")
    def get_cluster_settings(node, params, body):
        return node.get_cluster_settings()

    @d.route("PUT", "/_cluster/settings")
    def put_cluster_settings(node, params, body):
        return node.put_cluster_settings(body or {})

    @d.route("GET", "/_cat/shards")
    @d.route("GET", "/_cat/shards/{index}")
    def cat_shards(node, params, body, index=None):
        return node.cat_shards(index)

    @d.route("GET", "/_cat/count")
    @d.route("GET", "/_cat/count/{index}")
    def cat_count(node, params, body, index=None):
        return node.cat_count(index)

    @d.route("GET", "/_cat/nodes")
    def cat_nodes(node, params, body):
        from ..utils import monitor
        rt = monitor.runtime_stats()
        heap_used = rt.get("mem", {}).get("resident_in_bytes", 1 << 20)
        heap_max = max(heap_used * 2, 1)
        try:
            load = __import__("os").getloadavg()[0]
        except OSError:
            load = 0.0
        return [{"host": "127.0.0.1", "ip": "127.0.0.1",
                 "heap.current": heap_used,
                 "heap.percent": int(heap_used * 100 / heap_max),
                 "heap.max": heap_max,
                 "ram.percent": 42,
                 "file_desc.current": 1, "file_desc.percent": 1,
                 "file_desc.max": 1024,
                 "load": round(load, 2),
                 "node.role": "d", "master": "*",
                 "name": node.name}]

    @d.route("GET", "/_cat/master")
    def cat_master(node, params, body):
        return [{"node": node.name}]

    @d.route("GET", "/_cat/aliases")
    @d.route("GET", "/_cat/aliases/{name}")
    def cat_aliases(node, params, body, name=None):
        import fnmatch
        out = []
        for a, targets in sorted(node._aliases.items()):
            if name is not None and not any(
                    fnmatch.fnmatch(a, p) for p in name.split(",")):
                continue
            for i in sorted(targets):
                meta = node.alias_meta(a, i)
                out.append({"alias": a, "index": i,
                            "filter": "*" if meta.get("filter") else "-",
                            "routing.index":
                                meta.get("index_routing", "-"),
                            "routing.search":
                                meta.get("search_routing", "-")})
        return out

    @d.route("GET", "/_cat/templates")
    def cat_templates(node, params, body):
        return [{"name": n, "index_patterns": t["patterns"],
                 "order": t["order"]}
                for n, t in sorted(node._templates.items())]

    @d.route("GET", "/_cat/segments")
    @d.route("GET", "/_cat/segments/{index}")
    def cat_segments(node, params, body, index=None):
        # one row per segment (ref: RestSegmentsAction row shape;
        # version is Lucene-style numeric — the jax build reports the
        # columnar format version)
        out = []
        for name, svc in sorted(node.indices.items()):
            if index is not None and name not in {
                    x.name for x in node._resolve(index)}:
                continue
            for sid, eng in svc.shards.items():
                for i, seg in enumerate(eng.segments):
                    live = eng.live.get(seg.seg_id)
                    n_live = (int(live.sum()) if live is not None
                              else seg.num_docs)
                    out.append({
                        "index": name, "shard": sid, "prirep": "p",
                        "ip": "127.0.0.1",
                        "id": _cat_node_id(node.name),
                        "segment": f"_{i}", "generation": i,
                        "docs.count": n_live,
                        "docs.deleted": seg.num_docs - n_live,
                        "size": seg.nbytes(),
                        "size.memory": seg.nbytes(),
                        "committed": False, "searchable": True,
                        "version": "5.1.0", "compound": False})
        return out

    # -- index admin (register LAST: bare /{index} patterns) --------------
    @d.route("PUT", "/{index}")
    def create_index(node, params, body, index):
        body = body or {}
        return node.create_index(index, body.get("settings"),
                                 body.get("mappings"),
                                 aliases=body.get("aliases"),
                                 warmers=body.get("warmers"))

    @d.route("DELETE", "/{index}")
    def delete_index(node, params, body, index):
        return node.delete_index(index)

    @d.route("GET", "/{index}")
    @d.route("GET", "/{index}/{feature}")
    def get_index(node, params, body, index, feature=None):
        # ref: RestGetIndicesAction — optional feature list
        # (_settings,_mappings,_warmers,_aliases) trims the response
        if feature is not None and not feature.startswith("_"):
            if params.get("__method") == "HEAD":
                # HEAD /{index}/{type} = exists_type (ref:
                # RestTypesExistsAction)
                import fnmatch
                tpats = [p.strip() for p in feature.split(",")]
                for svc in node._resolve(index, metadata_op=True):
                    if any(fnmatch.fnmatch(t, p)
                           for t in svc.mapping_types for p in tpats):
                        return {}
                return RestStatus(404, {})
            raise IllegalArgumentError(
                f"no handler found for uri [/{index}/{feature}]")
        feats = {f.strip().removesuffix("s") for f in
                 (feature or "_settings,_mappings,_warmers,_aliases"
                  ).split(",")}
        svcs = node._resolve(
            index,
            expand_wildcards=params.get("expand_wildcards", "open"),
            ignore_unavailable=_truthy(params, "ignore_unavailable"),
            metadata_op=True)
        out = {}
        for svc in svcs:
            name = svc.name
            entry: dict = {}
            if "_mapping" in feats:
                entry.update(node.get_mapping(name)[name])
            if "_setting" in feats:
                entry.update(node.get_settings(name)[name])
            if "_aliase" in feats or "_alias" in feats \
                    or "_alia" in feats:
                entry.update(node.get_aliases(
                    name, include_empty=True)[name])
            if "_warmer" in feats:
                entry["warmers"] = {
                    wn: {"types": [], "source": wsrc}
                    for wn, wsrc in
                    getattr(svc, "warmers", {}).items()}
            out[name] = entry
        if not out and index is not None \
                and not _truthy(params, "ignore_unavailable") \
                and ("*" not in index
                     or params.get("allow_no_indices") == "false"):
            raise IndexNotFoundError(index)
        return out

    # query-driven writes / ttl / warmers / cache / recovery
    @d.route("POST", "/_delete_by_query")
    @d.route("POST", "/{index}/_delete_by_query")
    @d.route("DELETE", "/{index}/_query")     # legacy 2.0 shape
    def delete_by_query(node, params, body, index=None):
        return node.delete_by_query(index, _body_query(params, body))

    @d.route("POST", "/_update_by_query")
    @d.route("POST", "/{index}/_update_by_query")
    def update_by_query(node, params, body, index=None):
        return node.update_by_query(index, body)

    @d.route("PUT", "/_warmer/{name}")
    @d.route("POST", "/_warmer/{name}")
    @d.route("PUT", "/_warmers/{name}")
    @d.route("POST", "/_warmers/{name}")
    def put_warmer_all(node, params, body, name):
        return node.put_warmer(None, name, body)

    @d.route("PUT", "/{index}/_warmer/{name}")
    @d.route("POST", "/{index}/_warmer/{name}")
    @d.route("PUT", "/{index}/_warmers/{name}")
    @d.route("POST", "/{index}/_warmers/{name}")
    def put_warmer(node, params, body, index, name):
        return node.put_warmer(index, name, body)

    @d.route("GET", "/_warmer")
    @d.route("GET", "/_warmer/{name}")
    @d.route("GET", "/_warmers")
    @d.route("GET", "/_warmers/{name}")
    def get_warmer_all(node, params, body, name=None):
        return node.get_warmers(None, name)

    @d.route("GET", "/{index}/_warmer")
    @d.route("GET", "/{index}/_warmer/{name}")
    @d.route("GET", "/{index}/_warmers")
    @d.route("GET", "/{index}/_warmers/{name}")
    def get_warmer(node, params, body, index, name=None):
        return node.get_warmers(index, name)

    @d.route("DELETE", "/{index}/_warmer/{name}")
    @d.route("DELETE", "/{index}/_warmers/{name}")
    @d.route("DELETE", "/{index}/_warmer")
    @d.route("DELETE", "/{index}/_warmers")
    def delete_warmer(node, params, body, index, name=None):
        return node.delete_warmer(index, params.get("name", name))

    @d.route("POST", "/_cache/clear")
    @d.route("POST", "/{index}/_cache/clear")
    def clear_cache(node, params, body, index=None):
        return node.clear_cache(index)

    @d.route("GET", "/_recovery")
    @d.route("GET", "/{index}/_recovery")
    def recovery(node, params, body, index=None):
        return node.recovery_status(index)

    # percolator (ref: rest/action/percolate/RestPercolateAction; queries
    # live under the .percolator type as in ES 2.0)
    @d.route("GET", "/{index}/_percolate")
    @d.route("POST", "/{index}/_percolate")
    def percolate(node, params, body, index):
        return node.percolate(index, _body_query(params, body))

    @d.route("GET", "/{index}/{type}/_percolate")
    @d.route("POST", "/{index}/{type}/_percolate")
    def percolate_typed(node, params, body, index, type):
        return node.percolate(index, _body_query(params, body))

    @d.route("GET", "/{index}/_percolate/count")
    @d.route("POST", "/{index}/_percolate/count")
    @d.route("GET", "/{index}/{type}/_percolate/count")
    @d.route("POST", "/{index}/{type}/_percolate/count")
    def percolate_count(node, params, body, index, type=None):
        return node.percolate(index, _body_query(params, body),
                              count_only=True)

    @d.route("GET", "/{index}/{type}/{id}/_percolate")
    @d.route("POST", "/{index}/{type}/{id}/_percolate")
    def percolate_existing(node, params, body, index, type, id):
        # percolate an EXISTING doc: fetch it, then run the registered
        # queries against its source (ref: RestPercolateAction existing-
        # doc variant; percolate_index may redirect the query set)
        doc = node.get_doc(index, id, routing=params.get("routing"))
        want_version = params.get("version")
        if want_version is not None \
                and int(want_version) != doc.get("_version"):
            # ref: TransportPercolateAction existing-doc version check
            from ..utils.errors import VersionConflictError
            raise VersionConflictError(index, id,
                                       doc.get("_version", -1),
                                       int(want_version))
        src = doc["_source"]
        if isinstance(src, (bytes, str)):
            src = json.loads(src)
        target = params.get("percolate_index", index)
        req = dict(body or {})
        req["doc"] = src
        return node.percolate(target, req)

    @d.route("GET", "/{index}/{type}/{id}/_percolate/count")
    @d.route("POST", "/{index}/{type}/{id}/_percolate/count")
    def percolate_existing_count(node, params, body, index, type, id):
        doc = node.get_doc(index, id, routing=params.get("routing"))
        src = doc["_source"]
        if isinstance(src, (bytes, str)):
            src = json.loads(src)
        target = params.get("percolate_index", index)
        req = dict(body or {})
        req["doc"] = src
        return node.percolate(target, req, count_only=True)

    @d.route("POST", "/_mpercolate")
    def mpercolate(node, params, body):
        return node.mpercolate(body if isinstance(body, list) else [])

    # legacy typed operation routes (ES 2.0 per-type paths; single-type
    # internally, the type segment is accepted and echoed)
    @d.route("GET", "/{index}/{type}/_search")
    @d.route("POST", "/{index}/{type}/_search")
    def search_typed(node, params, body, index, type):
        idx = None if index in ("_all", "*") else index
        return node.search(idx, _search_body(params, body),
                           scroll=params.get("scroll"),
                           search_type=params.get("search_type"))

    @d.route("GET", "/{index}/{type}/_count")
    @d.route("POST", "/{index}/{type}/_count")
    def count_typed(node, params, body, index, type):
        idx = None if index in ("_all", "*") else index
        return node.count(idx, _body_query(params, body))

    @d.route("POST", "/{index}/{type}/{id}/_update")
    def update_typed(node, params, body, index, type, id):
        r = update_doc(node, params, body, index, id, doc_type=type)
        r.setdefault("_type", type)
        return r

    @d.route("GET", "/{index}/{type}/{id}/_source")
    def get_source_typed(node, params, body, index, type, id):
        realtime = params.get("realtime") not in ("false", "0")
        if _truthy(params, "refresh"):
            node.refresh(index)
        r = node.get_doc(index, id, doc_type=type,
                         routing=params.get("routing"),
                         realtime=realtime,
                         parent=params.get("parent"))
        src = r["_source"]
        obj = json.loads(src) if isinstance(src, (bytes, str)) else src
        from ..search.shard_searcher import filter_source
        inc = params.get("_source_include") or params.get("_source_includes")
        exc = params.get("_source_exclude") or params.get("_source_excludes")
        if inc or exc:
            obj = filter_source(obj, {
                "includes": inc.split(",") if inc else [],
                "excludes": exc.split(",") if exc else []})
        return obj

    @d.route("GET", "/{index}/{type}/{id}/_explain")
    @d.route("POST", "/{index}/{type}/{id}/_explain")
    def explain_typed(node, params, body, index, type, id):
        return node.explain_doc(index, id, _body_query(params, body))

    @d.route("GET", "/{index}/{type}/{id}/_mlt")
    @d.route("POST", "/{index}/{type}/{id}/_mlt")
    def mlt_typed(node, params, body, index, type, id):
        # ref: rest/action/mlt/RestMoreLikeThisAction — search with a
        # more_like_this query seeded by the doc
        mlt: dict = {"like": [{"_id": id}],
                     "min_term_freq": int(params.get("min_term_freq", 1)),
                     "min_doc_freq": int(params.get("min_doc_freq", 1))}
        if params.get("mlt_fields"):
            mlt["fields"] = params["mlt_fields"].split(",")
        sbody = dict(body or {})
        sbody["query"] = {"more_like_this": mlt}
        return node.search(index, sbody)

    @d.route("GET", "/_suggest")
    @d.route("POST", "/_suggest")
    @d.route("GET", "/{index}/_suggest")
    @d.route("POST", "/{index}/_suggest")
    def suggest_endpoint(node, params, body, index=None):
        # ref: rest/action/suggest/RestSuggestAction — bare suggest
        # request = search with only a suggest section
        r = node.search(index, {"suggest": body or {}, "size": 0})
        out = {"_shards": r["_shards"]}
        out.update(r.get("suggest", {}))
        return out

    @d.route("GET", "/_search/scroll/{scroll_id}")
    @d.route("POST", "/_search/scroll/{scroll_id}")
    def scroll_path(node, params, body, scroll_id):
        return node.scroll(scroll_id, params.get("scroll")
                           or (body or {}).get("scroll"))

    @d.route("DELETE", "/_search/scroll/{scroll_id}")
    def clear_scroll_path(node, params, body, scroll_id):
        r = node.clear_scroll(scroll_id.split(","))
        if r.pop("_missing", False):
            return RestStatus(404, r)
        return r

    @d.route("GET", "/{index}/_stats")
    @d.route("GET", "/{index}/_stats/{metric}")
    def index_stats(node, params, body, index, metric=None):
        return node.indices_stats(index, metric, **_stats_params(params))

    @d.route("PUT", "/{index}/_settings")
    @d.route("PUT", "/_settings")
    def put_settings(node, params, body, index=None):
        return node.update_index_settings(
            index, body or {},
            ignore_unavailable=_truthy(params, "ignore_unavailable"))

    @d.route("GET", "/_mapping/{type}")
    @d.route("GET", "/{index}/_mapping/{type}")
    @d.route("GET", "/_mappings/{type}")
    @d.route("GET", "/{index}/_mappings/{type}")
    def get_mapping_typed(node, params, body, index=None, type=None):
        return node.get_mapping(index, type,
                                params.get("expand_wildcards", "open"))

    @d.route("PUT", "/{index}/{type}/_mapping")
    @d.route("POST", "/{index}/{type}/_mapping")
    @d.route("PUT", "/{index}/{type}/_mappings")
    @d.route("POST", "/{index}/{type}/_mappings")
    @d.route("PUT", "/{index}/_mapping/{type}")
    @d.route("POST", "/{index}/_mapping/{type}")
    @d.route("PUT", "/{index}/_mappings/{type}")
    @d.route("POST", "/{index}/_mappings/{type}")
    @d.route("PUT", "/_mapping/{type}")
    @d.route("POST", "/_mapping/{type}")
    @d.route("PUT", "/_mappings/{type}")
    @d.route("POST", "/_mappings/{type}")
    def put_mapping_typed2(node, params, body, index=None, type=None):
        return node.put_mapping(index, body or {}, doc_type=type)

    @d.route("GET", "/_mapping/field/{fields}")
    @d.route("GET", "/{index}/_mapping/field/{fields}")
    @d.route("GET", "/_mapping/{type}/field/{fields}")
    @d.route("GET", "/{index}/_mapping/{type}/field/{fields}")
    def get_field_mapping(node, params, body, fields, index=None,
                          type=None):
        return node.get_field_mapping(
            index, fields, doc_type=type,
            include_defaults=_truthy(params, "include_defaults"))

    # legacy typed doc routes /{index}/{type}/{id}
    @d.route("PUT", "/{index}/{type}/{id}")
    @d.route("POST", "/{index}/{type}/{id}")
    def index_doc_typed(node, params, body, index, type, id):
        if type == ".percolator":
            return node.register_percolator(index, id, body)
        if type.startswith("_"):
            raise IllegalArgumentError(f"no handler for type [{type}]")
        return index_doc(node, params, body, index, id, doc_type=type)

    @d.route("POST", "/{index}/{type}")
    def index_auto_id_typed(node, params, body, index, type):
        if type.startswith("_"):
            raise IllegalArgumentError(f"no handler for type [{type}]")
        return node.index_doc(index, None, body or {},
                              refresh=params.get("refresh") == "true",
                              routing=params.get("routing"),
                              doc_type=type)

    @d.route("PUT", "/{index}/{type}/{id}/_create")
    @d.route("POST", "/{index}/{type}/{id}/_create")
    def create_doc_typed(node, params, body, index, type, id):
        params = {**params, "op_type": "create"}
        return index_doc(node, params, body, index, id, doc_type=type)

    @d.route("GET", "/{index}/{type}/{id}")
    def get_doc_typed(node, params, body, index, type, id):
        if type == ".percolator":
            return node.get_percolator(index, id)
        if type.startswith("_") and type != "_all":
            raise IllegalArgumentError(f"no handler for type [{type}]")
        return get_doc(node, params, body, index, id,
                       doc_type=type)

    @d.route("DELETE", "/{index}/{type}/{id}")
    def delete_doc_typed(node, params, body, index, type, id):
        if type == ".percolator":
            return node.unregister_percolator(index, id)
        if type.startswith("_") and type != "_all":
            raise IllegalArgumentError(f"no handler for type [{type}]")
        return delete_doc(node, params, body, index, id,
                          doc_type=type)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class RestServer:
    """HTTP front end for a Node (ref: HttpServer + RestController)."""

    def __init__(self, node: Node, host: str = "127.0.0.1", port: int = 9200):
        self.node = node
        self.dispatcher = RestDispatcher(node)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _respond(self, status: int, payload, pretty: bool = False,
                         head_only: bool = False, fmt: str | None = None,
                         headers: dict | None = None):
                if isinstance(payload, (dict, list)):
                    if fmt and fmt != "json":
                        from ..utils.xcontent import render_body
                        data, ctype = render_body(payload, fmt, pretty)
                    else:
                        data = json.dumps(
                            payload,
                            indent=2 if pretty else None).encode()
                        ctype = "application/json"
                else:
                    data = str(payload).encode()
                    ctype = "text/plain"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for hk, hv in (headers or {}).items():
                    self.send_header(hk, hv)
                self.end_headers()
                if not head_only:
                    self.wfile.write(data)

            def _handle(self, method: str):
                parsed = urlparse(self.path)
                req_path = parsed.path
                params = {k: v[0] for k, v in parse_qs(parsed.query).items()
                          if v}
                # bare flags like ?pretty
                for flag in parsed.query.split("&"):
                    if flag and "=" not in flag:
                        params[flag] = "true"
                # tenant id for the traffic control plane (search/
                # traffic.py): header or ?tenant_id= param, the param
                # winning (ref: the reference resolves auth principals
                # at the REST filter layer, before any action runs)
                tenant_hdr = self.headers.get("X-Tenant-Id")
                if tenant_hdr and "tenant_id" not in params:
                    params["tenant_id"] = tenant_hdr
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                try:
                    from ..utils.xcontent import parse_body
                    body = None
                    if raw.strip():
                        # ndjson is decided by ENDPOINT, not by newline
                        # count — a one-action _bulk body is still ndjson
                        if req_path.rstrip("/").endswith(
                                ("_bulk", "_msearch", "_mpercolate")):
                            body = [json.loads(line)
                                    for line in raw.decode("utf-8")
                                    .splitlines() if line.strip()]
                        else:
                            # content negotiation: JSON/YAML/CBOR bodies
                            # (ref: common/xcontent/XContentFactory)
                            body = parse_body(
                                raw, self.headers.get("Content-Type"))
                    result = outer.dispatcher.dispatch(
                        method, req_path, params, body)
                    accept_json = "application/json" in (
                        self.headers.get("Accept") or "")
                    if req_path.startswith("/_cat") \
                            and params.get("format") not in (
                                "json", "yaml", "cbor") \
                            and not accept_json:
                        # _cat endpoints speak aligned plain text (ref:
                        # rest/action/cat/AbstractCatAction + RestTable)
                        seg = req_path.strip("/").split("/")
                        endpoint = seg[1] if len(seg) > 1 else ""
                        result = _cat_text(result, params, endpoint)
                    status = 200
                    if isinstance(result, RestStatus):
                        status, result = result.status, result.payload
                    elif method in ("POST", "PUT") \
                            and isinstance(result, dict) \
                            and result.get("created"):
                        status = 201
                    self._respond(status, result,
                                  pretty=params.get("pretty") == "true",
                                  head_only=(method == "HEAD"),
                                  fmt=params.get("format"))
                except ElasticsearchTpuError as e:
                    # errors honor the negotiated format too — a CBOR/
                    # YAML client must be able to parse the failure.
                    # Admission-control sheds (429) carry the throttle
                    # horizon as a Retry-After header so well-behaved
                    # clients back off instead of hot-looping.
                    hdrs = None
                    ra = getattr(e, "retry_after_s", None)
                    if ra is not None:
                        from ..search.traffic import retry_after_header
                        hdrs = {"Retry-After": retry_after_header(ra)}
                    try:
                        self._respond(e.status,
                                      {"error": e.to_dict(),
                                       "status": e.status},
                                      head_only=(method == "HEAD"),
                                      fmt=params.get("format"),
                                      headers=hdrs)
                    except Exception:
                        self._respond(e.status,
                                      {"error": e.to_dict(),
                                       "status": e.status},
                                      head_only=(method == "HEAD"),
                                      headers=hdrs)
                except json.JSONDecodeError as e:
                    self._respond(400, {"error": {
                        "type": "parse_exception",
                        "reason": f"request body is not valid JSON: {e}"},
                        "status": 400})
                except Exception as e:  # noqa: BLE001 - the 500 boundary
                    self._respond(500, {"error": {
                        "type": type(e).__name__, "reason": str(e)},
                        "status": 500})

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

            def do_HEAD(self):
                self._handle("HEAD")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "RestServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def main():  # pragma: no cover - CLI entry (ref: bootstrap/Elasticsearch)
    import argparse

    ap = argparse.ArgumentParser(description="elasticsearch_tpu node")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--data", default=None, help="data path (durable mode)")
    ap.add_argument("--shards", type=int, default=None)
    ap.add_argument("--config", default=None,
                    help="elasticsearch.yml / .json config file "
                         "(layered under ES_TPU_* env and CLI flags, "
                         "ref: InternalSettingsPreparer)")
    args = ap.parse_args()
    from ..utils.settings import Settings
    overrides: dict = {}
    if args.data:
        overrides["path.data"] = args.data
    if args.shards is not None:
        overrides["index.number_of_shards"] = args.shards
    node = Node(Settings.prepare(overrides, config_path=args.config))
    server = RestServer(node, args.host, args.port).start()
    print(f"node [{node.name}] listening on http://{server.host}:{server.port}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
        node.close()


if __name__ == "__main__":  # pragma: no cover
    main()
