"""HTTP JSON REST API.

Reference analog: rest/ (RestController.java PathTrie dispatch :48-162,
handlers under rest/action/*) + http/netty/NettyHttpServerTransport.java.
Route shapes follow rest-api-spec/api/*.json so existing ES clients and
the YAML conformance suites can drive this server.

Implementation: stdlib ThreadingHTTPServer — the control plane is
host-side Python; the device does the heavy lifting, so a native event
loop buys nothing until multi-host RPC lands (transport/).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs, unquote

from ..node import Node
from ..utils.errors import (ElasticsearchTpuError, IllegalArgumentError,
                            IndexNotFoundError)
from .. import __version__


class Route:
    def __init__(self, method: str, pattern: str, handler):
        self.method = method
        self.handler = handler
        parts = pattern.strip("/").split("/")
        regex = []
        self.params: list[str] = []
        for p in parts:
            if p.startswith("{"):
                name = p[1:-1]
                self.params.append(name)
                regex.append(r"(?P<%s>[^/]+)" % name)
            else:
                regex.append(re.escape(p))
        self.regex = re.compile("^/" + "/".join(regex) + "/?$")
        # literal segments outrank {param} segments position-by-position
        # (ref: RestController PathTrie wildcard fallback); lexicographic
        # comparison of this key picks the most-literal matching route
        self.spec_key = tuple(1 if p.startswith("{") else 0 for p in parts)

    def match(self, method: str, path: str):
        if method != self.method:
            return None
        m = self.regex.match(path)
        if m is None:
            return None
        # decode AFTER segment split so %2F inside an id stays one
        # segment (the reference's PathTrie decodes per part too)
        return {k: unquote(v) for k, v in m.groupdict().items()}


class RestDispatcher:
    """Method+path -> handler registry (ref: RestController PathTrie)."""

    def __init__(self, node: Node):
        self.node = node
        self.routes: list[Route] = []
        register_routes(self)

    def route(self, method: str, pattern: str):
        def deco(fn):
            self.routes.append(Route(method, pattern, fn))
            return fn
        return deco

    def dispatch(self, method: str, path: str, params: dict, body):
        effective = "GET" if method == "HEAD" else method
        if method == "HEAD":
            # a few handlers differ between GET and exists-style HEAD
            # (e.g. alias exists -> 404); expose the real verb
            params = dict(params, __method="HEAD")
        best = None
        for r in self.routes:
            kw = r.match(effective, path)
            if kw is not None and (best is None
                                   or r.spec_key < best[0].spec_key):
                best = (r, kw)
        if best is not None:
            return best[0].handler(self.node, params, body, **best[1])
        raise IllegalArgumentError(
            f"no handler found for uri [{path}] and method [{method}]")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _truthy(params: dict, key: str) -> bool:
    """REST boolean params accept true/1/'' (bare flag) — ref:
    rest/RestRequest.paramAsBoolean."""
    return params.get(key) in ("true", "1", "", "wait_for")


class RestStatus:
    """Wrap a payload with an explicit HTTP status (e.g. 404 delete)."""

    def __init__(self, status: int, payload):
        self.status = status
        self.payload = payload


def _body_query(params: dict, body) -> dict:
    """Merge URI params (q, size, from, sort) into a search body.
    Ref: RestSearchAction.parseSearchRequest."""
    body = dict(body or {})
    q = params.get("q")
    if q and "query" not in body:
        body["query"] = {"query_string": {"query": q}}
    for key in ("size", "from"):
        if key in params:
            body[key] = int(params[key])
    if "sort" in params and "sort" not in body:
        entries = []
        for part in params["sort"].split(","):
            if ":" in part:
                f, o = part.split(":", 1)
                entries.append({f: o})
            else:
                entries.append({part: "asc"})
        body["sort"] = entries
    # URI-level source filtering overrides the body's _source (ref:
    # RestSearchAction.parseSearchSource fetchSource handling)
    inc = params.get("_source_include") or params.get("_source_includes")
    exc = params.get("_source_exclude") or params.get("_source_excludes")
    if inc or exc:
        body["_source"] = {"includes": inc.split(",") if inc else [],
                           "excludes": exc.split(",") if exc else []}
    elif "_source" in params:
        v = params["_source"]
        body["_source"] = (True if v == "true" else
                           False if v == "false" else v.split(","))
    return body


def _cat_text(rows, params: dict) -> str:
    """Render a _cat result as the aligned text table the reference's
    RestTable produces. Supports v (header row), h (column select),
    help (column listing)."""
    if not isinstance(rows, list):
        return str(rows)
    # column order: first row's insertion order, then any extras
    columns: list[str] = []
    for r in rows:
        for k in r:
            if k not in columns:
                columns.append(k)
    if params.get("help") in ("true", ""):
        return "".join(f"{c} | | \n" for c in columns) or "\n"
    if params.get("h"):
        columns = [c for c in params["h"].split(",")]
    if not rows:
        return "\n" if not params.get("h") else "\n"
    cells = [[("" if r.get(c) is None else str(r.get(c)))
              for c in columns] for r in rows]
    header = [list(columns)] if params.get("v") in ("true", "") else []
    table = header + cells
    widths = [max(len(row[i]) for row in table)
              for i in range(len(columns))]
    lines = []
    for row in table:
        line = " ".join(cell.ljust(widths[i])
                        for i, cell in enumerate(row)).rstrip()
        lines.append(line)
    return "\n".join(lines) + "\n"


def register_routes(d: RestDispatcher) -> None:
    @d.route("GET", "/")
    def root(node, params, body):
        return {
            "name": node.name,
            "cluster_name": node.cluster_name,
            "version": {"number": __version__,
                        "build_flavor": "tpu-native",
                        # jax stands where lucene stood in the reference
                        "lucene_version": "5.1.0-jax"},
            "tagline": "You Know, for (TPU) Search",
        }

    # -- cluster ----------------------------------------------------------
    @d.route("GET", "/_cluster/health")
    @d.route("GET", "/_cluster/health/{index}")
    def cluster_health(node, params, body, index=None):
        return node.cluster_health(level=params.get("level"), index=index)

    @d.route("GET", "/_cluster/stats")
    def cluster_stats(node, params, body):
        return node.stats()

    @d.route("GET", "/_nodes/stats")
    @d.route("GET", "/_nodes/stats/{metric}")
    @d.route("GET", "/_nodes/{node_id}/stats")
    @d.route("GET", "/_nodes/{node_id}/stats/{metric}")
    def nodes_stats(node, params, body, metric=None, node_id=None):
        r = node.nodes_stats()
        if metric:
            keep = {m.strip() for m in metric.split(",")}
            for nid, stats in r.get("nodes", {}).items():
                base = {k: stats[k] for k in ("name", "timestamp")
                        if k in stats}
                base.update({k: v for k, v in stats.items() if k in keep})
                r["nodes"][nid] = base
        return r

    @d.route("GET", "/_nodes")
    def nodes_info(node, params, body):
        return node.nodes_info()

    # literal /_nodes/X routes MUST register before /_nodes/{metric}:
    # dispatch is first-match, so the wildcard would shadow them
    @d.route("GET", "/_nodes/hot_threads")
    @d.route("GET", "/_nodes/{node_id}/hot_threads")
    def hot_threads(node, params, body, node_id=None):
        from ..node import parse_time_value
        n = int(params.get("threads", 3))
        ms = parse_time_value(params.get("interval", "500ms"), 500)
        return node.hot_threads(n, ms)

    @d.route("GET", "/_nodes/{metric}")
    @d.route("GET", "/_nodes/{node_id}/info/{metric}")
    def nodes_info_filtered(node, params, body, metric, node_id=None):
        r = node.nodes_info()
        keep = {m.strip() for m in metric.split(",")}
        for nid, info in r.get("nodes", {}).items():
            base = {k: info[k] for k in ("name", "version", "roles")
                    if k in info}
            base.update({k: v for k, v in info.items() if k in keep})
            r["nodes"][nid] = base
        return r

    @d.route("GET", "/_cluster/pending_tasks")
    def pending_tasks(node, params, body):
        return {"tasks": getattr(node, "pending_cluster_tasks", lambda: [])()}

    @d.route("POST", "/_cluster/reroute")
    def cluster_reroute(node, params, body):
        # single-node: commands validated and acked; allocation is
        # identity (ref: action/admin/cluster/reroute/ +
        # RoutingExplanations when ?explain)
        out: dict = {"acknowledged": True,
                     "state": {"cluster_name": node.cluster_name}}
        metric = params.get("metric")
        if metric:
            state = node.cluster_state(metric)
            state.pop("cluster_name", None)
            out["state"].update(state)
        if _truthy(params, "explain"):
            explanations = []
            for cmd in (body or {}).get("commands") or []:
                name, args = next(iter(cmd.items()))
                args = dict(args or {})
                if name == "cancel":
                    args.setdefault("allow_primary", False)
                    decision = {
                        "decider": "cancel_allocation_command",
                        "decision": "NO",
                        "explanation":
                            f"can't cancel [{args.get('shard')}] on "
                            f"node [{args.get('node')}]: shard not "
                            f"found or not cancellable"}
                else:
                    decision = {"decider": f"{name}_allocation_command",
                                "decision": "NO",
                                "explanation": f"single-node cluster "
                                               f"cannot [{name}]"}
                explanations.append({"command": name,
                                     "parameters": args,
                                     "decisions": [decision]})
            out["explanations"] = explanations
        return out

    @d.route("GET", "/_cat/thread_pool")
    def cat_thread_pool(node, params, body):
        return [{"node_name": node.name, "name": name,
                 "active": s["active"], "queue": s["queue"],
                 "rejected": s["rejected"]}
                for name, s in node.thread_pool.stats().items()]

    @d.route("GET", "/_cat/allocation")
    @d.route("GET", "/_cat/allocation/{node_id}")
    def cat_allocation(node, params, body, node_id=None):
        shards = sum(len(s.shards) for s in node.indices.values())
        return [{"shards": shards, "disk.used": "0b", "disk.avail": "1gb",
                 "disk.total": "1gb", "disk.percent": 0,
                 "host": "127.0.0.1", "ip": "127.0.0.1",
                 "node": node.name}]

    @d.route("GET", "/_cat/pending_tasks")
    def cat_pending_tasks(node, params, body):
        return []

    @d.route("GET", "/_cat/plugins")
    def cat_plugins(node, params, body):
        return []

    @d.route("GET", "/_cat/nodeattrs")
    def cat_nodeattrs(node, params, body):
        return [{"node": node.name, "attr": "accelerator",
                 "value": "tpu"}]

    @d.route("GET", "/_cat/fielddata")
    def cat_fielddata(node, params, body):
        out = []
        for name, svc in sorted(node.indices.items()):
            for sid, eng in svc.shards.items():
                reader = eng.acquire_searcher()
                for seg in reader.segments:
                    for fname in list(seg.keywords) + list(seg.numerics):
                        out.append({"node": node.name, "index": name,
                                    "field": fname})
        # aggregate duplicate rows
        uniq = {}
        for r in out:
            uniq[(r["index"], r["field"])] = r
        return list(uniq.values())

    @d.route("GET", "/_cat/recovery")
    @d.route("GET", "/_cat/recovery/{index}")
    def cat_recovery(node, params, body, index=None):
        out = []
        for name, svc in sorted(node.indices.items()):
            if index and name != index:
                continue
            for sid in svc.shards:
                out.append({"index": name, "shard": sid, "type": "store",
                            "stage": "done"})
        return out

    @d.route("GET", "/_cat/repositories")
    def cat_repositories(node, params, body):
        repos = getattr(node.snapshots, "repositories", {})
        return [{"id": rid, "type": "fs"} for rid in sorted(repos)]

    @d.route("GET", "/_cat/snapshots/{repo}")
    def cat_snapshots(node, params, body, repo):
        r = node.snapshots.repositories.get(repo)
        if r is None:
            return []
        return [{"id": sid, "status": "SUCCESS"}
                for sid in r.list_snapshots()]

    def _stats_params(params):
        def _csv(key):
            return params[key].split(",") if params.get(key) else None
        return {
            "level": params.get("level", "indices"),
            "types": _csv("types"),
            "groups": _csv("groups"),
            "fields": _csv("fields"),
            "fielddata_fields": _csv("fielddata_fields"),
            "completion_fields": _csv("completion_fields"),
        }

    @d.route("GET", "/_stats")
    @d.route("GET", "/_stats/{metric}")
    def stats(node, params, body, metric=None):
        return node.indices_stats(None, metric, **_stats_params(params))

    @d.route("GET", "/_cat/indices")
    def cat_indices(node, params, body):
        return node.cat_indices()

    @d.route("GET", "/_cat/health")
    def cat_health(node, params, body):
        h = node.cluster_health()
        return [{"cluster": h["cluster_name"], "status": h["status"],
                 "node.total": h["number_of_nodes"],
                 "shards": h["active_shards"]}]

    # -- search (order matters: register before /{index} wildcards) -------
    @d.route("GET", "/_search")
    @d.route("POST", "/_search")
    def search_all(node, params, body):
        return node.search(None, _body_query(params, body),
                           scroll=params.get("scroll"),
                           search_type=params.get("search_type"))

    @d.route("GET", "/{index}/_search")
    @d.route("POST", "/{index}/_search")
    def search(node, params, body, index):
        return node.search(index, _body_query(params, body),
                           scroll=params.get("scroll"),
                           search_type=params.get("search_type"))

    # indexed search templates (ref: RestPutSearchTemplateAction — ES 2.0
    # stored them in the .scripts index under lang `mustache`)
    @d.route("PUT", "/_search/template/{id}")
    @d.route("POST", "/_search/template/{id}")
    def put_indexed_template(node, params, body, id):
        body = body or {}
        src = body.get("template", body)
        if isinstance(src, dict):
            # compact separators: the stored form is matched by regex in
            # clients/tests (query\S\S\S\Smatch_all)
            src = json.dumps(src, separators=(",", ":"))
        src = str(src)
        if "{{}}" in src:
            # ref: MustacheScriptEngineService compile failure on an
            # empty mustache tag
            raise IllegalArgumentError(
                f"Unable to parse template [{src[:80]}]")
        node.put_stored_script(f"__template__{id}", src)
        return {"acknowledged": True, "_id": id, "created": True,
                "_version": 1}

    @d.route("GET", "/_search/template/{id}")
    def get_indexed_template(node, params, body, id):
        from ..script import ScriptService
        try:
            src = ScriptService.instance().get_stored(f"__template__{id}")
        except ElasticsearchTpuError:
            return RestStatus(404, {"_index": ".scripts", "_id": id,
                                    "found": False, "lang": "mustache"})
        return {"_index": ".scripts", "_id": id, "found": True,
                "lang": "mustache", "template": src, "_version": 1}

    @d.route("DELETE", "/_search/template/{id}")
    def delete_indexed_template(node, params, body, id):
        found = node.delete_stored_script(f"__template__{id}")
        if not found:
            return RestStatus(404, {"found": False,
                                    "_index": ".scripts", "_id": id,
                                    "_version": 1})
        return {"found": True, "_index": ".scripts", "_id": id,
                "_version": 2, "acknowledged": True}

    @d.route("GET", "/_search/template")
    @d.route("POST", "/_search/template")
    def search_template_all(node, params, body):
        return node.search_template(None, body)

    @d.route("GET", "/{index}/_search/template")
    @d.route("POST", "/{index}/_search/template")
    def search_template(node, params, body, index):
        return node.search_template(index, body)

    @d.route("GET", "/_render/template")
    @d.route("POST", "/_render/template")
    def render_template(node, params, body):
        return node.render_template(body)

    def _tv_body(params, body):
        body = dict(body or {})
        for flag in ("term_statistics", "field_statistics", "positions",
                     "offsets", "payloads", "realtime"):
            if flag in params and flag not in body:
                body[flag] = params[flag] in ("true", "1", "", "True")
        return body

    @d.route("GET", "/{index}/_termvectors/{id}")
    @d.route("POST", "/{index}/_termvectors/{id}")
    def termvectors(node, params, body, index, id):
        fields = params.get("fields")
        return node.term_vectors(index, id, _tv_body(params, body),
                                 fields.split(",") if fields else None)

    @d.route("GET", "/{index}/{type}/{id}/_termvectors")
    @d.route("POST", "/{index}/{type}/{id}/_termvectors")
    @d.route("GET", "/{index}/{type}/{id}/_termvector")
    @d.route("POST", "/{index}/{type}/{id}/_termvector")
    def termvectors_typed(node, params, body, index, type, id):
        fields = params.get("fields")
        r = node.term_vectors(index, id, _tv_body(params, body),
                              fields.split(",") if fields else None)
        r["_type"] = type
        return r

    @d.route("GET", "/_mtermvectors")
    @d.route("POST", "/_mtermvectors")
    @d.route("GET", "/{index}/_mtermvectors")
    @d.route("POST", "/{index}/_mtermvectors")
    @d.route("GET", "/{index}/{type}/_mtermvectors")
    @d.route("POST", "/{index}/{type}/_mtermvectors")
    def mtermvectors(node, params, body, index=None, type=None):
        if body is None and params.get("ids"):
            body = {"docs": [{"_id": i}
                             for i in params["ids"].split(",")]}
        body = dict(body or {})
        defaults = _tv_body(params, {})
        if defaults and body.get("docs"):
            body["docs"] = [{**defaults, **spec}
                            for spec in body["docs"]]
        return node.mtermvectors(index, body)

    @d.route("POST", "/_msearch")
    @d.route("POST", "/{index}/_msearch")
    def msearch(node, params, body, index=None):
        # body is a list of (header, body) pairs from ndjson
        requests = []
        lines = body if isinstance(body, list) else []
        for i in range(0, len(lines) - 1, 2):
            header, search_body = lines[i] or {}, lines[i + 1]
            requests.append((header.get("index", index), search_body))
        return node.msearch(requests)

    @d.route("GET", "/_count")
    @d.route("POST", "/_count")
    def count_all(node, params, body):
        return node.count(None, _body_query(params, body))

    @d.route("GET", "/{index}/_count")
    @d.route("POST", "/{index}/_count")
    def count(node, params, body, index):
        return node.count(index, _body_query(params, body))

    # -- bulk -------------------------------------------------------------
    @d.route("POST", "/_bulk")
    @d.route("PUT", "/_bulk")
    @d.route("POST", "/{index}/_bulk")
    def bulk(node, params, body, index=None, type=None):
        lines = body if isinstance(body, list) else []
        ops = []
        i = 0
        while i < len(lines):
            action_line = lines[i]
            action, meta = next(iter(action_line.items()))
            meta = meta or {}
            did = meta.get("_id")
            payload = {"_index": meta.get("_index", index),
                       "_id": str(did) if did is not None else None,
                       "_type": meta.get("_type", type),
                       "_routing": meta.get("_routing",
                                            meta.get("routing"))}
            if action in ("index", "create", "update"):
                i += 1
                payload["doc"] = lines[i] if i < len(lines) else {}
            ops.append((action, payload))
            i += 1
        refresh = params.get("refresh") in ("true", "", "wait_for")
        return node.bulk(ops, refresh=refresh)

    @d.route("POST", "/{index}/{type}/_bulk")
    @d.route("PUT", "/{index}/{type}/_bulk")
    def bulk_typed(node, params, body, index, type):
        return bulk(node, params, body, index, type)

    # -- maintenance ------------------------------------------------------
    @d.route("POST", "/_refresh")
    @d.route("POST", "/{index}/_refresh")
    @d.route("GET", "/{index}/_refresh")
    def refresh(node, params, body, index=None):
        return node.refresh(index)

    @d.route("POST", "/_flush")
    @d.route("POST", "/{index}/_flush")
    def flush(node, params, body, index=None):
        return node.flush(index)

    @d.route("POST", "/{index}/_forcemerge")
    @d.route("POST", "/{index}/_optimize")  # legacy 2.x name
    def forcemerge(node, params, body, index):
        return node.force_merge(index,
                                int(params.get("max_num_segments", 1)))

    # -- mappings / settings ----------------------------------------------
    @d.route("GET", "/_mapping")
    def get_mapping_all(node, params, body):
        return node.get_mapping(
            None, expand_wildcards=params.get("expand_wildcards", "open"))

    @d.route("GET", "/{index}/_mapping")
    def get_mapping(node, params, body, index):
        return node.get_mapping(
            index, expand_wildcards=params.get("expand_wildcards", "open"))

    @d.route("PUT", "/{index}/_mapping")
    @d.route("POST", "/{index}/_mapping")
    def put_mapping(node, params, body, index):
        return node.put_mapping(index, body or {})

    @d.route("GET", "/_settings")
    @d.route("GET", "/{index}/_settings")
    @d.route("GET", "/_settings/{name}")
    @d.route("GET", "/{index}/_settings/{name}")
    def get_settings(node, params, body, index=None, name=None):
        return node.get_settings(
            index, flat=params.get("flat_settings") in ("true", ""),
            name=name,
            expand_wildcards=params.get("expand_wildcards", "open"))

    # -- documents --------------------------------------------------------
    @d.route("POST", "/{index}/_doc")
    def index_auto_id(node, params, body, index):
        return node.index_doc(index, None, body or {},
                              refresh=params.get("refresh") == "true")

    @d.route("PUT", "/{index}/_create/{id}")
    @d.route("POST", "/{index}/_create/{id}")
    def create_doc(node, params, body, index, id):
        params = {**params, "op_type": "create"}
        return index_doc(node, params, body, index, id)

    @d.route("PUT", "/{index}/_doc/{id}")
    @d.route("POST", "/{index}/_doc/{id}")
    def index_doc(node, params, body, index, id, doc_type=None):
        version = params.get("version")
        vt = params.get("version_type", "internal")
        if params.get("op_type") == "create":
            # op_type=create fails on ANY existing doc, independent of
            # version type (ref: TransportIndexAction autogenerate/
            # create → DocumentAlreadyExistsException)
            from ..utils.errors import VersionConflictError
            exists = True
            try:
                node.get_doc(index, id,
                             routing=params.get("routing")
                             or params.get("parent"))
            except ElasticsearchTpuError:
                exists = False
            if exists:
                raise VersionConflictError(index, id, -1, -1)
        return node.index_doc(index, id, body or {},
                              version=int(version) if version else None,
                              routing=params.get("routing"),
                              refresh=_truthy(params, "refresh"),
                              ttl=params.get("ttl"),
                              doc_type=doc_type,
                              version_type=vt,
                              parent=params.get("parent"),
                              timestamp=params.get("timestamp"))

    @d.route("GET", "/{index}/_doc/{id}")
    def get_doc(node, params, body, index, id, doc_type=None):
        realtime = params.get("realtime") not in ("false", "0")
        if _truthy(params, "refresh"):
            node.refresh(index)   # refresh-before-read (ref: GetRequest.refresh)
        r = node.get_doc(index, id, routing=params.get("routing"),
                         doc_type=doc_type, realtime=realtime,
                         parent=params.get("parent"))
        want_version = params.get("version")
        # internal/external/external_gte all require equality on reads;
        # force skips the check (ref: common/lucene/uid/Versions +
        # VersionType read-conflict rules)
        if want_version and params.get("version_type") != "force" \
                and int(want_version) != r.get("_version"):
            # ref: get API version check → VersionConflictEngineException
            from ..utils.errors import VersionConflictError
            raise VersionConflictError(index, id, r.get("_version", -1),
                                       int(want_version))
        src = r.get("_source")
        obj = (json.loads(src) if isinstance(src, (bytes, str))
               else (src or {}))
        field_list = ([f.strip() for f in str(params["fields"]).split(",")]
                      if params.get("fields") else None)
        if field_list is not None:
            flds = {}
            for f in field_list:
                if f in ("_routing", "_parent"):
                    if f in r:
                        flds[f] = r[f]
                elif f == "_timestamp":
                    ts = node._index(index).doc_ts.get(id)
                    if ts is not None:
                        flds[f] = ts
                elif f == "_ttl":
                    # remaining ttl ms from the stored expiry column
                    # (ref: TTLFieldMapper value = expiry - now)
                    try:
                        svc = node._index(index)
                        raw = svc.shard_for(
                            id, r.get("_routing")).get(id)
                        rob = raw.get("_source")
                        rob = (json.loads(rob)
                               if isinstance(rob, (bytes, str)) else rob)
                        exp = (rob or {}).get("_ttl_expiry")
                        if exp:
                            import time as _t
                            flds[f] = int(exp - _t.time() * 1000)
                    except ElasticsearchTpuError:
                        pass
                elif f in obj:
                    v = obj[f]
                    flds[f] = v if isinstance(v, list) else [v]
            if flds:
                r["fields"] = flds
            # an explicit fields list suppresses _source unless requested
            if "_source" not in field_list and "_source" not in params:
                r.pop("_source", None)
                return r
        # GET-level source filtering (ref: RestGetAction fetchSource)
        from ..search.shard_searcher import filter_source
        inc = params.get("_source_include") or params.get("_source_includes")
        exc = params.get("_source_exclude") or params.get("_source_excludes")
        sparam = params.get("_source")
        if inc or exc:
            obj = filter_source(obj, {
                "includes": inc.split(",") if inc else [],
                "excludes": exc.split(",") if exc else []})
        elif sparam == "false":
            r.pop("_source", None)
            return r
        elif sparam and sparam != "true":
            obj = filter_source(obj, sparam.split(","))
        r["_source"] = obj
        return r

    @d.route("DELETE", "/{index}/_doc/{id}")
    def delete_doc(node, params, body, index, id, doc_type=None):
        version = params.get("version")
        r = node.delete_doc(index, id,
                            version=int(version) if version else None,
                            routing=params.get("routing"),
                            refresh=_truthy(params, "refresh"),
                            doc_type=doc_type,
                            version_type=params.get("version_type",
                                                    "internal"),
                            parent=params.get("parent"))
        if not r.get("found"):
            # delete of a missing doc is a 404 with found:false
            # (ref: RestDeleteAction status mapping)
            return RestStatus(404, {**r, "found": False})
        return r

    @d.route("POST", "/{index}/_update/{id}")
    def update_doc(node, params, body, index, id, doc_type=None):
        vt = params.get("version_type", "internal")
        if vt not in ("internal", "force"):
            # ref: UpdateRequest.validate — external versioning is not
            # supported by the update API
            raise IllegalArgumentError(
                "Validation Failed: 1: version type [" + vt +
                "] is not supported by the update API;")
        version = params.get("version")
        fields = params.get("fields")
        return node.update_doc(index, id, body or {},
                               refresh=_truthy(params, "refresh"),
                               doc_type=doc_type,
                               routing=params.get("routing"),
                               parent=params.get("parent"),
                               version=int(version) if version else None,
                               fields=(fields.split(",") if fields
                                       else None),
                               ttl=params.get("ttl"),
                               timestamp=params.get("timestamp"))

    # -- stored scripts (ref: RestPutIndexedScriptAction; ES 2.0 kept
    # these in the .scripts index) -------------------------------------
    @d.route("PUT", "/_scripts/{id}")
    @d.route("POST", "/_scripts/{id}")
    def put_script(node, params, body, id):
        # accepts expression scripts AND mustache search templates, with
        # string or object sources (ref: RestPutStoredScriptAction)
        body = body or {}
        spec = body.get("script", body)
        if isinstance(spec, dict):
            src = spec.get("source", spec.get("inline"))
        else:
            src = spec
        if src is None:
            raise IllegalArgumentError("stored script requires [source]")
        if isinstance(src, dict):
            src = json.dumps(src)
        node.put_stored_script(id, str(src))
        return {"acknowledged": True, "_id": id}

    @d.route("GET", "/_scripts/{id}")
    def get_script(node, params, body, id):
        from ..script import ScriptService
        # get_stored raises ScriptMissingError (404) when absent
        src = ScriptService.instance().get_stored(id)
        return {"_id": id, "found": True,
                "script": {"lang": "expression", "source": src}}

    @d.route("DELETE", "/_scripts/{id}")
    def delete_script(node, params, body, id):
        found = node.delete_stored_script(id)
        return {"acknowledged": found, "found": found}

    @d.route("POST", "/_mget")
    @d.route("GET", "/_mget")
    @d.route("POST", "/{index}/_mget")
    def mget(node, params, body, index=None, type=None):
        body = body or {}
        specs = body.get("docs")
        if specs is None and "ids" in body:
            specs = [{"_id": i} for i in body["ids"]]
        if not specs:
            raise IllegalArgumentError(
                "ActionRequestValidationException: Validation Failed: "
                "1: no documents to get;")
        realtime = params.get("realtime") not in ("false", "0")
        if _truthy(params, "refresh"):
            node.refresh(index)
        url_source = params.get("_source")
        url_inc = (params.get("_source_include")
                   or params.get("_source_includes"))
        url_exc = (params.get("_source_exclude")
                   or params.get("_source_excludes"))
        url_fields = (params["fields"].split(",")
                      if params.get("fields") else None)
        docs = []
        for spec in specs:
            idx = spec.get("_index", index)
            typ = spec.get("_type", type)
            did = spec.get("_id")
            if idx is None or did is None:
                raise IllegalArgumentError(
                    "ActionRequestValidationException: Validation "
                    "Failed: 1: index is missing;"
                    if idx is None else
                    "ActionRequestValidationException: Validation "
                    "Failed: 1: id is missing;")
            did = str(did)
            routing = spec.get("routing", spec.get("_routing"))
            parent = spec.get("parent", spec.get("_parent"))
            try:
                r = node.get_doc(
                    idx, did, doc_type=typ,
                    routing=str(routing) if routing is not None else None,
                    parent=str(parent) if parent is not None else None,
                    realtime=realtime)
                if not r.get("found", True):
                    docs.append({"_index": idx, "_type": typ or "_doc",
                                 "_id": did, "found": False})
                    continue
                src = r["_source"]
                obj = (json.loads(src)
                       if isinstance(src, (bytes, str)) else src)
                r["_index"] = idx
                if typ is not None:
                    r["_type"] = typ
                want_fields = spec.get("fields", spec.get("_fields",
                                                          url_fields))
                src_spec = spec.get("_source")
                if src_spec is None and (url_inc or url_exc):
                    src_spec = {
                        "includes": url_inc.split(",") if url_inc else [],
                        "excludes": url_exc.split(",") if url_exc else []}
                if src_spec is None and url_source is not None:
                    src_spec = (True if url_source == "true" else
                                False if url_source == "false" else
                                url_source.split(","))
                if want_fields:
                    if isinstance(want_fields, str):
                        want_fields = [want_fields]
                    flds = {}
                    for f in want_fields:
                        if f in ("_routing", "_parent"):
                            if f in r:
                                flds[f] = r[f]
                        elif f in obj:
                            v = obj[f]
                            flds[f] = v if isinstance(v, list) else [v]
                    if flds:
                        r["fields"] = flds
                    if "_source" in want_fields:
                        r["_source"] = obj
                    else:
                        r.pop("_source", None)
                elif src_spec is not None:
                    from ..search.shard_searcher import filter_source
                    filtered = filter_source(obj, src_spec)
                    if filtered is None:
                        r.pop("_source", None)
                    else:
                        r["_source"] = filtered
                else:
                    r["_source"] = obj
                docs.append(r)
            except ElasticsearchTpuError:
                docs.append({"_index": idx, "_type": typ or "_doc",
                             "_id": did, "found": False})
        return {"docs": docs}

    @d.route("POST", "/{index}/{type}/_mget")
    @d.route("GET", "/{index}/{type}/_mget")
    def mget_typed(node, params, body, index, type):
        return mget(node, params, body, index, type)

    @d.route("POST", "/{index}/_analyze")
    @d.route("GET", "/{index}/_analyze")
    @d.route("POST", "/_analyze")
    @d.route("GET", "/_analyze")
    def analyze(node, params, body, index=None):
        body = body or {}
        text = body.get("text") or params.get("text") or ""
        field = body.get("field") or params.get("field")
        tokenizer_name = body.get("tokenizer") or params.get("tokenizer")
        filter_names = body.get("filters") or params.get("filters") \
            or body.get("filter") or params.get("filter")
        svc = node.indices.get(index) if index is not None else None
        if field is not None and svc is not None:
            # analyze with the FIELD's own analyzer (ref:
            # TransportAnalyzeAction field resolution)
            analyzer = svc.mappers.search_analyzer_for(field)
            fm = svc.mappers.field(field)
            if fm is not None and fm.type == "text":
                analyzer = svc.mappers.analysis.analyzer(fm.analyzer)
        elif tokenizer_name is not None:
            # ad-hoc tokenizer + filter chain (ref:
            # TransportAnalyzeAction custom analyzer assembly)
            from ..index.analysis import (Analyzer, TOKENIZER_FACTORIES,
                                          TOKEN_FILTERS)
            from ..utils.settings import Settings as _S
            tk = TOKENIZER_FACTORIES.get(tokenizer_name)
            if tk is None:
                raise IllegalArgumentError(
                    f"failed to find tokenizer [{tokenizer_name}]")
            if isinstance(filter_names, str):
                filter_names = filter_names.split(",")
            filters = []
            for fn in filter_names or []:
                f = TOKEN_FILTERS.get(fn)
                if f is None:
                    raise IllegalArgumentError(
                        f"failed to find token filter [{fn}]")
                filters.append(f)
            analyzer = Analyzer("_custom_", tk(_S.EMPTY), filters)
        else:
            name = (body.get("analyzer") or params.get("analyzer")
                    or "standard")
            if svc is not None:
                analyzer = svc.mappers.analysis.analyzer(name)
            else:
                from ..index.analysis import AnalysisService
                analyzer = AnalysisService().analyzer(name)
        texts = text if isinstance(text, list) else [text]
        tokens = []
        pos = 0
        for t in texts:
            for tok in analyzer.analyze(str(t)):
                tokens.append({"token": tok, "position": pos})
                pos += 1
        return {"tokens": tokens}

    # -- scroll (ref: RestSearchScrollAction/RestClearScrollAction) -------
    @d.route("POST", "/_search/scroll")
    @d.route("GET", "/_search/scroll")
    def scroll(node, params, body, **kw):
        body = body or {}
        sid = body.get("scroll_id") or params.get("scroll_id")
        keepalive = body.get("scroll") or params.get("scroll")
        return node.scroll(sid, keepalive)

    @d.route("DELETE", "/_search/scroll")
    def clear_scroll(node, params, body, **kw):
        ids = (body or {}).get("scroll_id")
        if isinstance(ids, str):
            ids = [ids]
        r = node.clear_scroll(ids)
        if r.pop("_missing", False):
            return RestStatus(404, r)
        return r

    # -- validate / explain / segments ------------------------------------
    @d.route("GET", "/_validate/query")
    @d.route("POST", "/_validate/query")
    @d.route("GET", "/{index}/_validate/query")
    @d.route("POST", "/{index}/_validate/query")
    def validate_query(node, params, body, index=None):
        return node.validate_query(index, _body_query(params, body),
                                   explain=params.get("explain") == "true")

    @d.route("GET", "/_search_shards")
    @d.route("POST", "/_search_shards")
    @d.route("GET", "/{index}/_search_shards")
    @d.route("POST", "/{index}/_search_shards")
    def search_shards(node, params, body, index=None):
        # ref: action/admin/cluster/shards/ClusterSearchShardsAction —
        # which shard copies a search against `index` would touch
        nid = node.name
        shards = []
        for svc in node._resolve(index):
            for sid in sorted(svc.shards):
                shards.append([{"index": svc.name, "node": nid,
                                "shard": sid, "primary": True,
                                "state": "STARTED",
                                "relocating_node": None}])
        return {"nodes": {nid: {"name": nid,
                                "transport_address": "local"}},
                "shards": shards}

    @d.route("GET", "/{index}/_explain/{id}")
    @d.route("POST", "/{index}/_explain/{id}")
    def explain(node, params, body, index, id):
        return node.explain_doc(index, id, _body_query(params, body))

    @d.route("GET", "/_segments")
    @d.route("GET", "/{index}/_segments")
    def segments(node, params, body, index=None):
        return node.segments(
            index,
            ignore_unavailable=_truthy(params, "ignore_unavailable"),
            allow_no_indices=params.get("allow_no_indices") != "false")

    # -- aliases ----------------------------------------------------------
    @d.route("POST", "/_aliases")
    def update_aliases(node, params, body, **kw):
        return node.update_aliases((body or {}).get("actions") or [])

    @d.route("PUT", "/{index}/_alias/{alias}")
    @d.route("POST", "/{index}/_alias/{alias}")
    @d.route("PUT", "/{index}/_aliases/{alias}")
    @d.route("POST", "/{index}/_aliases/{alias}")
    def put_alias(node, params, body, index, alias):
        return node.put_alias(index, alias, body)

    @d.route("PUT", "/_alias/{alias}")
    @d.route("POST", "/_alias/{alias}")
    def put_alias_noindex(node, params, body, alias):
        # ref: IndicesAliasesRequest.validate — add requires an index
        raise IllegalArgumentError("alias action requires an [index]")

    @d.route("DELETE", "/{index}/_alias/{alias}")
    @d.route("DELETE", "/{index}/_aliases/{alias}")
    def delete_alias(node, params, body, index, alias):
        return node.delete_alias(index, alias)

    @d.route("GET", "/_alias")
    @d.route("GET", "/{index}/_alias")
    def get_alias_all(node, params, body, index=None):
        return node.get_aliases(index, include_empty=True)

    @d.route("GET", "/_aliases")
    @d.route("GET", "/{index}/_aliases")
    @d.route("GET", "/_aliases/{name}")
    @d.route("GET", "/{index}/_aliases/{name}")
    def get_aliases(node, params, body, index=None, name=None):
        # /_aliases always lists every resolved index (empty map when
        # no alias matches) — ref: RestGetIndicesAliasesAction
        return node.get_aliases(index, name=name, include_empty=True)

    @d.route("GET", "/_alias/{name}")
    @d.route("GET", "/{index}/_alias/{name}")
    def get_alias_by_name(node, params, body, name, index=None):
        r = node.get_aliases(index, name=name)
        if not any(v.get("aliases") for v in r.values()):
            # exists_alias (HEAD) needs the 404, as does a cluster-wide
            # GET for an absent alias; an index-scoped GET returns the
            # empty body with 200 (ref: RestAliasesExistAction vs
            # RestGetAliasesAction missing-alias handling)
            if params.get("__method") == "HEAD" or index is None:
                return RestStatus(404, r)
        return r

    # -- templates --------------------------------------------------------
    @d.route("PUT", "/_template/{name}")
    @d.route("POST", "/_template/{name}")
    def put_template(node, params, body, name):
        return node.put_template(name, body or {},
                                 create=_truthy(params, "create"))

    @d.route("GET", "/_template")
    @d.route("GET", "/_template/{name}")
    def get_template(node, params, body, name=None):
        return node.get_templates(
            name, flat=_truthy(params, "flat_settings"))

    @d.route("DELETE", "/_template/{name}")
    def delete_template(node, params, body, name):
        return node.delete_template(name)

    # -- open/close -------------------------------------------------------
    @d.route("POST", "/{index}/_close")
    def close_index(node, params, body, index):
        return node.close_index(index)

    @d.route("POST", "/{index}/_open")
    def open_index(node, params, body, index):
        return node.open_index(index)

    # -- snapshots (ref: rest/action/admin/cluster/snapshots/) ------------
    @d.route("PUT", "/_snapshot/{repo}")
    @d.route("POST", "/_snapshot/{repo}")
    def put_repository(node, params, body, repo):
        body = body or {}
        return node.snapshots.put_repository(
            repo, body.get("type", "fs"), body.get("settings") or {})

    @d.route("PUT", "/_snapshot/{repo}/{snap}")
    def create_snapshot(node, params, body, repo, snap):
        return node.snapshots.create_snapshot(
            repo, snap, (body or {}).get("indices"))

    @d.route("GET", "/_snapshot")
    @d.route("GET", "/_snapshot/{repo}")
    def get_repository(node, params, body, repo=None):
        return node.snapshots.get_repositories(repo)

    @d.route("POST", "/_snapshot/{repo}/_verify")
    def verify_repository(node, params, body, repo):
        return node.snapshots.verify_repository(repo)

    @d.route("GET", "/_snapshot/{repo}/{snap}")
    def get_snapshots(node, params, body, repo, snap):
        return node.snapshots.get_snapshots(repo, snap)

    @d.route("DELETE", "/_snapshot/{repo}/{snap}")
    def delete_snapshot(node, params, body, repo, snap):
        return node.snapshots.delete_snapshot(repo, snap)

    @d.route("POST", "/_snapshot/{repo}/{snap}/_restore")
    def restore_snapshot(node, params, body, repo, snap):
        body = body or {}
        return node.snapshots.restore_snapshot(
            repo, snap, body.get("indices"),
            body.get("rename_pattern"), body.get("rename_replacement"))

    # -- cluster state / settings / cat -----------------------------------
    @d.route("GET", "/_cluster/state")
    def cluster_state(node, params, body):
        return node.cluster_state()

    @d.route("GET", "/_cluster/state/{metrics}")
    @d.route("GET", "/_cluster/state/{metrics}/{index}")
    def cluster_state_filtered(node, params, body, metrics, index=None):
        return node.cluster_state(
            metrics, index,
            expand_wildcards=params.get("expand_wildcards", "open"),
            ignore_unavailable=_truthy(params, "ignore_unavailable"),
            allow_no_indices=params.get("allow_no_indices") != "false")

    @d.route("GET", "/_cluster/settings")
    def get_cluster_settings(node, params, body):
        return node.get_cluster_settings()

    @d.route("PUT", "/_cluster/settings")
    def put_cluster_settings(node, params, body):
        return node.put_cluster_settings(body or {})

    @d.route("GET", "/_cat/shards")
    def cat_shards(node, params, body):
        return node.cat_shards()

    @d.route("GET", "/_cat/count")
    @d.route("GET", "/_cat/count/{index}")
    def cat_count(node, params, body, index=None):
        return node.cat_count(index)

    @d.route("GET", "/_cat/nodes")
    def cat_nodes(node, params, body):
        return [{"name": node.name, "node.role": "dm", "master": "*"}]

    @d.route("GET", "/_cat/master")
    def cat_master(node, params, body):
        return [{"node": node.name}]

    @d.route("GET", "/_cat/aliases")
    @d.route("GET", "/_cat/aliases/{name}")
    def cat_aliases(node, params, body, name=None):
        import fnmatch
        out = []
        for a, targets in sorted(node._aliases.items()):
            if name is not None and not any(
                    fnmatch.fnmatch(a, p) for p in name.split(",")):
                continue
            for i in sorted(targets):
                meta = node.alias_meta(a, i)
                out.append({"alias": a, "index": i,
                            "filter": "*" if meta.get("filter") else "-",
                            "routing.index":
                                meta.get("index_routing", "-"),
                            "routing.search":
                                meta.get("search_routing", "-")})
        return out

    @d.route("GET", "/_cat/templates")
    def cat_templates(node, params, body):
        return [{"name": n, "index_patterns": t["patterns"],
                 "order": t["order"]}
                for n, t in sorted(node._templates.items())]

    @d.route("GET", "/_cat/segments")
    def cat_segments(node, params, body):
        out = []
        for name, svc in sorted(node.indices.items()):
            for sid, eng in svc.shards.items():
                st = eng.segment_stats()
                out.append({"index": name, "shard": sid, **st})
        return out

    # -- index admin (register LAST: bare /{index} patterns) --------------
    @d.route("PUT", "/{index}")
    def create_index(node, params, body, index):
        body = body or {}
        return node.create_index(index, body.get("settings"),
                                 body.get("mappings"),
                                 aliases=body.get("aliases"),
                                 warmers=body.get("warmers"))

    @d.route("DELETE", "/{index}")
    def delete_index(node, params, body, index):
        return node.delete_index(index)

    @d.route("GET", "/{index}")
    @d.route("GET", "/{index}/{feature}")
    def get_index(node, params, body, index, feature=None):
        # ref: RestGetIndicesAction — optional feature list
        # (_settings,_mappings,_warmers,_aliases) trims the response
        if feature is not None and not feature.startswith("_"):
            if params.get("__method") == "HEAD":
                # HEAD /{index}/{type} = exists_type (ref:
                # RestTypesExistsAction)
                import fnmatch
                tpats = [p.strip() for p in feature.split(",")]
                for svc in node._resolve(index, metadata_op=True):
                    if any(fnmatch.fnmatch(t, p)
                           for t in svc.mapping_types for p in tpats):
                        return {}
                return RestStatus(404, {})
            raise IllegalArgumentError(
                f"no handler found for uri [/{index}/{feature}]")
        feats = {f.strip().removesuffix("s") for f in
                 (feature or "_settings,_mappings,_warmers,_aliases"
                  ).split(",")}
        svcs = node._resolve(
            index,
            expand_wildcards=params.get("expand_wildcards", "open"),
            ignore_unavailable=_truthy(params, "ignore_unavailable"),
            metadata_op=True)
        out = {}
        for svc in svcs:
            name = svc.name
            entry: dict = {}
            if "_mapping" in feats:
                entry.update(node.get_mapping(name)[name])
            if "_setting" in feats:
                entry.update(node.get_settings(name)[name])
            if "_aliase" in feats or "_alias" in feats \
                    or "_alia" in feats:
                entry.update(node.get_aliases(
                    name, include_empty=True)[name])
            if "_warmer" in feats:
                entry["warmers"] = {
                    wn: {"types": [], "source": wsrc}
                    for wn, wsrc in
                    getattr(svc, "warmers", {}).items()}
            out[name] = entry
        if not out and index is not None \
                and not _truthy(params, "ignore_unavailable") \
                and ("*" not in index
                     or params.get("allow_no_indices") == "false"):
            raise IndexNotFoundError(index)
        return out

    # query-driven writes / ttl / warmers / cache / recovery
    @d.route("POST", "/_delete_by_query")
    @d.route("POST", "/{index}/_delete_by_query")
    @d.route("DELETE", "/{index}/_query")     # legacy 2.0 shape
    def delete_by_query(node, params, body, index=None):
        return node.delete_by_query(index, _body_query(params, body))

    @d.route("POST", "/_update_by_query")
    @d.route("POST", "/{index}/_update_by_query")
    def update_by_query(node, params, body, index=None):
        return node.update_by_query(index, body)

    @d.route("PUT", "/_warmer/{name}")
    @d.route("POST", "/_warmer/{name}")
    @d.route("PUT", "/_warmers/{name}")
    @d.route("POST", "/_warmers/{name}")
    def put_warmer_all(node, params, body, name):
        return node.put_warmer(None, name, body)

    @d.route("PUT", "/{index}/_warmer/{name}")
    @d.route("POST", "/{index}/_warmer/{name}")
    @d.route("PUT", "/{index}/_warmers/{name}")
    @d.route("POST", "/{index}/_warmers/{name}")
    def put_warmer(node, params, body, index, name):
        return node.put_warmer(index, name, body)

    @d.route("GET", "/_warmer")
    @d.route("GET", "/_warmer/{name}")
    @d.route("GET", "/_warmers")
    @d.route("GET", "/_warmers/{name}")
    def get_warmer_all(node, params, body, name=None):
        return node.get_warmers(None, name)

    @d.route("GET", "/{index}/_warmer")
    @d.route("GET", "/{index}/_warmer/{name}")
    @d.route("GET", "/{index}/_warmers")
    @d.route("GET", "/{index}/_warmers/{name}")
    def get_warmer(node, params, body, index, name=None):
        return node.get_warmers(index, name)

    @d.route("DELETE", "/{index}/_warmer/{name}")
    @d.route("DELETE", "/{index}/_warmers/{name}")
    @d.route("DELETE", "/{index}/_warmer")
    @d.route("DELETE", "/{index}/_warmers")
    def delete_warmer(node, params, body, index, name=None):
        return node.delete_warmer(index, params.get("name", name))

    @d.route("POST", "/_cache/clear")
    @d.route("POST", "/{index}/_cache/clear")
    def clear_cache(node, params, body, index=None):
        return node.clear_cache(index)

    @d.route("GET", "/_recovery")
    @d.route("GET", "/{index}/_recovery")
    def recovery(node, params, body, index=None):
        return node.recovery_status(index)

    # percolator (ref: rest/action/percolate/RestPercolateAction; queries
    # live under the .percolator type as in ES 2.0)
    @d.route("GET", "/{index}/_percolate")
    @d.route("POST", "/{index}/_percolate")
    def percolate(node, params, body, index):
        return node.percolate(index, _body_query(params, body))

    @d.route("GET", "/{index}/{type}/_percolate")
    @d.route("POST", "/{index}/{type}/_percolate")
    def percolate_typed(node, params, body, index, type):
        return node.percolate(index, _body_query(params, body))

    @d.route("GET", "/{index}/_percolate/count")
    @d.route("POST", "/{index}/_percolate/count")
    @d.route("GET", "/{index}/{type}/_percolate/count")
    @d.route("POST", "/{index}/{type}/_percolate/count")
    def percolate_count(node, params, body, index, type=None):
        return node.percolate(index, _body_query(params, body),
                              count_only=True)

    @d.route("GET", "/{index}/{type}/{id}/_percolate")
    @d.route("POST", "/{index}/{type}/{id}/_percolate")
    def percolate_existing(node, params, body, index, type, id):
        # percolate an EXISTING doc: fetch it, then run the registered
        # queries against its source (ref: RestPercolateAction existing-
        # doc variant; percolate_index may redirect the query set)
        doc = node.get_doc(index, id, routing=params.get("routing"))
        want_version = params.get("version")
        if want_version is not None \
                and int(want_version) != doc.get("_version"):
            # ref: TransportPercolateAction existing-doc version check
            from ..utils.errors import VersionConflictError
            raise VersionConflictError(index, id,
                                       doc.get("_version", -1),
                                       int(want_version))
        src = doc["_source"]
        if isinstance(src, (bytes, str)):
            src = json.loads(src)
        target = params.get("percolate_index", index)
        req = dict(body or {})
        req["doc"] = src
        return node.percolate(target, req)

    @d.route("GET", "/{index}/{type}/{id}/_percolate/count")
    @d.route("POST", "/{index}/{type}/{id}/_percolate/count")
    def percolate_existing_count(node, params, body, index, type, id):
        doc = node.get_doc(index, id, routing=params.get("routing"))
        src = doc["_source"]
        if isinstance(src, (bytes, str)):
            src = json.loads(src)
        target = params.get("percolate_index", index)
        req = dict(body or {})
        req["doc"] = src
        return node.percolate(target, req, count_only=True)

    @d.route("POST", "/_mpercolate")
    def mpercolate(node, params, body):
        return node.mpercolate(body if isinstance(body, list) else [])

    # legacy typed operation routes (ES 2.0 per-type paths; single-type
    # internally, the type segment is accepted and echoed)
    @d.route("GET", "/{index}/{type}/_search")
    @d.route("POST", "/{index}/{type}/_search")
    def search_typed(node, params, body, index, type):
        idx = None if index in ("_all", "*") else index
        return node.search(idx, _body_query(params, body),
                           scroll=params.get("scroll"),
                           search_type=params.get("search_type"))

    @d.route("GET", "/{index}/{type}/_count")
    @d.route("POST", "/{index}/{type}/_count")
    def count_typed(node, params, body, index, type):
        idx = None if index in ("_all", "*") else index
        return node.count(idx, _body_query(params, body))

    @d.route("POST", "/{index}/{type}/{id}/_update")
    def update_typed(node, params, body, index, type, id):
        r = update_doc(node, params, body, index, id, doc_type=type)
        r.setdefault("_type", type)
        return r

    @d.route("GET", "/{index}/{type}/{id}/_source")
    def get_source_typed(node, params, body, index, type, id):
        realtime = params.get("realtime") not in ("false", "0")
        if _truthy(params, "refresh"):
            node.refresh(index)
        r = node.get_doc(index, id, doc_type=type,
                         routing=params.get("routing"),
                         realtime=realtime,
                         parent=params.get("parent"))
        src = r["_source"]
        obj = json.loads(src) if isinstance(src, (bytes, str)) else src
        from ..search.shard_searcher import filter_source
        inc = params.get("_source_include") or params.get("_source_includes")
        exc = params.get("_source_exclude") or params.get("_source_excludes")
        if inc or exc:
            obj = filter_source(obj, {
                "includes": inc.split(",") if inc else [],
                "excludes": exc.split(",") if exc else []})
        return obj

    @d.route("GET", "/{index}/{type}/{id}/_explain")
    @d.route("POST", "/{index}/{type}/{id}/_explain")
    def explain_typed(node, params, body, index, type, id):
        return node.explain_doc(index, id, _body_query(params, body))

    @d.route("GET", "/{index}/{type}/{id}/_mlt")
    @d.route("POST", "/{index}/{type}/{id}/_mlt")
    def mlt_typed(node, params, body, index, type, id):
        # ref: rest/action/mlt/RestMoreLikeThisAction — search with a
        # more_like_this query seeded by the doc
        mlt: dict = {"like": [{"_id": id}],
                     "min_term_freq": int(params.get("min_term_freq", 1)),
                     "min_doc_freq": int(params.get("min_doc_freq", 1))}
        if params.get("mlt_fields"):
            mlt["fields"] = params["mlt_fields"].split(",")
        sbody = dict(body or {})
        sbody["query"] = {"more_like_this": mlt}
        return node.search(index, sbody)

    @d.route("GET", "/_suggest")
    @d.route("POST", "/_suggest")
    @d.route("GET", "/{index}/_suggest")
    @d.route("POST", "/{index}/_suggest")
    def suggest_endpoint(node, params, body, index=None):
        # ref: rest/action/suggest/RestSuggestAction — bare suggest
        # request = search with only a suggest section
        r = node.search(index, {"suggest": body or {}, "size": 0})
        out = {"_shards": r["_shards"]}
        out.update(r.get("suggest", {}))
        return out

    @d.route("GET", "/_search/scroll/{scroll_id}")
    @d.route("POST", "/_search/scroll/{scroll_id}")
    def scroll_path(node, params, body, scroll_id):
        return node.scroll(scroll_id, params.get("scroll")
                           or (body or {}).get("scroll"))

    @d.route("DELETE", "/_search/scroll/{scroll_id}")
    def clear_scroll_path(node, params, body, scroll_id):
        r = node.clear_scroll(scroll_id.split(","))
        if r.pop("_missing", False):
            return RestStatus(404, r)
        return r

    @d.route("GET", "/{index}/_stats")
    @d.route("GET", "/{index}/_stats/{metric}")
    def index_stats(node, params, body, index, metric=None):
        return node.indices_stats(index, metric, **_stats_params(params))

    @d.route("PUT", "/{index}/_settings")
    @d.route("PUT", "/_settings")
    def put_settings(node, params, body, index=None):
        return node.update_index_settings(
            index, body or {},
            ignore_unavailable=_truthy(params, "ignore_unavailable"))

    @d.route("GET", "/_mapping/{type}")
    @d.route("GET", "/{index}/_mapping/{type}")
    @d.route("GET", "/_mappings/{type}")
    @d.route("GET", "/{index}/_mappings/{type}")
    def get_mapping_typed(node, params, body, index=None, type=None):
        return node.get_mapping(index, type,
                                params.get("expand_wildcards", "open"))

    @d.route("PUT", "/{index}/{type}/_mapping")
    @d.route("POST", "/{index}/{type}/_mapping")
    @d.route("PUT", "/{index}/{type}/_mappings")
    @d.route("POST", "/{index}/{type}/_mappings")
    @d.route("PUT", "/{index}/_mapping/{type}")
    @d.route("POST", "/{index}/_mapping/{type}")
    @d.route("PUT", "/{index}/_mappings/{type}")
    @d.route("POST", "/{index}/_mappings/{type}")
    @d.route("PUT", "/_mapping/{type}")
    @d.route("POST", "/_mapping/{type}")
    @d.route("PUT", "/_mappings/{type}")
    @d.route("POST", "/_mappings/{type}")
    def put_mapping_typed2(node, params, body, index=None, type=None):
        return node.put_mapping(index, body or {}, doc_type=type)

    @d.route("GET", "/_mapping/field/{fields}")
    @d.route("GET", "/{index}/_mapping/field/{fields}")
    @d.route("GET", "/_mapping/{type}/field/{fields}")
    @d.route("GET", "/{index}/_mapping/{type}/field/{fields}")
    def get_field_mapping(node, params, body, fields, index=None,
                          type=None):
        return node.get_field_mapping(
            index, fields, doc_type=type,
            include_defaults=_truthy(params, "include_defaults"))

    # legacy typed doc routes /{index}/{type}/{id}
    @d.route("PUT", "/{index}/{type}/{id}")
    @d.route("POST", "/{index}/{type}/{id}")
    def index_doc_typed(node, params, body, index, type, id):
        if type == ".percolator":
            return node.register_percolator(index, id, body)
        if type.startswith("_"):
            raise IllegalArgumentError(f"no handler for type [{type}]")
        return index_doc(node, params, body, index, id, doc_type=type)

    @d.route("POST", "/{index}/{type}")
    def index_auto_id_typed(node, params, body, index, type):
        if type.startswith("_"):
            raise IllegalArgumentError(f"no handler for type [{type}]")
        return node.index_doc(index, None, body or {},
                              refresh=params.get("refresh") == "true",
                              routing=params.get("routing"),
                              doc_type=type)

    @d.route("PUT", "/{index}/{type}/{id}/_create")
    @d.route("POST", "/{index}/{type}/{id}/_create")
    def create_doc_typed(node, params, body, index, type, id):
        params = {**params, "op_type": "create"}
        return index_doc(node, params, body, index, id, doc_type=type)

    @d.route("GET", "/{index}/{type}/{id}")
    def get_doc_typed(node, params, body, index, type, id):
        if type == ".percolator":
            return node.get_percolator(index, id)
        if type.startswith("_") and type != "_all":
            raise IllegalArgumentError(f"no handler for type [{type}]")
        return get_doc(node, params, body, index, id,
                       doc_type=type)

    @d.route("DELETE", "/{index}/{type}/{id}")
    def delete_doc_typed(node, params, body, index, type, id):
        if type == ".percolator":
            return node.unregister_percolator(index, id)
        if type.startswith("_") and type != "_all":
            raise IllegalArgumentError(f"no handler for type [{type}]")
        return delete_doc(node, params, body, index, id,
                          doc_type=type)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class RestServer:
    """HTTP front end for a Node (ref: HttpServer + RestController)."""

    def __init__(self, node: Node, host: str = "127.0.0.1", port: int = 9200):
        self.node = node
        self.dispatcher = RestDispatcher(node)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _respond(self, status: int, payload, pretty: bool = False,
                         head_only: bool = False):
                if isinstance(payload, (dict, list)):
                    data = json.dumps(payload,
                                      indent=2 if pretty else None).encode()
                    ctype = "application/json"
                else:
                    data = str(payload).encode()
                    ctype = "text/plain"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                if not head_only:
                    self.wfile.write(data)

            def _handle(self, method: str):
                parsed = urlparse(self.path)
                req_path = parsed.path
                params = {k: v[0] for k, v in parse_qs(parsed.query).items()
                          if v}
                # bare flags like ?pretty
                for flag in parsed.query.split("&"):
                    if flag and "=" not in flag:
                        params[flag] = "true"
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                try:
                    body = None
                    if raw.strip():
                        text = raw.decode("utf-8")
                        # ndjson is decided by ENDPOINT, not by newline
                        # count — a one-action _bulk body is still ndjson
                        if req_path.rstrip("/").endswith(
                                ("_bulk", "_msearch", "_mpercolate")):
                            body = [json.loads(line)
                                    for line in text.splitlines()
                                    if line.strip()]
                        else:
                            body = json.loads(text)
                    result = outer.dispatcher.dispatch(
                        method, req_path, params, body)
                    accept_json = "application/json" in (
                        self.headers.get("Accept") or "")
                    if req_path.startswith("/_cat") \
                            and params.get("format") != "json" \
                            and not accept_json:
                        # _cat endpoints speak aligned plain text (ref:
                        # rest/action/cat/AbstractCatAction + RestTable)
                        result = _cat_text(result, params)
                    status = 200
                    if isinstance(result, RestStatus):
                        status, result = result.status, result.payload
                    elif method in ("POST", "PUT") \
                            and isinstance(result, dict) \
                            and result.get("created"):
                        status = 201
                    self._respond(status, result,
                                  pretty=params.get("pretty") == "true",
                                  head_only=(method == "HEAD"))
                except ElasticsearchTpuError as e:
                    self._respond(e.status,
                                  {"error": e.to_dict(), "status": e.status},
                                  head_only=(method == "HEAD"))
                except json.JSONDecodeError as e:
                    self._respond(400, {"error": {
                        "type": "parse_exception",
                        "reason": f"request body is not valid JSON: {e}"},
                        "status": 400})
                except Exception as e:  # noqa: BLE001 - the 500 boundary
                    self._respond(500, {"error": {
                        "type": type(e).__name__, "reason": str(e)},
                        "status": 500})

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

            def do_HEAD(self):
                self._handle("HEAD")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "RestServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def main():  # pragma: no cover - CLI entry (ref: bootstrap/Elasticsearch)
    import argparse

    ap = argparse.ArgumentParser(description="elasticsearch_tpu node")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--data", default=None, help="data path (durable mode)")
    ap.add_argument("--shards", type=int, default=1)
    args = ap.parse_args()
    node = Node({"path.data": args.data,
                 "index.number_of_shards": args.shards}
                if args.data else {"index.number_of_shards": args.shards})
    server = RestServer(node, args.host, args.port).start()
    print(f"node [{node.name}] listening on http://{server.host}:{server.port}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
        node.close()


if __name__ == "__main__":  # pragma: no cover
    main()
