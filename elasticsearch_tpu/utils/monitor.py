"""Host & runtime monitoring: OS / process / runtime / fs / device stats.

Reference analog: monitor/ — OsService, ProcessService, JvmService,
FsService (MonitorService.java), with the native Sigar path
(monitor/sigar/SigarService.java:30) replaced by direct /proc reading
(Linux) — no JNI needed; and a TPU-native addition: accelerator device
stats from the JAX backend. `_nodes/hot_threads` becomes a Python thread
stack sampler (action/admin/cluster/node/hotthreads/).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

_START_TIME = time.time()
_last_cpu: tuple[float, float] | None = None


def _read_file(path: str) -> str:
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return ""


def os_stats() -> dict:
    """Ref: monitor/os/OsStats.java — load average, memory, cpu."""
    out: dict = {"timestamp": int(time.time() * 1000)}
    load = _read_file("/proc/loadavg").split()
    if len(load) >= 3:
        out["load_average"] = [float(load[0]), float(load[1]), float(load[2])]
    mem: dict = {}
    for line in _read_file("/proc/meminfo").splitlines():
        parts = line.split()
        if parts and parts[0] in ("MemTotal:", "MemFree:", "MemAvailable:",
                                  "SwapTotal:", "SwapFree:"):
            mem[parts[0][:-1]] = int(parts[1]) * 1024
    if mem:
        total = mem.get("MemTotal", 0)
        free = mem.get("MemAvailable", mem.get("MemFree", 0))
        out["mem"] = {
            "total_in_bytes": total,
            "free_in_bytes": free,
            "used_in_bytes": max(total - free, 0),
            "free_percent": int(100 * free / total) if total else 0,
            "used_percent": int(100 * (total - free) / total) if total else 0,
        }
        out["swap"] = {
            "total_in_bytes": mem.get("SwapTotal", 0),
            "free_in_bytes": mem.get("SwapFree", 0),
            "used_in_bytes": max(mem.get("SwapTotal", 0)
                                 - mem.get("SwapFree", 0), 0),
        }
    # whole-machine cpu percent from /proc/stat deltas
    global _last_cpu
    stat = _read_file("/proc/stat").splitlines()
    if stat and stat[0].startswith("cpu "):
        nums = [float(x) for x in stat[0].split()[1:8]]
        idle = nums[3] + (nums[4] if len(nums) > 4 else 0)
        total_t = sum(nums)
        if _last_cpu is not None and total_t > _last_cpu[0]:
            dt = total_t - _last_cpu[0]
            didle = idle - _last_cpu[1]
            out["cpu"] = {"percent": int(100 * (1 - didle / dt))}
        _last_cpu = (total_t, idle)
    out["cpu"] = out.get("cpu", {"percent": 0})
    out["available_processors"] = os.cpu_count() or 1
    return out


def process_stats() -> dict:
    """Ref: monitor/process/ProcessStats.java."""
    out: dict = {"timestamp": int(time.time() * 1000), "id": os.getpid()}
    status = _read_file("/proc/self/status")
    for line in status.splitlines():
        if line.startswith("VmRSS:"):
            out["mem"] = {"resident_in_bytes": int(line.split()[1]) * 1024}
        elif line.startswith("Threads:"):
            out["threads"] = int(line.split()[1])
    try:
        out["open_file_descriptors"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        out["open_file_descriptors"] = -1
    try:
        with open("/proc/self/stat") as f:
            parts = f.read().split()
        tck = os.sysconf("SC_CLK_TCK")
        out["cpu"] = {"total_in_millis": int(
            (float(parts[13]) + float(parts[14])) * 1000 / tck)}
    except (OSError, ValueError, IndexError):
        pass
    return out


def runtime_stats() -> dict:
    """The JvmService analog: Python runtime — gc, threads, uptime.
    Ref: monitor/jvm/JvmStats.java."""
    import gc
    counts = gc.get_count()
    return {
        "timestamp": int(time.time() * 1000),
        "uptime_in_millis": int((time.time() - _START_TIME) * 1000),
        "version": sys.version.split()[0],
        "gc": {"collections": {f"gen{i}": {"count": c}
                               for i, c in enumerate(counts)}},
        "threads": {"count": threading.active_count()},
        "mem": process_stats().get("mem", {}),
    }


def fs_stats(paths: list[str]) -> dict:
    """Ref: monitor/fs/FsStats.java — per data path disk usage."""
    import shutil
    data = []
    total = {"total_in_bytes": 0, "free_in_bytes": 0, "available_in_bytes": 0}
    for p in paths or ["."]:
        try:
            du = shutil.disk_usage(p)
        except OSError:
            continue
        entry = {"path": p, "total_in_bytes": du.total,
                 "free_in_bytes": du.free, "available_in_bytes": du.free}
        data.append(entry)
        for k in total:
            total[k] += entry[k]
    return {"timestamp": int(time.time() * 1000), "total": total,
            "data": data}


def device_stats() -> dict:
    """TPU-native extension: accelerator devices + HBM stats from the JAX
    backend (the framework's equivalent of the reference's OS-level
    memory pressure view, because the working set lives in HBM)."""
    try:
        import jax
        devices = []
        for d in jax.devices():
            entry = {"id": d.id, "platform": d.platform,
                     "kind": getattr(d, "device_kind", "unknown")}
            try:
                ms = d.memory_stats()
                if ms:
                    entry["memory"] = {
                        "bytes_in_use": ms.get("bytes_in_use"),
                        "bytes_limit": ms.get("bytes_limit"),
                        "peak_bytes_in_use": ms.get("peak_bytes_in_use"),
                    }
            except Exception:
                pass
            devices.append(entry)
        return {"count": len(devices), "devices": devices}
    except Exception:
        return {"count": 0, "devices": []}


def hot_threads(top_n: int = 3, interval_ms: int = 500) -> str:
    """Thread stack sampler. Ref: action/admin/cluster/node/hotthreads/ —
    two samples of every thread's stack; threads whose top frame moved
    between samples are 'hot'. Output is the jstack-style text format
    the _nodes/hot_threads API returns."""
    def snapshot() -> dict[int, list]:
        return {tid: traceback.extract_stack(frame)
                for tid, frame in sys._current_frames().items()}

    first = snapshot()
    time.sleep(min(interval_ms, 2000) / 1000.0)
    second = snapshot()
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    entries = []
    for tid, stack in second.items():
        if tid == me or not stack:
            continue
        prev = first.get(tid)
        moved = prev is None or (prev and prev[-1][:2] != stack[-1][:2])
        entries.append((moved, tid, stack))
    entries.sort(key=lambda e: (not e[0], e[1]))
    lines = [f"::: {{{names.get(tid, f'thread-{tid}')}}}\n"
             f"   {'100.0' if moved else '0.0'}% cpu usage by thread\n"
             + "".join(f"     {ln}\n" for ln in
                       traceback.format_list(stack[-10:]))
             for moved, tid, stack in entries[:top_n]]
    return "".join(lines) or "no hot threads\n"
