"""XContent: pluggable wire formats for REST bodies and responses.

Reference analog: common/xcontent/ — XContentType{JSON, YAML, CBOR,
SMILE} with XContentFactory sniffing the request Content-Type and
rendering responses in the negotiated type. Here JSON is native, YAML
rides PyYAML, and CBOR is a self-contained RFC 8949 codec (major types
0-5 + simple values + doubles — the subset JSON-shaped documents use).
SMILE (a Jackson-private binary JSON) is recognized and rejected with a
clear 406-style error rather than half-implemented.
"""

from __future__ import annotations

import json
import struct

from .errors import IllegalArgumentError

JSON = "application/json"
YAML = "application/yaml"
CBOR = "application/cbor"
SMILE = "application/smile"


# ---------------------------------------------------------------------------
# CBOR (RFC 8949 subset)
# ---------------------------------------------------------------------------


def cbor_dumps(obj) -> bytes:
    out = bytearray()
    _cb_encode(obj, out)
    return bytes(out)


def _cb_head(major: int, n: int, out: bytearray) -> None:
    if n < 24:
        out.append((major << 5) | n)
    elif n < 0x100:
        out.append((major << 5) | 24)
        out.append(n)
    elif n < 0x10000:
        out.append((major << 5) | 25)
        out += n.to_bytes(2, "big")
    elif n < 0x100000000:
        out.append((major << 5) | 26)
        out += n.to_bytes(4, "big")
    else:
        out.append((major << 5) | 27)
        out += n.to_bytes(8, "big")


def _cb_encode(obj, out: bytearray) -> None:
    if obj is False:
        out.append(0xF4)
    elif obj is True:
        out.append(0xF5)
    elif obj is None:
        out.append(0xF6)
    elif isinstance(obj, int):
        if obj >= 0:
            _cb_head(0, obj, out)
        else:
            _cb_head(1, -1 - obj, out)
    elif isinstance(obj, float):
        out.append(0xFB)
        out += struct.pack(">d", obj)
    elif isinstance(obj, bytes):
        _cb_head(2, len(obj), out)
        out += obj
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        _cb_head(3, len(b), out)
        out += b
    elif isinstance(obj, (list, tuple)):
        _cb_head(4, len(obj), out)
        for v in obj:
            _cb_encode(v, out)
    elif isinstance(obj, dict):
        _cb_head(5, len(obj), out)
        for k, v in obj.items():
            _cb_encode(str(k), out)
            _cb_encode(v, out)
    else:
        _cb_encode(str(obj), out)  # dates/np scalars degrade to text


class _CborReader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise IllegalArgumentError("truncated CBOR input")
        b = self.data[self.pos: self.pos + n]
        self.pos += n
        return b

    def _len(self, info: int) -> int:
        if info < 24:
            return info
        if info == 24:
            return self._take(1)[0]
        if info == 25:
            return int.from_bytes(self._take(2), "big")
        if info == 26:
            return int.from_bytes(self._take(4), "big")
        if info == 27:
            return int.from_bytes(self._take(8), "big")
        raise IllegalArgumentError(
            f"unsupported CBOR length encoding [{info}]")

    def decode(self):
        b = self._take(1)[0]
        major, info = b >> 5, b & 0x1F
        if major == 0:
            return self._len(info)
        if major == 1:
            return -1 - self._len(info)
        if major == 2:
            return self._take(self._len(info))
        if major == 3:
            return self._take(self._len(info)).decode("utf-8")
        if major == 4:
            return [self.decode() for _ in range(self._len(info))]
        if major == 5:
            return {self.decode(): self.decode()
                    for _ in range(self._len(info))}
        if major == 7:
            if info == 20:
                return False
            if info == 21:
                return True
            if info in (22, 23):
                return None
            if info == 25:  # half float
                h = int.from_bytes(self._take(2), "big")
                return _half_to_float(h)
            if info == 26:
                return struct.unpack(">f", self._take(4))[0]
            if info == 27:
                return struct.unpack(">d", self._take(8))[0]
        raise IllegalArgumentError(
            f"unsupported CBOR item [major={major} info={info}]")


def _half_to_float(h: int) -> float:
    sign = -1.0 if h & 0x8000 else 1.0
    exp = (h >> 10) & 0x1F
    frac = h & 0x3FF
    if exp == 0:
        return sign * frac * 2.0 ** -24
    if exp == 31:
        return sign * (float("inf") if frac == 0 else float("nan"))
    return sign * (1 + frac / 1024.0) * 2.0 ** (exp - 15)


def cbor_loads(data: bytes):
    r = _CborReader(data)
    obj = r.decode()
    if r.pos != len(data):
        raise IllegalArgumentError("trailing bytes after CBOR value")
    return obj


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

_SMILE_MAGIC = b":)\n"


def content_type_of(header: str | None, raw: bytes) -> str:
    """Negotiated request content type; sniffs the SMILE/CBOR magic the
    way XContentFactory.xContentType does when the header is absent or
    generic."""
    h = (header or "").split(";")[0].strip().lower()
    if h in (JSON, YAML, CBOR, SMILE, "text/yaml", "application/x-yaml"):
        return YAML if "yaml" in h else h
    if raw[:3] == _SMILE_MAGIC:
        return SMILE
    if raw[:1] in (b"\xbf", b"\xa0") or (raw and raw[0] >> 5 == 5):
        return CBOR
    return JSON


def parse_body(raw: bytes, content_type: str | None):
    """Request bytes -> python object per the negotiated type."""
    ctype = content_type_of(content_type, raw)
    if ctype == SMILE:
        raise IllegalArgumentError(
            "SMILE content is not supported by this build; send JSON, "
            "YAML, or CBOR")
    if ctype == CBOR:
        return cbor_loads(raw)
    if ctype == YAML:
        import yaml
        return yaml.safe_load(raw.decode("utf-8"))
    return json.loads(raw.decode("utf-8"))


def render_body(payload, fmt: str | None,
                pretty: bool = False) -> tuple[bytes, str]:
    """Response object -> (bytes, content type) per the `format` param
    (ref: RestRequest XContentType from `format`)."""
    f = (fmt or "json").lower()
    if f in ("yaml", "yml"):
        import yaml
        return (yaml.safe_dump(payload, sort_keys=False,
                               allow_unicode=True).encode(), YAML)
    if f == "cbor":
        return cbor_dumps(payload), CBOR
    if f == "smile":
        raise IllegalArgumentError(
            "SMILE responses are not supported by this build")
    return (json.dumps(payload,
                       indent=2 if pretty else None).encode(), JSON)
