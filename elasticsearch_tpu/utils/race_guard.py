"""Runtime complement to graftlint's shared-state-race rule.

The static lockset pass proves the SOURCE guards its declared-shared
structures; this module proves the PROCESS does. Hot-path containers
whose discipline the race pass verifies (the dispatch scheduler's
pending queue, the traffic controller's tenant map, the resident entry
LRU, the tile pager's residency map, the metrics registry, the shard
request cache) are constructed through ``guarded_dict`` /
``guarded_odict`` / ``guarded_list``, which return container subclasses
that remember the lock contractually guarding them. While ARMED
(``ES_TPU_RACE_GUARD=1`` at Node init, or the ``race_guarded`` pytest
fixture), every mutating operation cheaply asserts that lock is held —
a mutation that slipped around the lock increments a per-site trip
counter instead of silently corrupting the structure, so a stress test
(or a bench run) surfaces the race as a moving number at the exact
site, not as a once-a-month KeyError.

Disarmed cost: one module-level bool read per mutation on the guarded
structures — no lock operations, no allocation; the containers behave
exactly like dict/OrderedDict/list. Armed checks never raise either:
the counter is the signal (raising would turn a benign stats race into
a 500 for the request that happened to trip it).

Stats surface as ``nodes_stats()["dispatch"]["race_guard_trips"]``
ONLY while armed (absent otherwise — the steady-state payload is
unchanged), mirroring trace_guard's transfer_guard_trips contract.
"""

from __future__ import annotations

import collections
import os
import threading

_TRUE = ("1", "true", "on", "yes")

_mx = threading.Lock()
_armed = False
_trips = 0
_trips_by_site: dict[str, int] = {}


def armed() -> bool:
    return _armed


def env_requested() -> bool:
    return os.environ.get("ES_TPU_RACE_GUARD", "").lower() in _TRUE


def arm() -> bool:
    """Arm process-wide (idempotent). Returns True when newly armed."""
    global _armed
    with _mx:
        if _armed:
            return False
        _armed = True
        return True


def disarm() -> None:
    global _armed
    with _mx:
        _armed = False


def reset_counters() -> None:
    global _trips
    with _mx:
        _trips = 0
        _trips_by_site.clear()


def record_trip(site: str) -> None:
    global _trips
    with _mx:
        _trips += 1
        _trips_by_site[site] = _trips_by_site.get(site, 0) + 1


def trips() -> int:
    return _trips


def trips_by_site() -> dict[str, int]:
    with _mx:
        return dict(_trips_by_site)


def snapshot() -> dict | None:
    """Counter payload merged flat into nodes_stats()["dispatch"];
    None when not armed (the key appears only while the guard is
    live, like trace_guard's)."""
    if not _armed:
        return None
    return {"race_guard_trips": _trips}


def _owned(lock) -> bool:
    """Is `lock` held (by the current thread, where the primitive can
    tell)? RLock knows its owner; a plain Lock only knows it is held —
    good enough: the declared structures are mutated strictly under
    their own lock, so "someone holds it" vs "we hold it" differ only
    in pathological interleavings the trip counter exists to catch."""
    is_owned = getattr(lock, "_is_owned", None)
    if is_owned is not None:
        try:
            return bool(is_owned())
        except TypeError:
            pass
    locked = getattr(lock, "locked", None)
    if locked is not None:
        return bool(locked())
    return True     # unknown primitive: never false-positive


def _check(container) -> None:
    # getattr, not attribute access: a copy-constructed twin
    # (OrderedDict.copy() builds one via __class__) carries no guard
    # and must behave like the plain builtin
    guard = getattr(container, "_guard", None)
    if guard is not None and _armed and not _owned(guard[0]):
        record_trip(guard[1])


class GuardedDict(dict):
    """dict asserting its declared lock is held on every mutation."""

    __slots__ = ("_guard",)


class GuardedODict(collections.OrderedDict):
    """OrderedDict twin (the LRU shapes: move_to_end is a mutation)."""

    # no __slots__: OrderedDict's C layout owns the instance state


class GuardedList(list):
    """list asserting its declared lock is held on every mutation."""

    __slots__ = ("_guard",)


def _install_guards(cls, base, names) -> None:
    """Wrap every mutating method of `base` named in `names` with the
    lock assertion — ONE list of guarded operations per container
    type, so adding a missed mutator is a one-line change (the
    copy-pasted-method version drifted: sort/reverse/__iadd__ were
    exactly the mutators it forgot)."""
    for name in names:
        fn = getattr(base, name)

        def make(fn):
            def wrapper(self, *a, **kw):
                _check(self)
                return fn(self, *a, **kw)
            return wrapper

        w = make(fn)
        w.__name__ = name
        w.__qualname__ = f"{cls.__name__}.{name}"
        setattr(cls, name, w)


_DICT_MUTATORS = ("__setitem__", "__delitem__", "__ior__", "pop",
                  "popitem", "setdefault", "update", "clear")
_install_guards(GuardedDict, dict, _DICT_MUTATORS)
_install_guards(GuardedODict, collections.OrderedDict,
                _DICT_MUTATORS + ("move_to_end",))
_install_guards(GuardedList, list,
                ("__setitem__", "__delitem__", "__iadd__", "__imul__",
                 "append", "extend", "insert", "pop", "remove",
                 "clear", "sort", "reverse"))


def guarded_dict(lock, site: str) -> GuardedDict:
    """Declare a lock-guarded dict. `site` names the structure in trip
    stats ("dispatch.DispatchScheduler._pending" style)."""
    d = GuardedDict()
    d._guard = (lock, site)
    return d


def guarded_odict(lock, site: str) -> GuardedODict:
    d = GuardedODict()
    d._guard = (lock, site)
    return d


def guarded_list(lock, site: str) -> GuardedList:
    lst = GuardedList()
    lst._guard = (lock, site)
    return lst
