"""Layered immutable settings.

Reference analog: common/settings/Settings.java + ImmutableSettings.java —
a flat, dot-separated key->string map with typed getters (getAsInt,
getAsBytesSize, getAsTime), group extraction (getByPrefix / getGroups) and
builder-style layering; node/internal/InternalSettingsPreparer.java merges
config file < env < explicit overrides.

TPU-first deviation: no Guice — components take a Settings (or a typed
dataclass derived from one) at construction; nothing is mutable after
build. Dynamic cluster settings are handled by publishing a NEW Settings
in cluster state (see cluster/), never by in-place mutation.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Iterator, Mapping


_TIME_UNITS = {
    "nanos": 1e-9, "micros": 1e-6, "ms": 1e-3,
    "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0, "w": 604800.0,
}
_BYTE_UNITS = {
    "b": 1, "kb": 1024, "k": 1024, "mb": 1024 ** 2, "m": 1024 ** 2,
    "gb": 1024 ** 3, "g": 1024 ** 3, "tb": 1024 ** 4, "t": 1024 ** 4,
    "pb": 1024 ** 5, "p": 1024 ** 5,
}
_SIZE_RE = re.compile(r"^\s*([0-9.+-]+)\s*([a-zA-Z%]*)\s*$")


def _flatten(prefix: str, obj: Any, out: dict) -> None:
    if isinstance(obj, Mapping):
        for k, v in obj.items():
            _flatten(f"{prefix}{k}.", v, out)
    elif isinstance(obj, (list, tuple)):
        out[prefix.rstrip(".")] = list(obj)
    else:
        out[prefix.rstrip(".")] = obj


class Settings:
    """Flat immutable key->value settings map with typed accessors.

    Nested dicts flatten to dot-keys; keys may also be given pre-dotted
    ("index.number_of_shards"), matching the reference's flat map model.
    """

    EMPTY: "Settings"

    def __init__(self, data: "Mapping[str, Any] | Settings | None" = None):
        flat: dict[str, Any] = {}
        if isinstance(data, Settings):
            flat = dict(data._map)
        elif data:
            _flatten("", data, flat)
        self._map: dict[str, Any] = flat

    # -- builders ----------------------------------------------------------
    @classmethod
    def builder(cls) -> "SettingsBuilder":
        return SettingsBuilder()

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Settings":
        return cls(d)

    @classmethod
    def from_file(cls, path: str) -> "Settings":
        """Load a YAML (elasticsearch.yml form), JSON, or .properties
        config file by extension (ref: common/settings/loader/ —
        YamlSettingsLoader/JsonSettingsLoader/PropertiesSettingsLoader).
        """
        if path.endswith((".yml", ".yaml")):
            import yaml
            with open(path, "r") as f:
                return cls(yaml.safe_load(f) or {})
        if path.endswith(".properties"):
            out: dict = {}
            with open(path, "r") as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith(("#", "!")):
                        continue
                    k, _, v = line.partition("=")
                    out[k.strip()] = v.strip()
            return cls(out)
        with open(path, "r") as f:
            return cls(json.load(f))

    @classmethod
    def prepare(cls, overrides: Mapping[str, Any] | None = None,
                config_path: str | None = None,
                env: Mapping[str, str] | None = None) -> "Settings":
        """Merge config file < environment (ES_TPU_*) < explicit overrides.

        Ref: node/internal/InternalSettingsPreparer.prepareSettings.
        """
        b = cls.builder()
        if config_path and os.path.exists(config_path):
            b.put_all(cls.from_file(config_path)._map)
        env = os.environ if env is None else env
        for k, v in env.items():
            if k.startswith("ES_TPU_"):
                b.put(k[len("ES_TPU_"):].lower().replace("__", "."), v)
        if overrides:
            b.put_all(Settings(overrides)._map)
        return b.build()

    # -- accessors ---------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return self._map.get(key, default)

    def get_str(self, key: str, default: str | None = None) -> str | None:
        v = self._map.get(key)
        return default if v is None else str(v)

    def get_int(self, key: str, default: int | None = None) -> int | None:
        v = self._map.get(key)
        return default if v is None else int(v)

    def get_float(self, key: str, default: float | None = None) -> float | None:
        v = self._map.get(key)
        return default if v is None else float(v)

    def get_bool(self, key: str, default: bool | None = None) -> bool | None:
        v = self._map.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        return str(v).lower() in ("true", "1", "on", "yes")

    def get_list(self, key: str, default: list | None = None) -> list | None:
        v = self._map.get(key)
        if v is None:
            # comma-joined fallback: "a,b,c"
            return default
        if isinstance(v, list):
            return v
        return [s.strip() for s in str(v).split(",") if s.strip()]

    def get_time(self, key: str, default: str | float | None = None) -> float | None:
        """Duration in seconds; accepts '30s', '5m', '100ms', bare numbers (ms).

        Ref: common/unit/TimeValue.java parsing rules.
        """
        v = self._map.get(key)
        if v is None:
            if default is None:
                return None
            if isinstance(default, (int, float)):
                return float(default)  # numeric defaults are seconds (return unit)
            v = default
        if isinstance(v, (int, float)):
            return float(v) / 1e3  # bare numbers in settings are millis (TimeValue rule)
        m = _SIZE_RE.match(str(v))
        if not m or (m.group(2) and m.group(2) not in _TIME_UNITS):
            raise ValueError(f"failed to parse time value [{v}] for [{key}]")
        return float(m.group(1)) * _TIME_UNITS.get(m.group(2) or "ms")

    def get_bytes(self, key: str, default: str | int | None = None) -> int | None:
        """Byte size; accepts '512mb', '60%'-of-total via get_memory, ints.

        Ref: common/unit/ByteSizeValue.java.
        """
        v = self._map.get(key, default)
        if v is None:
            return None
        if isinstance(v, (int, float)):
            return int(v)
        m = _SIZE_RE.match(str(v))
        if not m or (m.group(2) and m.group(2).lower() not in _BYTE_UNITS):
            raise ValueError(f"failed to parse byte size [{v}] for [{key}]")
        return int(float(m.group(1)) * _BYTE_UNITS.get(m.group(2).lower() or "b", 1))

    def get_ratio(self, key: str, default: str | float | None = None) -> float | None:
        """'60%' -> 0.60; floats pass through. Ref: MemorySizeValue.java."""
        v = self._map.get(key, default)
        if v is None:
            return None
        s = str(v)
        if s.endswith("%"):
            return float(s[:-1]) / 100.0
        return float(s)

    def by_prefix(self, prefix: str) -> "Settings":
        """Sub-settings with `prefix` stripped. Ref: Settings.getByPrefix."""
        s = Settings()
        s._map = {k[len(prefix):]: v for k, v in self._map.items() if k.startswith(prefix)}
        return s

    def groups(self, prefix: str) -> dict[str, "Settings"]:
        """Ref: Settings.getGroups — e.g. analysis.analyzer.<name>.*"""
        if not prefix.endswith("."):
            prefix += "."
        out: dict[str, Settings] = {}
        for k, v in self._map.items():
            if k.startswith(prefix):
                rest = k[len(prefix):]
                if "." in rest:
                    name, sub = rest.split(".", 1)
                    out.setdefault(name, Settings())._map[sub] = v
        return out

    def as_dict(self) -> dict[str, Any]:
        return dict(self._map)

    def merged_with(self, other: "Settings | Mapping[str, Any]") -> "Settings":
        b = SettingsBuilder().put_all(self._map)
        b.put_all(other._map if isinstance(other, Settings) else Settings(other)._map)
        return b.build()

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def __iter__(self) -> Iterator[str]:
        return iter(self._map)

    def __len__(self) -> int:
        return len(self._map)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Settings) and self._map == other._map

    def __repr__(self) -> str:
        return f"Settings({self._map!r})"


class SettingsBuilder:
    def __init__(self):
        self._map: dict[str, Any] = {}

    def put(self, key: str, value: Any) -> "SettingsBuilder":
        self._map[key] = value
        return self

    def put_all(self, data: Mapping[str, Any]) -> "SettingsBuilder":
        self._map.update(Settings(data)._map if not isinstance(data, Settings) else data._map)
        return self

    def remove(self, key: str) -> "SettingsBuilder":
        self._map.pop(key, None)
        return self

    def build(self) -> Settings:
        s = Settings()
        s._map = dict(self._map)
        return s


Settings.EMPTY = Settings()


def parse_time_value(v, default_ms: int = 60_000) -> int:
    """'5m' / '30s' / '1h' / millis -> millis (ref: common/unit/TimeValue)."""
    if v is None:
        return default_ms
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    units = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000,
             "w": 604_800_000}
    for suffix in ("ms", "s", "m", "h", "d", "w"):
        if s.endswith(suffix):
            try:
                return int(float(s[: -len(suffix)]) * units[suffix])
            except ValueError:
                break
    try:
        return int(s)
    except ValueError:
        raise ValueError(f"failed to parse time value [{v}]")
