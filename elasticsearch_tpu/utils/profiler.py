"""JAX-profiler phase hooks: capture device traces of live traffic.

Reference analog: the hot_threads / JVM-profiler side of operations
tooling — here the interesting time is on the DEVICE, so the equivalent
capture is a jax.profiler trace (XLA op timeline, HBM traffic) started
and stopped over REST (`_nodes/profiler/start|stop`) while real
searches flow. Phase annotations (`annotate("query_phase")`) nest the
engine's phases inside the trace; they compile to TraceMe no-ops when
no trace is active.
"""

from __future__ import annotations

import contextlib
import threading

_lock = threading.Lock()
_active_dir: str | None = None


def start(path: str) -> dict:
    global _active_dir
    with _lock:
        if _active_dir is not None:
            from .errors import IllegalArgumentError
            raise IllegalArgumentError(
                f"profiler already tracing to [{_active_dir}]")
        import jax
        jax.profiler.start_trace(path)
        _active_dir = path
    return {"tracing": True, "path": path}


def stop() -> dict:
    global _active_dir
    with _lock:
        if _active_dir is None:
            from .errors import IllegalArgumentError
            raise IllegalArgumentError("profiler is not tracing")
        import jax
        path = _active_dir
        try:
            jax.profiler.stop_trace()
        finally:
            # a failed stop must not wedge the profiler in "already
            # tracing" until process restart
            _active_dir = None
    return {"tracing": False, "path": path}


def status() -> dict:
    return {"tracing": _active_dir is not None,
            **({"path": _active_dir} if _active_dir else {})}


def annotate(name: str):
    """Phase annotation context: shows up as a named span in the trace
    timeline; near-zero cost when no trace is active."""
    if _active_dir is None:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.TraceAnnotation(name)
