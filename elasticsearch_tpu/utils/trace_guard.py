"""Runtime complement to graftlint: transfer guards + compile logging.

graftlint proves the SOURCE can't host-sync or recompile on the hot
path; this module proves the PROCESS doesn't. When armed it:

  * sets jax's transfer guards to ``disallow`` — any IMPLICIT
    device<->host transfer (a numpy array silently uploaded into a
    compiled call, a traced value silently fetched) raises at the
    violation site. Explicit ``device_put`` / ``device_get`` — the
    spellings the staged feed/fetch pipeline uses on purpose — stay
    legal, so the resident loop runs unchanged;
  * turns on ``jax_log_compiles`` and counts compile events through a
    logging handler — an unexpected recompile on a warm path shows up
    as a moving counter instead of a silent latency cliff.

Stats surface under ``nodes_stats()["dispatch"]`` as
``transfer_guard_trips`` / ``recompiles`` while armed (absent when
not, so the steady-state payload is unchanged). Arm per-process via
``arm()``/``disarm()`` (the tier-1 fixture in tests/test_graftlint.py)
or ``ES_TPU_TRACE_GUARD=1`` at node construction (bench runs report
hot-path hygiene alongside latency).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading

from .metrics import CounterMetric

_TRUE = ("1", "true", "on", "yes")


class _CompileCounter(logging.Handler):
    """Counts jax's "Finished XLA compilation/Compiling ..." records."""

    def __init__(self, stats: "GuardStats"):
        super().__init__(level=logging.DEBUG)
        self._stats = stats

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — never let logging throw
            return
        # exactly one "Compiling <fn> with global shapes..." per XLA
        # compile (pxla); "Finished ..." records would double-count
        if msg.startswith("Compiling "):
            self._stats.recompiles.inc()


class GuardStats:
    def __init__(self):
        self.transfer_guard_trips = CounterMetric()
        self.recompiles = CounterMetric()


_mx = threading.Lock()
_stats = GuardStats()
_armed = False
_prev_guards: dict[str, object] = {}
_handler: _CompileCounter | None = None
_propagate: dict[str, bool] = {}
_levels: dict[str, int] = {}
_JAX_LOGGERS = ("jax._src.dispatch", "jax._src.interpreters.pxla",
                "jax._src.pjit")
# the PROCESS-WIDE config options (jax.transfer_guard() the context
# manager is thread-local — arming there would leave every REST worker
# / dispatch-leader thread unguarded, reporting clean hygiene exactly
# where violations hide)
_GUARD_OPTS = ("jax_transfer_guard_host_to_device",
               "jax_transfer_guard_device_to_device",
               "jax_transfer_guard_device_to_host")


def armed() -> bool:
    return _armed


def env_requested() -> bool:
    return os.environ.get("ES_TPU_TRACE_GUARD", "").lower() in _TRUE


def arm() -> bool:
    """Arm process-wide (idempotent). Returns True when newly armed."""
    global _armed, _handler
    import jax

    with _mx:
        if _armed:
            return False
        for opt in _GUARD_OPTS:
            _prev_guards[opt] = getattr(jax.config, opt)
            jax.config.update(opt, "disallow")
        _prev_guards["jax_log_compiles"] = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        _handler = _CompileCounter(_stats)
        for name in _JAX_LOGGERS:
            lg = logging.getLogger(name)
            lg.addHandler(_handler)
            _levels[name] = lg.level
            if lg.level > logging.DEBUG or lg.level == logging.NOTSET:
                lg.setLevel(logging.DEBUG)
            # jax_log_compiles logs every compile at WARNING; the
            # counter is the consumer, not the console — keep the
            # records out of the root handlers while armed
            _propagate[name] = lg.propagate
            lg.propagate = False
        _armed = True
        return True


def disarm() -> None:
    global _armed, _handler
    import jax

    with _mx:
        if not _armed:
            return
        for opt in _GUARD_OPTS:
            # restore the exact prior value — None (unset) included, so
            # an operator's GLOBAL jax_transfer_guard setting (which an
            # unset per-direction option falls through to) survives the
            # arm/disarm cycle
            jax.config.update(opt, _prev_guards.pop(opt, None))
        # restore (not clear) compile logging — an operator's own
        # JAX_LOG_COMPILES must survive an arm/disarm cycle
        jax.config.update("jax_log_compiles",
                          bool(_prev_guards.pop("jax_log_compiles", False)))
        if _handler is not None:
            for name in _JAX_LOGGERS:
                lg = logging.getLogger(name)
                lg.removeHandler(_handler)
                lg.propagate = _propagate.pop(name, True)
                lg.setLevel(_levels.pop(name, logging.NOTSET))
            _handler = None
        _armed = False


def reset_counters() -> None:
    global _stats
    _stats = GuardStats()
    if _handler is not None:
        _handler._stats = _stats


def record_trip() -> None:
    _stats.transfer_guard_trips.inc()


def _is_transfer_guard_error(e: BaseException) -> bool:
    msg = str(e).lower()
    return "transfer" in msg and ("disallow" in msg or "guard" in msg)


@contextlib.contextmanager
def trap():
    """Count a transfer-guard violation passing through a hot-path
    boundary (the executor's dispatch/collect), then let it propagate —
    the counter is how a bench run sees hygiene regress even when the
    caller swallows the per-request error."""
    if not _armed:
        yield
        return
    try:
        yield
    except BaseException as e:
        if _is_transfer_guard_error(e):
            record_trip()
        raise


def snapshot() -> dict | None:
    """Counter payload for nodes_stats()["dispatch"], None when not
    armed (keys appear only while the guard is live)."""
    if not _armed:
        return None
    return {
        "transfer_guard_trips": _stats.transfer_guard_trips.count,
        "recompiles": _stats.recompiles.count,
    }
