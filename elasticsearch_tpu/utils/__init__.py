from .settings import Settings
from .errors import (
    ElasticsearchTpuError,
    IndexNotFoundError,
    IndexAlreadyExistsError,
    DocumentMissingError,
    VersionConflictError,
    MapperParsingError,
    QueryParsingError,
    SearchParseError,
    CircuitBreakingError,
    IllegalArgumentError,
    ShardNotFoundError,
)
from .metrics import CounterMetric, MeanMetric, EWMA, MeterMetric, MetricsRegistry
from .breaker import CircuitBreaker, HierarchyCircuitBreakerService
from .lifecycle import LifecycleComponent, LifecycleState

__all__ = [
    "Settings",
    "ElasticsearchTpuError",
    "IndexNotFoundError",
    "IndexAlreadyExistsError",
    "DocumentMissingError",
    "VersionConflictError",
    "MapperParsingError",
    "QueryParsingError",
    "SearchParseError",
    "CircuitBreakingError",
    "IllegalArgumentError",
    "ShardNotFoundError",
    "CounterMetric",
    "MeanMetric",
    "EWMA",
    "MeterMetric",
    "MetricsRegistry",
    "CircuitBreaker",
    "HierarchyCircuitBreakerService",
    "LifecycleComponent",
    "LifecycleState",
]
