"""Hierarchical memory circuit breakers.

Reference analog: common/breaker/MemoryCircuitBreaker.java +
indices/breaker/HierarchyCircuitBreakerService.java:43-61 — estimate-based
accounting that trips *before* an allocation OOMs, with per-breaker limits
(fielddata 60%, request 40%) under a parent total (70%).

TPU-first reinterpretation: the scarce resource is HBM, not JVM heap.
The "fielddata" breaker accounts device-resident column/posting bytes; the
"request" breaker accounts per-search transient device buffers (dense
score accumulators, agg bucket arrays). Limits default to fractions of
per-device HBM (detected from jax; overridable via settings).
"""

from __future__ import annotations

import threading

from .errors import CircuitBreakingError
from .settings import Settings

_DEFAULT_TOTAL = 16 * 1024 ** 3  # v5e has 16GB HBM/chip; overridden when detectable


def _device_memory_bytes() -> int:
    try:
        import jax

        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", None)
        if stats:
            limit = (stats() or {}).get("bytes_limit")
            if limit:
                return int(limit)
    except Exception:
        pass
    return _DEFAULT_TOTAL


class Hold:
    """One releasable breaker reservation: released at most once, from
    any exit path — `with breaker.hold(n):` for scoped transients, or
    kept and `release()`d / `shrink()`ed explicitly for reservations
    that outlive the acquiring frame (queued dispatch outputs).

    This is the structural fast path graftlint's breaker-hold rule
    recognizes: pairing is carried by the object, not by every caller
    re-deriving the byte count on each exit."""

    __slots__ = ("_breaker", "_bytes", "_released")

    def __init__(self, breaker: "CircuitBreaker", nbytes: int):
        self._breaker = breaker
        self._bytes = nbytes
        self._released = False

    @property
    def bytes(self) -> int:
        return 0 if self._released else self._bytes

    def shrink(self, new_bytes: int) -> None:
        """Downgrade the reservation (e.g. transient estimate -> queued
        output footprint), releasing the difference now."""
        if self._released or new_bytes >= self._bytes:
            return
        self._breaker.release(self._bytes - max(0, new_bytes))
        self._bytes = max(0, new_bytes)

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._breaker.release(self._bytes)

    def __enter__(self) -> "Hold":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class CircuitBreaker:
    """One named breaker: add estimates, trip past the limit.

    Ref: common/breaker/MemoryCircuitBreaker.java (addEstimateBytesAndMaybeBreak).
    """

    def __init__(self, name: str, limit: int, overhead: float = 1.0,
                 parent: "HierarchyCircuitBreakerService | None" = None):
        self.name = name
        self.limit = limit
        self.overhead = overhead
        self._used = 0
        self._trips = 0
        self._lock = threading.Lock()
        self._parent = parent

    def add_estimate(self, bytes_wanted: int) -> int:
        with self._lock:
            new_used = self._used + bytes_wanted
            if self.limit > 0 and new_used * self.overhead > self.limit:
                self._trips += 1
                raise CircuitBreakingError(self.name, int(new_used * self.overhead), self.limit)
            self._used = new_used
        if self._parent is not None:
            try:
                self._parent.check_parent()
            except CircuitBreakingError:
                with self._lock:
                    # clamp: a concurrent release() may already have clamped
                    # _used to 0, so a raw subtraction could go negative and
                    # corrupt all later accounting
                    self._used = max(0, self._used - bytes_wanted)
                raise
        return self._used

    def hold(self, bytes_wanted: int) -> Hold:
        """add_estimate + a Hold owning the release (raises
        CircuitBreakingError like add_estimate when over limit, in
        which case nothing is held)."""
        self.add_estimate(bytes_wanted)
        return Hold(self, bytes_wanted)

    def add_without_breaking(self, bytes_delta: int) -> int:
        with self._lock:
            self._used += bytes_delta
            return self._used

    def release(self, bytes_freed: int) -> None:
        with self._lock:
            self._used = max(0, self._used - bytes_freed)

    @property
    def used(self) -> int:
        return self._used

    @property
    def trips(self) -> int:
        return self._trips

    def stats(self) -> dict:
        return {
            "limit_size_in_bytes": self.limit,
            "estimated_size_in_bytes": self._used,
            "overhead": self.overhead,
            "tripped": self._trips,
        }


class HierarchyCircuitBreakerService:
    """Child breakers (fielddata/request) under a parent total limit.

    Ref: indices/breaker/HierarchyCircuitBreakerService.java:43-61.
    Settings (fractions of device HBM):
      indices.breaker.total.limit    default 70%
      indices.breaker.fielddata.limit default 60%
      indices.breaker.request.limit  default 40%
    """

    def __init__(self, settings: Settings = Settings.EMPTY, total_memory: int | None = None):
        total_memory = total_memory or settings.get_bytes(
            "indices.breaker.total.memory", None) or _device_memory_bytes()
        self.total_memory = total_memory
        self.parent_limit = int(total_memory * settings.get_ratio("indices.breaker.total.limit", 0.70))
        self._breakers: dict[str, CircuitBreaker] = {}
        self._parent_trips = 0
        self.register("fielddata", int(total_memory * settings.get_ratio(
            "indices.breaker.fielddata.limit", 0.60)), overhead=1.03)
        self.register("request", int(total_memory * settings.get_ratio(
            "indices.breaker.request.limit", 0.40)), overhead=1.0)

    def register(self, name: str, limit: int, overhead: float = 1.0) -> CircuitBreaker:
        b = CircuitBreaker(name, limit, overhead, parent=self)
        self._breakers[name] = b
        return b

    def breaker(self, name: str) -> CircuitBreaker:
        return self._breakers[name]

    def check_parent(self) -> None:
        total = sum(b.used for b in self._breakers.values())
        if total > self.parent_limit:
            self._parent_trips += 1
            raise CircuitBreakingError("parent", total, self.parent_limit)

    def stats(self) -> dict:
        """Per-breaker limit/estimated/trip-count plus the parent
        budget (ref: CircuitBreakerStats incl. the `parent` entry of
        AllCircuitBreakerStats)."""
        out = {name: b.stats() for name, b in self._breakers.items()}
        out["parent"] = {
            "limit_size_in_bytes": self.parent_limit,
            "estimated_size_in_bytes": sum(
                b.used for b in self._breakers.values()),
            "overhead": 1.0,
            "tripped": self._parent_trips,
        }
        return out


_default_service: HierarchyCircuitBreakerService | None = None
_default_lock = threading.Lock()


def breaker_service(settings: Settings | None = None
                    ) -> HierarchyCircuitBreakerService:
    """Process-wide breaker service guarding the device's HBM.

    Deliberately ONE service per process even when several in-process
    test nodes exist: they share the same physical device, so a shared
    budget is the correct accounting (unlike the reference, where each
    JVM owns its heap). The FIRST caller's settings configure the
    limits — Node passes its settings at construction; later callers
    get the existing service."""
    global _default_service
    with _default_lock:
        if _default_service is None:
            _default_service = HierarchyCircuitBreakerService(
                settings or Settings.EMPTY)
        return _default_service
