"""Component lifecycle state machine.

Reference analog: common/component/Lifecycle.java +
AbstractLifecycleComponent.java — INITIALIZED -> STARTED -> STOPPED ->
CLOSED shared by every node service so Node.start/stop/close can walk
services in dependency order (node/Node.java:230-273, :273-330).
"""

from __future__ import annotations

import enum
import threading


class LifecycleState(enum.Enum):
    INITIALIZED = "initialized"
    STARTED = "started"
    STOPPED = "stopped"
    CLOSED = "closed"


class LifecycleComponent:
    """Subclasses implement do_start/do_stop/do_close."""

    def __init__(self):
        self._state = LifecycleState.INITIALIZED
        self._lifecycle_lock = threading.RLock()

    @property
    def lifecycle_state(self) -> LifecycleState:
        return self._state

    def start(self) -> None:
        with self._lifecycle_lock:
            if self._state == LifecycleState.STARTED:
                return
            if self._state == LifecycleState.CLOSED:
                raise RuntimeError(f"cannot start closed component {type(self).__name__}")
            self.do_start()
            self._state = LifecycleState.STARTED

    def stop(self) -> None:
        with self._lifecycle_lock:
            if self._state != LifecycleState.STARTED:
                return
            self.do_stop()
            self._state = LifecycleState.STOPPED

    def close(self) -> None:
        with self._lifecycle_lock:
            if self._state == LifecycleState.CLOSED:
                return
            if self._state == LifecycleState.STARTED:
                self.stop()
            self.do_close()
            self._state = LifecycleState.CLOSED

    def do_start(self) -> None:  # pragma: no cover - trivial default
        pass

    def do_stop(self) -> None:  # pragma: no cover
        pass

    def do_close(self) -> None:  # pragma: no cover
        pass
