"""Typed error hierarchy.

Reference analog: org.elasticsearch.ElasticsearchException and subclasses
(e.g. index/engine/VersionConflictEngineException.java,
indices/IndexMissingException.java). Each error carries an HTTP status so
the REST layer can render it the way rest/BytesRestResponse.java does.
"""

from __future__ import annotations


class ElasticsearchTpuError(Exception):
    """Base error. `status` is the HTTP status the REST layer returns."""

    status = 500

    def __init__(self, message: str = "", **kwargs):
        super().__init__(message)
        self.message = message
        self.info = kwargs

    def to_dict(self) -> dict:
        return {
            "type": type(self).__name__,
            "reason": self.message,
            **{k: v for k, v in self.info.items() if v is not None},
        }


class IllegalArgumentError(ElasticsearchTpuError):
    status = 400


class IndexNotFoundError(ElasticsearchTpuError):
    """Ref: indices/IndexMissingException.java (404)."""

    status = 404

    def __init__(self, index: str):
        super().__init__(f"no such index [{index}]", index=index)
        self.index = index


class IndexClosedError(ElasticsearchTpuError):
    """Ref: indices/IndexClosedException.java (403 FORBIDDEN)."""

    status = 403

    def __init__(self, index: str):
        super().__init__(f"closed", index=index)
        self.index = index


class AliasesMissingError(ElasticsearchTpuError):
    """Ref: rest/action/admin/indices/alias/delete/
    AliasesMissingException (404)."""

    status = 404

    def __init__(self, names):
        super().__init__(f"aliases {list(names)} missing")


class TypeMissingError(ElasticsearchTpuError):
    """Ref: indices/TypeMissingException.java (404)."""

    status = 404

    def __init__(self, type_name: str):
        super().__init__(f"type [{type_name}] missing")


class WarmerMissingError(ElasticsearchTpuError):
    """Ref: search/warmer/IndexWarmerMissingException.java (404)."""

    status = 404

    def __init__(self, name: str):
        super().__init__(f"index_warmer [{name}] missing")


class IndexAlreadyExistsError(ElasticsearchTpuError):
    """Ref: indices/IndexAlreadyExistsException.java (400)."""

    status = 400

    def __init__(self, index: str):
        super().__init__(f"index [{index}] already exists", index=index)
        self.index = index


class RoutingMissingError(ElasticsearchTpuError):
    """Ref: action/RoutingMissingException.java (400): a doc op on a
    parent-mapped (or routing-required) type without routing/parent."""

    status = 400

    def __init__(self, index: str, doc_id: str):
        super().__init__(
            f"routing is required for [{index}]/[{doc_id}]",
            index=index, id=doc_id)


class ShardNotFoundError(ElasticsearchTpuError):
    status = 404

    def __init__(self, index: str, shard: int):
        super().__init__(f"no such shard [{index}][{shard}]", index=index, shard=shard)


class DocumentMissingError(ElasticsearchTpuError):
    """Ref: index/engine/DocumentMissingException.java (404)."""

    status = 404

    def __init__(self, index: str, doc_id: str):
        super().__init__(f"document [{doc_id}] missing", index=index, id=doc_id)


class VersionConflictError(ElasticsearchTpuError):
    """Optimistic-concurrency failure.

    Ref: index/engine/VersionConflictEngineException.java; raised by the
    version check in index/engine/InternalEngine.java:253-274.
    """

    status = 409

    def __init__(self, index: str, doc_id: str, current: int, provided: int):
        super().__init__(
            f"version conflict for [{doc_id}]: current [{current}], provided [{provided}]",
            index=index,
            id=doc_id,
            current_version=current,
            provided_version=provided,
        )
        self.current_version = current


class MapperParsingError(ElasticsearchTpuError):
    """Ref: index/mapper/MapperParsingException.java (400)."""

    status = 400


class QueryParsingError(ElasticsearchTpuError):
    """Ref: index/query/QueryParsingException.java (400)."""

    status = 400


class SearchParseError(ElasticsearchTpuError):
    """Ref: search/SearchParseException.java (400)."""

    status = 400


class ScriptException(ElasticsearchTpuError):
    """Script compile/runtime failure.

    Ref: the GeneralScriptException / expression-compile errors thrown out
    of script/ScriptService.java compile (400 — bad script in request).
    """

    status = 400


class ScriptMissingError(ElasticsearchTpuError):
    """Stored script not found (404, like a missing doc in `.scripts`)."""

    status = 404

    def __init__(self, script_id: str):
        super().__init__(f"unable to find script [{script_id}]",
                         script_id=script_id)


class CircuitBreakingError(ElasticsearchTpuError):
    """Memory budget exceeded before an allocation would blow HBM/host RAM.

    Ref: common/breaker/CircuitBreakingException.java; thrown by
    common/breaker/MemoryCircuitBreaker.java when the estimate crosses the
    limit.
    """

    status = 429

    def __init__(self, breaker: str, wanted: int, limit: int):
        super().__init__(
            f"[{breaker}] data too large: wanted [{wanted}b] would exceed limit [{limit}b]",
            breaker=breaker,
            bytes_wanted=wanted,
            bytes_limit=limit,
        )


class TrafficRejectedError(ElasticsearchTpuError):
    """Admission-control shed (search/traffic.py): the tenant's rate or
    concurrency quota said no BEFORE the request took a thread-pool
    slot or breaker hold. 429 like the reference's
    EsRejectedExecutionException, but structured: `retry_after_s`
    prices when the token bucket will admit again (the REST layer
    renders it as a Retry-After header)."""

    status = 429

    def __init__(self, tenant: str, reason: str,
                 retry_after_s: float = 1.0):
        # a rate-0 (fully blocked) tenant prices to infinity; clamp so
        # the JSON body and Retry-After header stay finite and valid
        if not (retry_after_s == retry_after_s
                and retry_after_s < float("inf")):
            retry_after_s = 3600.0
        super().__init__(
            f"traffic admission rejected for tenant [{tenant}]: "
            f"{reason}", tenant=tenant,
            retry_after=round(retry_after_s, 3))
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class SearchTimeoutError(ElasticsearchTpuError):
    """A shard missed the search deadline (per-request `timeout` /
    `search.default_search_timeout`).

    Ref: the per-shard QueryPhase timeout that surfaces as
    `timed_out: true` + a failed shard in SearchPhaseController — only
    fatal to the request when partial results are disallowed (504).
    """

    status = 504

    def __init__(self, index: str | None = None, shard: int | None = None,
                 timeout_ms: int | None = None):
        where = (f"[{index}][{shard}]" if index is not None
                 else "search")
        msg = f"{where} exceeded the search deadline"
        if timeout_ms is not None:
            msg += f" of [{timeout_ms}ms]"
        super().__init__(msg, index=index, shard=shard,
                         timeout_ms=timeout_ms)


class HostDownError(ElasticsearchTpuError):
    """A mesh host is evicted (failed its heartbeat/exec contract) and
    its shards cannot be re-sourced from a surviving replica — the
    shard-level entry a degraded multihost response carries in
    `_shards.failures` (parallel/multihost.py).

    Ref: NoShardAvailableActionException rendered per shard when a
    node leaves and no started copy remains (503: retryable — the
    host's rejoin restores coverage)."""

    status = 503

    def __init__(self, host: str, shard: int | None = None):
        where = f"[{shard}]" if shard is not None else ""
        super().__init__(
            f"shard{where} lives on evicted mesh host [{host}]",
            host=host, shard=shard)
        self.host = host


class StaleEpochError(ElasticsearchTpuError):
    """A mesh control-plane message carries a membership epoch that no
    longer matches the receiver's — the seq-fencing guard that keeps a
    rejoined (or slow) host from replaying a turn minted against an
    older mesh shape (parallel/multihost.py). Drivers retry against
    the current epoch; the message itself is never served.

    Ref: the master-fencing term checks zen2 puts on cluster-state
    publishes (Coordinator.publish rejects stale terms with 409)."""

    status = 409

    def __init__(self, msg: str, epoch: int | None = None,
                 current: int | None = None):
        super().__init__(msg, epoch=epoch, current=current)


class LeaseFencedError(ElasticsearchTpuError):
    """An exec turn was minted under a coordinator-lease term the
    receiver (or the current holder) no longer honors — the fencing
    that replaces the single-driver-at-a-time convention: a concurrent
    driver gets a 409-and-retry instead of a seq collision
    (parallel/membership.py / parallel/multihost.py). The driver
    re-acquires (or hands off) the lease and retries; nothing is
    served under the stale term.

    Ref: zen2's master term fencing — a publish under an old term is
    rejected so two masters can never both commit."""

    status = 409
    # class-level defaults: a wire-rebuilt instance (tcp_transport
    # restores the base contract without subclass __init__) still
    # answers .term/.holder
    term: int | None = None
    holder: str | None = None

    def __init__(self, msg: str, term: int | None = None,
                 holder: str | None = None):
        super().__init__(msg, term=term, holder=holder)
        self.term = term
        self.holder = holder


class FaultInjectedError(ElasticsearchTpuError):
    """A deterministic injected fault (utils/faults.py) standing in for
    a real device/shard failure — OOM, preemption, tunnel drop."""

    status = 500


class PowerLossError(FaultInjectedError):
    """An injected crash point fired (utils/faults.py `crash_point`):
    the process "died" exactly at a named storage write site, leaving
    whatever partial on-disk state the real crash would have left. A
    test catches this where the OS would have reaped the process —
    NOTHING in the storage stack may catch it (a crashed process does
    not run exception handlers); recovery happens on the next open."""

    status = 500


class ShardFailedError(ElasticsearchTpuError):
    """A shard is in a FAILED (contained) state — typically corruption
    detected during recovery/load (index/store.py corruption marker).
    The NODE stays up: searches over the shard answer with structured
    `_shards.failures` entries, writes answer 503 so clients retry
    against a promoted copy.

    Ref: index/shard/IndexShard failing the shard with
    `corrupted_<uuid>` markers (store corruption handling) while the
    node keeps serving its healthy shards."""

    status = 503

    def __init__(self, index: str, shard: int, reason: str = ""):
        super().__init__(
            f"[{index}][{shard}] shard is failed"
            + (f": {reason}" if reason else ""),
            index=index, shard=shard)
        self.index = index
        self.shard = shard
        self.reason = reason


class ClusterBlockError(ElasticsearchTpuError):
    """An operation hit a cluster-level or index-level block.

    Ref: cluster/block/ClusterBlockException.java (503 when retryable) —
    raised by the action layer's checkGlobalBlock/checkRequestBlock before
    executing (e.g. writes while no master is elected or state is not
    recovered).
    """

    status = 503

    def __init__(self, descriptions):
        super().__init__(f"blocked by: {descriptions}")
