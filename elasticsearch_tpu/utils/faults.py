"""Deterministic fault injection for the search read path.

Reference analog: the reference exercises its partial-failure semantics
(`_shards.failures`, `timed_out`, replica retry in
TransportSearchTypeAction.onFirstPhaseResult) with MockTransportService
disruptions and ESIntegTestCase's random shard failures. A device-mesh
stack has no wire to cut, so this registry injects the equivalent
failure classes AT the dispatch boundary — the reader/executor seam a
real device error (OOM, preemption, tunnel drop) would surface through:

  * ``shard_error``  — dispatch raises FaultInjectedError (a dead shard)
  * ``shard_delay``  — dispatch sleeps (a straggler shard; deadline food)
  * ``breaker_trip`` — a real add_estimate past the named breaker's
    limit, so the CircuitBreakingError AND the trip counter come from
    the production breaker, not a stand-in
  * ``device_dead``  — PERMANENT device death: fires at EVERY phase,
    deterministically (no ``rate=`` decay — a dead chip does not flake
    back to life between dispatches). The injectable the mesh eviction
    threshold (parallel/repack.py) keys on, distinct from transient
    ``shard_error`` which must NOT evict while under-threshold; the
    re-expansion probe consults ``device_dead_matches`` so removing the
    rule is how a "repaired" device comes back

Host-level CONTROL-PLANE kinds (the multihost mesh's failure classes,
hooked at every transport boundary parallel/multihost.py crosses —
ping, clock, exec broadcast, fetch):

  * ``host_dead``   — PERMANENT machine death: every control-plane
    message to OR from the host fails, deterministically (no ``rate=``,
    same reasoning as ``device_dead``). The injectable the host
    eviction threshold keys on; the rejoin probe consults
    ``host_dead_matches`` so removing the rule is how a repaired
    machine comes back
  * ``ctrl_drop``   — a TRANSIENT dropped control-plane message (the
    wire analog of ``shard_error``): the send raises; retry/backoff is
    what recovers it
  * ``ctrl_delay``  — a slow control-plane link: the boundary sleeps
    ``ms=`` before proceeding
  * ``net_partition`` — BIDIRECTIONAL group severing: every
    control-plane message whose two ends straddle the named host set
    (``hosts=a+b``, ``+``-separated) fails, deterministically —
    within-group and outside-group traffic proceeds, so both partition
    halves stay internally live (the split-brain the membership quorum
    must fence). ``heal=`` names hosts subtracted back out of the
    severed set (``heal=all`` disables the rule), and the runtime
    ``heal_partition()`` helper edits the live rule without reseeding —
    partition→heal arcs replay byte-for-byte under one seed. The probe
    helper ``net_partition_matches`` never consumes. Composes with
    ``ctrl_drop``/``ctrl_delay`` rules in the same spec

STORAGE kinds (the durability path's failure classes, hooked at every
``index/store.py`` and ``index/translog.py`` write/read boundary —
the adversary the crash-recovery matrix drives):

  * ``crash_point`` — the process "dies" at a named write site:
    ``site=store`` phases ``seg_npz|seg_meta|commit|cleanup``,
    ``site=translog`` phases ``append|fsync|rotate``. Fires AT MOST
    ONCE per installed registry (a process crashes once), first
    leaving the torn on-disk state the real crash would leave (a
    half-written translog record at ``append``; with
    ``unsynced=drop``, OS-buffered-but-unfsynced translog bytes are
    dropped too — the POWER-LOSS simulation the durability-mode
    guarantee tests need). Then raises ``PowerLossError`` — or, with
    ``kill=1``, SIGKILLs the process (the kill -9 soak's injectable:
    death lands exactly at the write site, no handler runs)
  * ``disk_corrupt`` — post-hoc corruption of the file a READ is
    about to touch (``mode=flip`` one seeded byte, ``mode=truncate``
    the tail quarter), at read phases ``load_npz|load_meta|
    read_commit`` (store) / ``read`` (translog); the read proceeds
    and the production checksum/crc path does the detecting
  * ``io_error``   — the read raises ``OSError(EIO)`` (a dying disk),
    same read phases

Spec grammar (env ``ES_TPU_FAULT_INJECT`` or node setting
``search.fault_injection``; comma-separated rules)::

    shard_error:shard=1:rate=1.0
    shard_delay:ms=200:rate=0.3:seed=7
    breaker_trip:breaker=request:index=logs
    shard_error:shard=1:replica=0          # mesh: fail one replica row
    device_dead:replica=0:site=mesh        # mesh: one row PERMANENTLY dead
    host_dead:host=host-1                  # multihost: machine death
    ctrl_drop:action=exec:rate=0.5:seed=3  # flaky exec broadcast
    ctrl_delay:ms=50:host=host-2:action=fetch
    net_partition:hosts=host-1+host-2        # sever {1,2} from the rest
    net_partition:hosts=host-1+host-2:heal=host-2  # host-2 healed back
    crash_point:site=store:phase=commit    # die mid-flush, commit torn
    crash_point:site=translog:phase=append:rate=0.02:seed=9:kill=1
    crash_point:site=translog:phase=fsync:unsynced=drop  # power loss
    disk_corrupt:site=store:phase=load_npz:mode=flip
    io_error:site=store:phase=load_meta:index=logs:shard=0

Rule selectors ``site`` (reader|mesh), ``index``, ``shard``, ``replica``
restrict where a rule fires; omitted selectors match everything.
``phase`` picks the boundary: ``submit`` (program enqueue — where a
dead shard errors out) or ``collect`` (result sync — where a straggler
burns wall-clock). Defaults: errors/breaker trips fire at submit,
delays at collect, matching how the real failure classes present.
Control-plane kinds take ``host=`` (the REMOTE end of the message —
matching both directions is what makes an injected dead host
unreachable, not merely unresponsive) and ``action=`` (the action
name's trailing segment: ``action=ping`` matches
``internal:mesh/ping`` — the grammar splits rules on ``:``, so the
tail is the addressable form for namespaced actions); they never fire
at data-plane dispatch boundaries and data-plane kinds never fire at
control-plane ones.
``rate`` draws from ONE seeded RNG (``seed=`` on any rule reseeds the
registry), so a given spec+seed yields the same firing sequence every
run — chaos tests stay reproducible without real hardware failures.
"""

from __future__ import annotations

import os
import random
import threading
import time

from .errors import FaultInjectedError, PowerLossError

DISPATCH_KINDS = ("shard_error", "shard_delay", "breaker_trip",
                  "device_dead")
CTRL_KINDS = ("host_dead", "ctrl_drop", "ctrl_delay", "net_partition")
STORAGE_KINDS = ("crash_point", "disk_corrupt", "io_error")
KINDS = DISPATCH_KINDS + CTRL_KINDS + STORAGE_KINDS

# the write sites a crash_point may name and the read sites a
# disk_corrupt/io_error may name, per storage subsystem — validated at
# parse time so a typo'd phase fails the spec instead of silently
# never firing
STORAGE_WRITE_PHASES = {
    "store": ("seg_npz", "seg_meta", "commit", "cleanup"),
    "translog": ("append", "fsync", "rotate"),
}
STORAGE_READ_PHASES = {
    "store": ("load_npz", "load_meta", "read_commit"),
    "translog": ("read",),
}


class FaultRule:
    """One parsed rule: a fault kind plus match selectors."""

    __slots__ = ("kind", "site", "index", "shard", "replica", "phase",
                 "rate", "ms", "breaker", "host", "action", "mode",
                 "kill", "unsynced", "hosts", "heal", "fired")

    def __init__(self, kind: str, site: str | None = None,
                 index: str | None = None, shard: int | None = None,
                 replica: int | None = None, phase: str | None = None,
                 rate: float = 1.0, ms: float = 0.0,
                 breaker: str = "request", host: str | None = None,
                 action: str | None = None, mode: str = "flip",
                 kill: int = 0, unsynced: str | None = None,
                 hosts: frozenset | None = None,
                 heal: frozenset | None = None):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind [{kind}] "
                             f"(expected one of {KINDS})")
        self.kind = kind
        self.mode = mode
        self.kill = bool(kill)
        self.unsynced = unsynced
        if kind != "net_partition" and (hosts is not None
                                        or heal is not None):
            raise ValueError(
                f"[hosts=]/[heal=] apply only to net_partition, "
                f"not [{kind}] (use host= for single-host selectors)")
        self.hosts = frozenset(hosts) if hosts is not None else None
        self.heal = frozenset(heal) if heal is not None else frozenset()
        if kind not in STORAGE_KINDS:
            if mode != "flip" or kill or unsynced is not None:
                raise ValueError(
                    f"[mode=]/[kill=]/[unsynced=] apply only to storage "
                    f"kinds {STORAGE_KINDS}, not [{kind}]")
        if kind in STORAGE_KINDS:
            # storage rules select on (site, phase, index, shard); a
            # file has no replica/host identity and no dispatch phase
            for sel, val in (("replica", replica), ("host", host),
                             ("action", action)):
                if val is not None:
                    raise ValueError(
                        f"{kind} is a storage fault; [{sel}=] does not "
                        "apply (use site=/phase=/index=/shard=)")
            if site is not None and site not in STORAGE_WRITE_PHASES:
                raise ValueError(
                    f"{kind} site must be one of "
                    f"{tuple(STORAGE_WRITE_PHASES)}, got [{site}]")
            valid = (STORAGE_WRITE_PHASES if kind == "crash_point"
                     else STORAGE_READ_PHASES)
            if phase is not None:
                sites = (site,) if site is not None else tuple(valid)
                if not any(phase in valid[s] for s in sites):
                    raise ValueError(
                        f"{kind} phase [{phase}] is not a valid "
                        f"{'write' if kind == 'crash_point' else 'read'}"
                        f" site for {sites} (expected "
                        f"{ {s: valid[s] for s in sites} })")
            if kind != "crash_point" and (kill or unsynced is not None):
                raise ValueError(
                    f"[kill=]/[unsynced=] apply only to crash_point")
            if kind != "disk_corrupt" and mode != "flip":
                raise ValueError("[mode=] applies only to disk_corrupt")
            if mode not in ("flip", "truncate"):
                raise ValueError(
                    f"disk_corrupt mode must be flip|truncate, "
                    f"got [{mode}]")
            if unsynced not in (None, "drop"):
                raise ValueError(
                    f"crash_point unsynced must be [drop] when given, "
                    f"got [{unsynced}]")
            self.site = site
            self.index = index
            self.shard = shard
            self.replica = None
            self.host = None
            self.action = None
            self.phase = phase
            self.rate = rate
            self.ms = ms
            self.breaker = breaker
            self.fired = 0
            return
        if kind in CTRL_KINDS:
            # control-plane rules select on (host, action) only — a
            # machine-level fault has no shard/replica/phase identity
            for sel, val in (("site", site), ("index", index),
                             ("shard", shard), ("replica", replica),
                             ("phase", phase)):
                if val is not None:
                    raise ValueError(
                        f"{kind} is a control-plane fault; [{sel}=] "
                        "does not apply (use host=/action=)")
            if kind == "host_dead" and rate != 1.0:
                raise ValueError(
                    "host_dead is persistent; [rate=] decay is not "
                    "allowed (use ctrl_drop for transient faults)")
            if kind == "ctrl_delay" and ms <= 0.0:
                raise ValueError("ctrl_delay needs [ms=]")
            if kind == "net_partition":
                if not self.hosts:
                    raise ValueError(
                        "net_partition needs [hosts=] (the severed "
                        "group, +-separated: hosts=h-1+h-2)")
                if host is not None or action is not None:
                    raise ValueError(
                        "net_partition severs whole links; [host=]/"
                        "[action=] do not apply (use hosts=/heal=, and "
                        "compose ctrl_drop/ctrl_delay rules for "
                        "action-scoped faults)")
                if rate != 1.0:
                    raise ValueError(
                        "net_partition is persistent while installed; "
                        "[rate=] decay is not allowed (use ctrl_drop "
                        "for flaky links)")
                unknown = self.heal - self.hosts - {"all"}
                if unknown:
                    raise ValueError(
                        f"net_partition heal names hosts outside the "
                        f"partition set: {sorted(unknown)}")
        elif host is not None or action is not None:
            raise ValueError(
                f"{kind} fires at data-plane dispatch boundaries; "
                "[host=]/[action=] apply only to "
                f"control-plane kinds {CTRL_KINDS}")
        self.site = site
        self.index = index
        self.shard = shard
        self.replica = replica
        self.host = host
        self.action = action
        # a dead shard presents at enqueue; a straggler presents while
        # the caller waits on results — the phase defaults encode that.
        # A dead DEVICE presents everywhere: device_dead matches any
        # phase (and may not specify one).
        if kind == "device_dead":
            if phase is not None:
                raise ValueError(
                    "device_dead fires at every phase; drop [phase=]")
            if rate != 1.0:
                raise ValueError(
                    "device_dead is persistent; [rate=] decay is not "
                    "allowed (use shard_error for transient faults)")
            self.phase = None
        elif kind in CTRL_KINDS:
            self.phase = None
        else:
            self.phase = phase or ("collect" if kind == "shard_delay"
                                   else "submit")
        self.rate = rate
        self.ms = ms
        self.breaker = breaker
        self.fired = 0

    def matches(self, site: str, index: str | None, shard: int | None,
                replica: int | None, phase: str) -> bool:
        if self.kind in CTRL_KINDS or self.kind in STORAGE_KINDS:
            return False
        if self.phase is not None and self.phase != phase:
            return False
        if self.site is not None and site != self.site:
            return False
        if self.index is not None and index != self.index:
            return False
        if self.shard is not None and shard != self.shard:
            return False
        if self.replica is not None and replica != self.replica:
            return False
        return True

    def severed_hosts(self) -> frozenset:
        """net_partition's EFFECTIVE severed set: hosts minus heals
        (heal=all empties it — the rule stays installed but cuts
        nothing, so a spec can pin the full arc deterministically)."""
        if self.kind != "net_partition" or self.hosts is None:
            return frozenset()
        if "all" in self.heal:
            return frozenset()
        return self.hosts - self.heal

    def matches_ctrl(self, action: str, host: str | None,
                     me: str | None = None) -> bool:
        """Control-plane boundary match. `host` is the REMOTE end of
        the message (target on send, source on receive) so a
        host-pinned fault severs both directions; `action=` accepts the
        full name or its trailing segment (`ping` ~ internal:mesh/ping).
        `me` is the LOCAL end — net_partition fires when exactly one
        end is inside the severed group (links WITHIN the group and
        links wholly outside it stay up: both halves remain internally
        live, which is the split-brain shape quorum fencing exists
        for). A caller that omits `me` is treated as outside the set."""
        if self.kind not in CTRL_KINDS:
            return False
        if self.kind == "net_partition":
            cut = self.severed_hosts()
            return (host in cut) != (me in cut)
        if self.host is not None and host != self.host:
            return False
        if self.action is not None and action != self.action \
                and action.rsplit("/", 1)[-1] != self.action:
            return False
        return True

    def matches_storage(self, site: str, phase: str,
                        index: str | None, shard: int | None) -> bool:
        """Storage boundary match: (site, phase) name the write/read
        site; index/shard scope the rule to one shard's files when the
        caller knows them (Store/Translog carry their owner's ids)."""
        if self.kind not in STORAGE_KINDS:
            return False
        if self.site is not None and site != self.site:
            return False
        if self.phase is not None and phase != self.phase:
            return False
        if self.index is not None and index != self.index:
            return False
        if self.shard is not None and shard != self.shard:
            return False
        return True

    def describe(self) -> dict:
        sel = {k: getattr(self, k)
               for k in ("site", "index", "shard", "replica", "host",
                         "action")
               if getattr(self, k) is not None}
        out = {"kind": self.kind, "phase": self.phase or "any",
               "rate": self.rate, "fired": self.fired, **sel}
        if self.kind in ("shard_delay", "ctrl_delay"):
            out["ms"] = self.ms
        if self.kind == "breaker_trip":
            out["breaker"] = self.breaker
        if self.kind == "disk_corrupt":
            out["mode"] = self.mode
        if self.kind == "crash_point":
            if self.kill:
                out["kill"] = True
            if self.unsynced is not None:
                out["unsynced"] = self.unsynced
        if self.kind == "net_partition":
            out["hosts"] = sorted(self.hosts or ())
            if self.heal:
                out["heal"] = sorted(self.heal)
        return out


class FaultRegistry:
    """A parsed fault spec + one seeded RNG shared by every rate draw."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.rules = rules
        self.seed = seed
        self._rng = random.Random(seed)
        self._mx = threading.Lock()

    @classmethod
    def parse(cls, spec: str | None) -> "FaultRegistry":
        rules: list[FaultRule] = []
        seed = 0
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            kw: dict = {}
            for f in fields[1:]:
                key, _, val = f.partition("=")
                key = key.strip()
                val = val.strip()
                if key in ("shard", "replica", "kill"):
                    kw[key] = int(val)
                elif key in ("rate", "ms"):
                    kw[key] = float(val)
                elif key == "seed":
                    seed = int(val)
                elif key in ("site", "index", "breaker", "phase",
                             "host", "action", "mode", "unsynced"):
                    kw[key] = val
                elif key in ("hosts", "heal"):
                    # host GROUPS are +-separated (the rule grammar
                    # already claims , and :)
                    kw[key] = frozenset(
                        h for h in val.split("+") if h)
                else:
                    raise ValueError(
                        f"unknown fault selector [{key}] in [{part}]")
            rules.append(FaultRule(fields[0].strip(), **kw))
        return cls(rules, seed)

    def on_dispatch(self, site: str, index: str | None = None,
                    shard: int | None = None,
                    replica: int | None = None,
                    phase: str = "submit",
                    skip_delay: bool = False) -> None:
        """Evaluate every matching rule at a dispatch boundary; raises
        (shard_error / breaker_trip) or sleeps (shard_delay).
        skip_delay=True skips shard_delay rules — the caller already
        injected the straggler delay elsewhere (a resident stepped
        dispatch meters it inside device execution via StepBudget) and
        must not sleep it a second time at the collect boundary."""
        for rule in self.rules:
            if skip_delay and rule.kind == "shard_delay":
                continue
            if not rule.matches(site, index, shard, replica, phase):
                continue
            with self._mx:
                if rule.rate < 1.0 and self._rng.random() >= rule.rate:
                    continue
                rule.fired += 1
            if rule.kind == "shard_delay":
                time.sleep(rule.ms / 1000.0)
            elif rule.kind == "shard_error":
                raise FaultInjectedError(
                    f"injected shard_error at {site} dispatch",
                    index=index, shard=shard)
            elif rule.kind == "device_dead":
                raise FaultInjectedError(
                    f"injected device_dead at {site} dispatch "
                    f"(permanent)", index=index, shard=shard)
            elif rule.kind == "breaker_trip":
                from .breaker import breaker_service
                b = breaker_service().breaker(rule.breaker)
                # a REAL over-limit estimate: the trip counter, error
                # shape, and (non-)retention all come from the
                # production breaker path
                wanted = (b.limit + 1) if b.limit > 0 else (1 << 62)
                # un-tripped (e.g. unlimited breaker): the Hold's scoped
                # exit gives the bytes straight back, no leak
                with b.hold(wanted):
                    pass

    def on_ctrl(self, action: str, host: str | None = None,
                me: str | None = None) -> None:
        """Evaluate control-plane rules at a transport boundary
        (parallel/multihost.py hooks every send AND every handler
        entry); raises (host_dead / ctrl_drop / net_partition) or
        sleeps (ctrl_delay). `host` is the remote end of the message,
        `me` the local end (net_partition needs both to decide whether
        the link straddles the severed group)."""
        for rule in self.rules:
            if not rule.matches_ctrl(action, host, me=me):
                continue
            with self._mx:
                if rule.rate < 1.0 and self._rng.random() >= rule.rate:
                    continue
                rule.fired += 1
            if rule.kind == "ctrl_delay":
                time.sleep(rule.ms / 1000.0)
            elif rule.kind == "host_dead":
                raise FaultInjectedError(
                    f"injected host_dead: [{host}] is unreachable "
                    f"for [{action}] (permanent)")
            elif rule.kind == "net_partition":
                raise FaultInjectedError(
                    f"injected net_partition: link [{me}]<->[{host}] "
                    f"severed for [{action}]")
            else:  # ctrl_drop
                raise FaultInjectedError(
                    f"injected ctrl_drop: [{action}] to/from [{host}] "
                    "lost on the wire")

    def on_storage_write(self, site: str, phase: str,
                         index: str | None = None,
                         shard: int | None = None,
                         partial=None, unsynced_drop=None) -> None:
        """Evaluate crash_point rules at a storage WRITE boundary
        (index/store.py save/commit/cleanup sites, index/translog.py
        append/fsync/rotate). A firing rule first runs `partial` (the
        caller's torn-state writer — e.g. half a translog record) and,
        under ``unsynced=drop``, `unsynced_drop` (the caller's
        page-cache-loss simulation: truncate back to the last fsynced
        offset) — then dies: SIGKILL with ``kill=1``, else
        PowerLossError. One-shot: a process crashes once, so a fired
        crash_point never fires again under the same registry."""
        for rule in self.rules:
            if rule.kind != "crash_point" or rule.fired:
                continue
            if not rule.matches_storage(site, phase, index, shard):
                continue
            with self._mx:
                if rule.fired:
                    continue
                if rule.rate < 1.0 and self._rng.random() >= rule.rate:
                    continue
                rule.fired += 1
            if partial is not None:
                partial()
            if rule.unsynced == "drop" and unsynced_drop is not None:
                unsynced_drop()
            if rule.kill:
                import signal
                os.kill(os.getpid(), signal.SIGKILL)
            raise PowerLossError(
                f"injected crash_point at {site}:{phase}"
                + (f" [{index}][{shard}]" if index is not None else ""))

    def on_storage_read(self, site: str, phase: str, path: str,
                        index: str | None = None,
                        shard: int | None = None) -> None:
        """Evaluate disk_corrupt/io_error rules at a storage READ
        boundary, BEFORE the caller opens `path`: disk_corrupt mutates
        the file on disk (seeded flip / tail truncate) and lets the
        read proceed — detection stays the production checksum/crc
        path's job; io_error raises OSError(EIO) like a dying disk."""
        import errno
        for rule in self.rules:
            if rule.kind not in ("disk_corrupt", "io_error"):
                continue
            if not rule.matches_storage(site, phase, index, shard):
                continue
            with self._mx:
                if rule.rate < 1.0 and self._rng.random() >= rule.rate:
                    continue
                rule.fired += 1
                if rule.kind == "disk_corrupt":
                    _corrupt_file(path, rule.mode, self._rng)
                    continue
            raise OSError(errno.EIO,
                          f"injected io_error at {site}:{phase}", path)

    def step_delay_ms(self, site: str, index: str | None = None,
                      shard: int | None = None,
                      replica: int | None = None) -> float:
        """Total shard_delay milliseconds matching this dispatch at the
        collect boundary, CONSUMED here (rate draws + fired counts) so
        the resident step loop can meter the straggler inside device
        execution instead of sleeping it at collect. One call per
        dispatch (StepBudget enforces the once)."""
        total = 0.0
        for rule in self.rules:
            if rule.kind != "shard_delay":
                continue
            if not rule.matches(site, index, shard, replica, "collect"):
                continue
            with self._mx:
                if rule.rate < 1.0 and self._rng.random() >= rule.rate:
                    continue
                rule.fired += 1
            total += rule.ms
        return total

    def snapshot(self) -> dict:
        return {"enabled": bool(self.rules), "seed": self.seed,
                "rules": [r.describe() for r in self.rules]}


_mx = threading.Lock()
_registry: FaultRegistry | None = None


def active() -> FaultRegistry:
    """The process-wide registry; first use parses ES_TPU_FAULT_INJECT."""
    global _registry
    if _registry is None:
        with _mx:
            if _registry is None:
                _registry = FaultRegistry.parse(
                    os.environ.get("ES_TPU_FAULT_INJECT", ""))
    return _registry


def configure(spec: str | None, seed: int | None = None) -> FaultRegistry:
    """Install a new registry from a spec string (None/"" disables)."""
    global _registry
    with _mx:
        reg = FaultRegistry.parse(spec)
        if seed is not None:
            reg.seed = seed
            reg._rng = random.Random(seed)
        _registry = reg
        return reg


def clear() -> None:
    configure("")


def enabled() -> bool:
    return bool(active().rules)


def on_dispatch(site: str, index: str | None = None,
                shard: int | None = None,
                replica: int | None = None,
                phase: str = "submit",
                skip_delay: bool = False) -> None:
    """Hook call at a dispatch boundary — no-op (one attribute check)
    when no rules are installed."""
    reg = active()
    if reg.rules:
        reg.on_dispatch(site, index=index, shard=shard, replica=replica,
                        phase=phase, skip_delay=skip_delay)


def on_ctrl(action: str, host: str | None = None,
            me: str | None = None) -> None:
    """Control-plane boundary hook — no-op (one attribute check) when
    no rules are installed."""
    reg = active()
    if reg.rules:
        reg.on_ctrl(action, host=host, me=me)


def on_storage_write(site: str, phase: str, index: str | None = None,
                     shard: int | None = None,
                     partial=None, unsynced_drop=None) -> None:
    """Storage write-boundary hook (crash_point) — no-op (one
    attribute check) when no rules are installed."""
    reg = active()
    if reg.rules:
        reg.on_storage_write(site, phase, index=index, shard=shard,
                             partial=partial,
                             unsynced_drop=unsynced_drop)


def on_storage_read(site: str, phase: str, path: str,
                    index: str | None = None,
                    shard: int | None = None) -> None:
    """Storage read-boundary hook (disk_corrupt / io_error) — no-op
    (one attribute check) when no rules are installed."""
    reg = active()
    if reg.rules:
        reg.on_storage_read(site, phase, path, index=index, shard=shard)


def _corrupt_file(path: str, mode: str, rng: random.Random) -> None:
    """The disk_corrupt mutator: one seeded byte-flip mid-file or a
    tail-quarter truncation — the two corruption shapes a real torn
    write / bad sector presents. Missing/empty files are left alone
    (nothing to corrupt; the read will fail on its own terms)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size <= 0:
        return
    if mode == "truncate":
        keep = size - max(size // 4, 1)
        with open(path, "r+b") as f:
            f.truncate(max(keep, 0))
        return
    pos = rng.randrange(size)
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))


def host_dead_matches(host: str) -> bool:
    """Does a persistent host_dead rule still cover this host? The
    rejoin probe (parallel/multihost.py) asks this BEFORE pinging:
    while the rule stands, the injected machine is still dead;
    removing it (faults.configure/clear) is the deterministic analog
    of the machine coming back. Does NOT consume a firing — probes are
    not messages."""
    for rule in active().rules:
        if rule.kind == "host_dead" and rule.matches_ctrl("probe", host):
            return True
    return False


def net_partition_matches(a: str, b: str | None) -> bool:
    """Does an installed net_partition rule sever the a<->b link? The
    membership/rejoin probes (parallel/multihost.py) ask this BEFORE
    pinging: while the link straddles a severed group the partition
    stands; healing it (heal_partition / configure with heal=) is the
    deterministic analog of the network coming back. Does NOT consume
    a firing — probes are not messages."""
    for rule in active().rules:
        if rule.kind != "net_partition":
            continue
        cut = rule.severed_hosts()
        if (a in cut) != (b in cut):
            return True
    return False


def heal_partition(hosts=None) -> None:
    """Runtime heal counterpart of net_partition: fold the named hosts
    (iterable; None = every partitioned host) back into the connected
    component by adding them to each rule's heal set. Edits the LIVE
    rules under the registry lock — no reconfigure, no reseed, so the
    one RNG's draw sequence (and every other rule's determinism) is
    preserved across the partition→heal arc."""
    reg = active()
    with reg._mx:
        for rule in reg.rules:
            if rule.kind != "net_partition":
                continue
            if hosts is None:
                rule.heal = rule.heal | {"all"}
            else:
                rule.heal = rule.heal | (frozenset(hosts) & rule.hosts)


def device_dead_matches(site: str, index: str | None = None,
                        shard: int | None = None,
                        replica: int | None = None) -> bool:
    """Does a persistent device_dead rule still cover this placement?
    The re-expansion probe (parallel/repack.py) asks this BEFORE
    touching real hardware: while the rule stands, the injected device
    is still dead; removing it (faults.configure/clear) is the
    deterministic analog of the chip coming back. Does NOT consume a
    firing — probes are not dispatches."""
    for rule in active().rules:
        if rule.kind == "device_dead" and rule.matches(
                site, index, shard, replica, "probe"):
            return True
    return False


class StepBudget:
    """One-shot straggler budget for a device-stepped dispatch (the
    resident query loop): the FIRST take() consumes the matching
    collect-phase shard_delay rules and hands their total to the step
    loop, which sleeps it per tile chunk inside device execution;
    `taken` then tells the collect boundary to skip delay rules so the
    straggler is not charged twice. Cold dispatches never call take(),
    leaving PR 4's collect-boundary behavior untouched."""

    __slots__ = ("site", "index", "shard", "replica", "taken")

    def __init__(self, site: str, index: str | None = None,
                 shard: int | None = None, replica: int | None = None):
        self.site = site
        self.index = index
        self.shard = shard
        self.replica = replica
        self.taken = False

    def take(self) -> float:
        if self.taken:
            return 0.0
        self.taken = True
        reg = active()
        if not reg.rules:
            return 0.0
        return reg.step_delay_ms(self.site, index=self.index,
                                 shard=self.shard, replica=self.replica)


def snapshot() -> dict:
    return active().snapshot()
