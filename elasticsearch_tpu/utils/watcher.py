"""ResourceWatcherService: polling file watcher with listeners.

Reference analog: watcher/ResourceWatcherService.java + FileWatcher /
FileChangesListener — a scheduled poll at three frequencies (HIGH 5s,
MEDIUM 25s, LOW 60s, overridable via
`resource.reload.interval.{high,medium,low}`; `resource.reload.enabled`
gates the whole service) notifying listeners of created / changed /
deleted files. The reference uses it to hot-reload file scripts, role
mappings and hunspell dictionaries; here it backs file-script reload
(script/service.py) and is a public extension point for plugins.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

from .settings import Settings

HIGH, MEDIUM, LOW = "high", "medium", "low"
_DEFAULT_INTERVALS = {HIGH: 5.0, MEDIUM: 25.0, LOW: 60.0}


class FileChangesListener:
    """Ref: watcher/FileChangesListener.java — override any subset."""

    def on_file_created(self, path: str) -> None:  # pragma: no cover
        pass

    def on_file_changed(self, path: str) -> None:  # pragma: no cover
        pass

    def on_file_deleted(self, path: str) -> None:  # pragma: no cover
        pass


@dataclass
class FileWatcher:
    """Watches one file or directory tree by mtime+size snapshots
    (ref: watcher/FileWatcher.java)."""

    path: str
    listeners: list[FileChangesListener] = field(default_factory=list)
    _state: dict[str, tuple[float, int]] = field(default_factory=dict)
    _initialized: bool = False

    def add_listener(self, listener: FileChangesListener) -> None:
        self.listeners.append(listener)

    def _scan(self) -> dict[str, tuple[float, int]]:
        out: dict[str, tuple[float, int]] = {}
        if os.path.isfile(self.path):
            try:
                st = os.stat(self.path)
                out[self.path] = (st.st_mtime, st.st_size)
            except OSError:
                pass
            return out
        for root, _dirs, files in os.walk(self.path):
            for f in files:
                p = os.path.join(root, f)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                out[p] = (st.st_mtime, st.st_size)
        return out

    def init(self) -> None:
        """First scan: existing files surface as created (the reference
        calls onFileInit, which most listeners alias to created)."""
        self._state = self._scan()
        self._initialized = True
        for p in sorted(self._state):
            for l in self.listeners:
                l.on_file_created(p)

    def check(self) -> None:
        if not self._initialized:
            self.init()
            return
        now = self._scan()
        for p in sorted(now):
            if p not in self._state:
                for l in self.listeners:
                    l.on_file_created(p)
            elif now[p] != self._state[p]:
                for l in self.listeners:
                    l.on_file_changed(p)
        for p in sorted(self._state):
            if p not in now:
                for l in self.listeners:
                    l.on_file_deleted(p)
        self._state = now


class ResourceWatcherService:
    """Schedules FileWatcher polls on a daemon thread.

    `notify_now(freq)` runs a poll synchronously — what the reference's
    tests do through its exposed Scheduler — so tests and callers never
    need to sleep.
    """

    def __init__(self, settings: Settings = Settings.EMPTY):
        self.enabled = settings.get_bool("resource.reload.enabled", True)
        self.intervals = {
            f: settings.get_time(f"resource.reload.interval.{f}", dflt)
            for f, dflt in _DEFAULT_INTERVALS.items()}
        self._watchers: dict[str, list[FileWatcher]] = {
            HIGH: [], MEDIUM: [], LOW: []}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_run = {f: 0.0 for f in _DEFAULT_INTERVALS}

    def add(self, watcher: FileWatcher, frequency: str = MEDIUM
            ) -> FileWatcher:
        if frequency not in self._watchers:
            raise ValueError(f"unknown watch frequency [{frequency}]")
        watcher.init()
        with self._lock:
            self._watchers[frequency].append(watcher)
        if self.enabled:
            self._ensure_thread()
        return watcher

    def remove(self, watcher: FileWatcher) -> None:
        with self._lock:
            for lst in self._watchers.values():
                if watcher in lst:
                    lst.remove(watcher)

    def notify_now(self, frequency: str = MEDIUM) -> None:
        with self._lock:
            watchers = list(self._watchers[frequency])
        for w in watchers:
            w.check()

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="resource-watcher")
        self._thread.start()

    def _run(self) -> None:
        import time
        tick = min(1.0, min(self.intervals.values()))
        while not self._stop.wait(tick):
            now = time.monotonic()
            for freq, interval in self.intervals.items():
                if now - self._last_run[freq] >= interval:
                    self._last_run[freq] = now
                    try:
                        self.notify_now(freq)
                    except Exception:  # listener bugs must not kill polls
                        import logging
                        logging.getLogger(__name__).exception(
                            "resource watcher poll failed")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
