"""Metrics primitives + registry.

Reference analog: common/metrics/ (CounterMetric.java, MeanMetric.java,
EWMA.java, MeterMetric.java). Python counters are GIL-atomic enough for
the host control plane; device-side timing comes from the search executor.
"""

from __future__ import annotations

import math
import threading
import time


class CounterMetric:
    """Monotonic (inc/dec) counter. Ref: common/metrics/CounterMetric.java."""

    __slots__ = ("_count", "_lock")

    def __init__(self):
        # writes are locked (+= is read-modify-write); the bare read in
        # .count is a single int load, atomic under the GIL
        # graftlint: ok(shared-state-race): GIL-atomic single-op read;
        # all writes serialize under _lock
        self._count = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._count += n

    def dec(self, n: int = 1) -> None:
        with self._lock:
            self._count -= n

    @property
    def count(self) -> int:
        return self._count


class HighWaterMetric:
    """High-water-mark gauge: record() keeps the max ever seen — ints
    (the dispatch scheduler's in-flight pipeline depth) or floats (the
    resident loop's staged-feed overlap in ms)."""

    __slots__ = ("_max", "_last", "_lock")

    def __init__(self):
        # graftlint: ok(shared-state-race): GIL-atomic single-value
        # reads in .max/.last; the compare-and-store writes serialize
        # under _lock
        self._max = 0
        # graftlint: ok(shared-state-race): GIL-atomic single-value
        # read; writes serialize under _lock
        self._last = 0
        self._lock = threading.Lock()

    def record(self, value: int | float) -> None:
        with self._lock:
            self._last = value
            if value > self._max:
                self._max = value

    @property
    def max(self) -> int | float:
        return self._max

    @property
    def last(self) -> int | float:
        return self._last


class MeanMetric:
    """Sum + count -> mean. Ref: common/metrics/MeanMetric.java."""

    __slots__ = ("_sum", "_count", "_lock")

    def __init__(self):
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def inc(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def mean(self) -> float:
        # one lock for BOTH loads: a mean computed from a sum and a
        # count out of different inc() generations is a torn read
        with self._lock:
            return self._sum / self._count if self._count else 0.0


class EWMA:
    """Exponentially-weighted moving average. Ref: common/metrics/EWMA.java.

    Internally locked: update() is a read-modify-write shared by
    MeterMetric's rate tick and the traffic controller's adaptive
    coalescing window, both of which feed it from concurrent request
    threads — the shared-state-race pass verifies the lockset instead
    of trusting callers to serialize."""

    __slots__ = ("alpha", "_value", "_initialized", "_lock")

    def __init__(self, alpha: float = 0.3, initial: float = 0.0,
                 seeded: bool = False):
        """`seeded=True` starts the series AT `initial` (the first
        sample decays toward it) instead of replacing it — the adaptive
        window's merged-round average starts at 1.0 that way."""
        self.alpha = alpha
        self._value = initial
        self._initialized = seeded
        self._lock = threading.Lock()

    def update(self, sample: float) -> None:
        with self._lock:
            if not self._initialized:
                self._value = sample
                self._initialized = True
            else:
                self._value += self.alpha * (sample - self._value)

    def reset(self) -> None:
        """Forget the series (the adaptive window's idle reset): the
        next sample re-seeds the average instead of decaying toward it."""
        with self._lock:
            self._value = 0.0
            self._initialized = False

    @property
    def initialized(self) -> bool:
        with self._lock:
            return self._initialized

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class MeterMetric:
    """Events/sec with 1m EWMA. Ref: common/metrics/MeterMetric.java."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._count = CounterMetric()
        self._start = clock()
        self._m1 = EWMA(alpha=1 - math.exp(-5.0 / 60.0))
        self._last_tick = self._start
        self._uncounted = 0
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._count.inc(n)
            self._uncounted += n
            self._tick_locked()

    def _tick_locked(self) -> None:
        now = self._clock()
        while now - self._last_tick >= 5.0:
            self._m1.update(self._uncounted / 5.0)
            self._uncounted = 0
            self._last_tick += 5.0

    @property
    def count(self) -> int:
        return self._count.count

    @property
    def mean_rate(self) -> float:
        elapsed = self._clock() - self._start
        return self._count.count / elapsed if elapsed > 0 else 0.0

    @property
    def one_minute_rate(self) -> float:
        # tick on read too, so an idle meter decays (reference MeterMetric
        # ticks in the getter as well as in mark)
        with self._lock:
            self._tick_locked()
            return self._m1.value


class MetricsRegistry:
    """Named metrics, for stats APIs (_nodes/stats analog)."""

    def __init__(self):
        from . import race_guard
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = race_guard.guarded_dict(
            self._lock, "metrics.MetricsRegistry._metrics")

    def counter(self, name: str) -> CounterMetric:
        return self._get(name, CounterMetric)

    def mean(self, name: str) -> MeanMetric:
        return self._get(name, MeanMetric)

    def meter(self, name: str) -> MeterMetric:
        return self._get(name, MeterMetric)

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric [{name}] already registered as {type(m).__name__}")
            return m

    def snapshot(self) -> dict:
        out = {}
        # under _lock: a concurrent _get() inserting a new metric while
        # this iterates would raise RuntimeError mid-stats (the metric
        # objects themselves serialize their own reads)
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            if isinstance(m, CounterMetric):
                out[name] = m.count
            elif isinstance(m, MeanMetric):
                out[name] = {"count": m.count, "sum": m.sum, "mean": m.mean}
            elif isinstance(m, MeterMetric):
                out[name] = {"count": m.count, "mean_rate": m.mean_rate,
                             "one_minute_rate": m.one_minute_rate}
        return out
