"""Metrics primitives + registry.

Reference analog: common/metrics/ (CounterMetric.java, MeanMetric.java,
EWMA.java, MeterMetric.java). Python counters are GIL-atomic enough for
the host control plane; device-side timing comes from the search executor.
"""

from __future__ import annotations

import math
import threading
import time


class CounterMetric:
    """Monotonic (inc/dec) counter. Ref: common/metrics/CounterMetric.java."""

    __slots__ = ("_count", "_lock")

    def __init__(self):
        self._count = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._count += n

    def dec(self, n: int = 1) -> None:
        with self._lock:
            self._count -= n

    @property
    def count(self) -> int:
        return self._count


class HighWaterMetric:
    """High-water-mark gauge: record() keeps the max ever seen — ints
    (the dispatch scheduler's in-flight pipeline depth) or floats (the
    resident loop's staged-feed overlap in ms)."""

    __slots__ = ("_max", "_last", "_lock")

    def __init__(self):
        self._max = 0
        self._last = 0
        self._lock = threading.Lock()

    def record(self, value: int | float) -> None:
        with self._lock:
            self._last = value
            if value > self._max:
                self._max = value

    @property
    def max(self) -> int | float:
        return self._max

    @property
    def last(self) -> int | float:
        return self._last


class MeanMetric:
    """Sum + count -> mean. Ref: common/metrics/MeanMetric.java."""

    __slots__ = ("_sum", "_count", "_lock")

    def __init__(self):
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def inc(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0


class EWMA:
    """Exponentially-weighted moving average. Ref: common/metrics/EWMA.java."""

    def __init__(self, alpha: float = 0.3, initial: float = 0.0):
        self.alpha = alpha
        self._value = initial
        self._initialized = False

    def update(self, sample: float) -> None:
        if not self._initialized:
            self._value = sample
            self._initialized = True
        else:
            self._value += self.alpha * (sample - self._value)

    @property
    def value(self) -> float:
        return self._value


class MeterMetric:
    """Events/sec with 1m EWMA. Ref: common/metrics/MeterMetric.java."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._count = CounterMetric()
        self._start = clock()
        self._m1 = EWMA(alpha=1 - math.exp(-5.0 / 60.0))
        self._last_tick = self._start
        self._uncounted = 0
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._count.inc(n)
            self._uncounted += n
            self._tick_locked()

    def _tick_locked(self) -> None:
        now = self._clock()
        while now - self._last_tick >= 5.0:
            self._m1.update(self._uncounted / 5.0)
            self._uncounted = 0
            self._last_tick += 5.0

    @property
    def count(self) -> int:
        return self._count.count

    @property
    def mean_rate(self) -> float:
        elapsed = self._clock() - self._start
        return self._count.count / elapsed if elapsed > 0 else 0.0

    @property
    def one_minute_rate(self) -> float:
        # tick on read too, so an idle meter decays (reference MeterMetric
        # ticks in the getter as well as in mark)
        with self._lock:
            self._tick_locked()
            return self._m1.value


class MetricsRegistry:
    """Named metrics, for stats APIs (_nodes/stats analog)."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> CounterMetric:
        return self._get(name, CounterMetric)

    def mean(self, name: str) -> MeanMetric:
        return self._get(name, MeanMetric)

    def meter(self, name: str) -> MeterMetric:
        return self._get(name, MeterMetric)

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric [{name}] already registered as {type(m).__name__}")
            return m

    def snapshot(self) -> dict:
        out = {}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, CounterMetric):
                out[name] = m.count
            elif isinstance(m, MeanMetric):
                out[name] = {"count": m.count, "sum": m.sum, "mean": m.mean}
            elif isinstance(m, MeterMetric):
                out[name] = {"count": m.count, "mean_rate": m.mean_rate,
                             "one_minute_rate": m.one_minute_rate}
        return out
