"""Named thread pools with stats.

Reference analog: threadpool/ThreadPool.java:65-127 — 15 named pools
isolating task classes (search, index, bulk, get, refresh, flush,
management, snapshot, ...) with fixed/scaling policies and bounded
queues.

TPU-native proportions: the device executes search/aggregation work as
single batched programs, so the huge search/bulk pools of the reference
collapse; what remains host-side is IO-ish work (refresh builds, merges,
snapshot uploads, management requests). Pools keep the reference's names
and bounded-queue semantics so the _nodes/stats/thread_pool and
_cat/thread_pool surfaces stay meaningful.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor

from .errors import ElasticsearchTpuError


class EsRejectedExecutionError(ElasticsearchTpuError):
    status = 429


class NamedPool:
    def __init__(self, name: str, size: int, queue_size: int = -1):
        self.name = name
        self.size = size
        self.queue_size = queue_size
        self._exec = ThreadPoolExecutor(max_workers=size,
                                        thread_name_prefix=f"pool-{name}")
        self._lock = threading.Lock()
        self.active = 0
        self.completed = 0
        self.rejected = 0
        self.largest = 0

    def submit(self, fn, *args, **kwargs) -> Future:
        with self._lock:
            queued = self.active - self.size
            if 0 <= self.queue_size <= queued:
                self.rejected += 1
                raise EsRejectedExecutionError(
                    f"rejected execution on thread pool [{self.name}] "
                    f"(queue capacity {self.queue_size})")
            self.active += 1
            self.largest = max(self.largest, self.active)

        def run():
            try:
                return fn(*args, **kwargs)
            finally:
                with self._lock:
                    self.active -= 1
                    self.completed += 1

        return self._exec.submit(run)

    def stats(self) -> dict:
        with self._lock:
            return {"threads": self.size, "queue": max(
                        self.active - self.size, 0),
                    "active": min(self.active, self.size),
                    "rejected": self.rejected,
                    "largest": self.largest,
                    "completed": self.completed}

    def shutdown(self) -> None:
        self._exec.shutdown(wait=False, cancel_futures=True)


class ThreadPoolService:
    """Ref: ThreadPool.java defaults (:112-127), adapted to the device
    execution model (see module docstring)."""

    DEFAULTS = (
        # name, size(threads), bounded queue (-1 = unbounded)
        ("generic", 4, -1),
        ("management", 2, -1),
        ("search", 4, 1000),     # host-side fan-out/merge only
        ("index", 2, 200),
        ("bulk", 2, 50),
        ("get", 2, 1000),
        ("refresh", 1, -1),
        ("flush", 1, -1),
        ("merge", 1, -1),        # ref: optimize pool
        ("snapshot", 1, -1),
        ("warmer", 1, -1),
        ("listener", 1, -1),
    )

    def __init__(self, overrides: dict | None = None):
        self.pools: dict[str, NamedPool] = {}
        for name, size, q in self.DEFAULTS:
            conf = (overrides or {}).get(name, {})
            self.pools[name] = NamedPool(
                name, int(conf.get("size", size)),
                int(conf.get("queue_size", q)))

    def executor(self, name: str) -> NamedPool:
        pool = self.pools.get(name)
        if pool is None:
            raise KeyError(f"no thread pool named [{name}]")
        return pool

    def stats(self) -> dict:
        return {name: p.stats() for name, p in sorted(self.pools.items())}

    def shutdown(self) -> None:
        for p in self.pools.values():
            p.shutdown()
