"""ScriptService: spec parsing, stored scripts, column-bound accessors.

Ref: script/ScriptService.java — inline/indexed(stored)/file script
sources with a compile cache (the cache lives in expression.py), and
the fielddata-backed doc bindings of search/lookup/DocLookup.java.
"""

from __future__ import annotations

from ..utils.errors import ScriptException, ScriptMissingError
from .expression import (CompiledScript, compile_script, DocAccessor,
                         FieldHandle)


def parse_script_spec(spec) -> tuple[str, dict]:
    """Normalize every accepted script shape -> (source, params).

    Accepted: "expr", {"script": ...} unwrapping, {"inline"/"source":
    "expr", "params": {...}, "lang": "expression"}, {"id": "stored"}.
    Ref: script request parsing in ScriptParameterParser.java.
    """
    if isinstance(spec, str):
        return spec, {}
    if not isinstance(spec, dict):
        raise ScriptException(f"invalid script spec {spec!r}")
    if "script" in spec and not any(k in spec for k in ("inline", "source", "id", "file")):
        inner = spec["script"]
        params = dict(spec.get("params") or {})
        if isinstance(inner, str):
            return inner, params
        src, p2 = parse_script_spec(inner)
        params.update(p2)
        return src, params
    src = spec.get("inline") or spec.get("source")
    if src is None and "id" in spec:
        src = ScriptService.instance().get_stored(spec["id"])
    if src is None and "file" in spec:
        src = ScriptService.instance().file_scripts.get(str(spec["file"]))
        if src is None:
            raise ScriptMissingError(str(spec["file"]))
    if src is None:
        raise ScriptException(f"no script source in {spec!r}")
    lang = spec.get("lang", "expression")
    if lang not in SUPPORTED_LANGS:
        # ref: ScriptService.java "script_lang not supported [x]"
        raise ScriptException(f"script_lang not supported [{lang}]")
    return src, dict(spec.get("params") or {})


# the groovy sources the reference's suites use are a subset the
# expression engine compiles directly (assignments, ctx._source,
# arithmetic, doc['f'].value) — see script/expression.py; "painless"
# rides the same subset. mustache = search templates.
SUPPORTED_LANGS = ("expression", "expressions", "painless", "groovy",
                   "mustache")


def numeric_param(name: str, val) -> float:
    """Device-executed scripts (script query/score/sort) carry params as
    f32 operands of the jitted program; non-numeric params are a 400."""
    try:
        return float(val)
    except (TypeError, ValueError):
        raise ScriptException(
            f"script params must be numeric for device execution; "
            f"[{name}] is {type(val).__name__}")


class ScriptService:
    """Stored-script registry (ES 2.0 kept these in the `.scripts`
    index — ScriptService.java indexed scripts). Process-global,
    shared by all nodes in this process; a node with a data path
    persists the registry to scripts.json and reloads it at startup
    (Node._load_stored_scripts)."""

    _instance: "ScriptService | None" = None

    def __init__(self):
        self.stored: dict[str, str] = {}
        # per-script lang + version (the .scripts doc metadata)
        self.meta: dict[str, dict] = {}
        # file scripts (ref: config/scripts dir, hot-reloaded via the
        # resource watcher — Node._watch_file_scripts)
        self.file_scripts: dict[str, str] = {}

    @classmethod
    def instance(cls) -> "ScriptService":
        if cls._instance is None:
            cls._instance = ScriptService()
        return cls._instance

    def put_stored(self, script_id: str, source: str) -> None:
        # stored entries are either expressions or mustache search
        # templates (ref: .scripts index holds both; template lang is
        # detected by shape — JSON/placeholder sources skip expression
        # validation)
        src = source.strip()
        if not (src.startswith("{") or "{{" in src):
            compile_script(source)  # validate at store time
        self.stored[script_id] = source
        cur = self.meta.get(script_id)
        self.meta[script_id] = (
            {"lang": cur["lang"], "version": cur["version"] + 1}
            if cur else {"lang": "expression", "version": 1})

    def get_stored(self, script_id: str) -> str:
        src = self.stored.get(script_id)
        if src is None:
            raise ScriptMissingError(script_id)
        return src

    def delete_stored(self, script_id: str) -> bool:
        self.meta.pop(script_id, None)
        return self.stored.pop(script_id, None) is not None

    # -- versioned indexed scripts (the .scripts-index analog) ---------
    # Ref: ScriptService.java indexed scripts ride normal index/get/
    # delete semantics — versions, version_type external/external_gte/
    # force — against the `.scripts` index.

    def put_versioned(self, script_id: str, source: str, lang: str,
                      version: int | None = None,
                      version_type: str = "internal") -> tuple[int, bool]:
        """-> (new version, created)."""
        if lang not in SUPPORTED_LANGS:
            raise ScriptException(f"script_lang not supported [{lang}]")
        src = source.strip()
        if lang != "mustache" and not (src.startswith("{")
                                       or "{{" in src):
            try:
                compile_script(source)
            except ScriptException as e:
                raise ScriptException(
                    f"Unable to parse [{source}] lang [{lang}]: {e}")
        # one id = one document; lang is its type attribute. A put under
        # a DIFFERENT lang replaces the doc with a fresh version stream
        # (so the write side agrees with get/delete, which treat a lang
        # mismatch as "document absent")
        meta = self.meta.get(script_id)
        cur = meta["version"] if meta and meta["lang"] == lang else None
        new_v = self._write_version(script_id, cur, version, version_type)
        self.stored[script_id] = source
        self.meta[script_id] = {"lang": lang, "version": new_v}
        return new_v, cur is None

    @staticmethod
    def _write_version(script_id: str, cur: int | None,
                       version: int | None, version_type: str) -> int:
        from ..utils.errors import VersionConflictError
        if version_type == "external":
            if version is None:
                raise ScriptException(
                    "version_type [external] requires an explicit version")
            if cur is not None and version <= cur:
                raise VersionConflictError(".scripts", script_id, cur,
                                           version)
            return version
        if version_type == "external_gte":
            if version is None:
                raise ScriptException(
                    "version_type [external_gte] requires an explicit "
                    "version")
            if cur is not None and version < cur:
                raise VersionConflictError(".scripts", script_id, cur,
                                           version)
            return version
        if version_type == "force":
            return version if version is not None else (cur or 0) + 1
        # internal: optimistic equality on the current version
        if version is not None and cur is not None and version != cur:
            raise VersionConflictError(".scripts", script_id, cur, version)
        return (cur or 0) + 1

    def check_read_version(self, script_id: str,
                           version: int | None,
                           version_type: str = "internal") -> None:
        from ..utils.errors import VersionConflictError
        if version is None or version_type == "force":
            return
        cur = self.meta.get(script_id, {}).get("version")
        if cur is None:
            return
        if version_type == "external_gte":
            # reads require current >= expected (VersionType.EXTERNAL_GTE
            # isVersionConflictForReads)
            if cur < version:
                raise VersionConflictError(".scripts", script_id, cur,
                                           version)
        elif version != cur:  # internal + external read = equality
            raise VersionConflictError(".scripts", script_id, cur, version)

    def get_meta(self, script_id: str) -> dict | None:
        """{"source", "lang", "version"} or None."""
        src = self.stored.get(script_id)
        if src is None:
            return None
        m = self.meta.get(script_id, {"lang": "expression", "version": 1})
        return {"source": src, **m}

    def delete_versioned(self, script_id: str,
                         version: int | None = None,
                         version_type: str = "internal") -> int | None:
        """Returns the tombstone version, or None when absent."""
        cur = self.meta.get(script_id, {}).get("version")
        if script_id not in self.stored:
            return None
        new_v = self._write_version(script_id, cur, version, version_type)
        self.stored.pop(script_id, None)
        self.meta.pop(script_id, None)
        return new_v


class SegmentDocAccessor(DocAccessor):
    """Host backend: doc['f'] for ONE doc of a host Segment.

    Numeric fields give float/int values; keyword fields the term
    string; missing fields an empty handle with value 0 (ES fielddata
    missing-as-0 expression semantics).
    """

    def __init__(self, segment, local_doc: int):
        self.seg = segment
        self.d = local_doc

    def get(self, field: str) -> FieldHandle:
        seg, d = self.seg, self.d
        nc = seg.numerics.get(field)
        if nc is not None:
            if not nc.exists[d]:
                return FieldHandle(0.0, True, 0)
            raw = nc.raw[d]
            v = int(raw) if nc.raw.dtype.kind == "i" else float(raw)
            if nc.kind == "date":
                v = int(raw)  # epoch millis, like doc['date'].value in ES
            return FieldHandle(v, False, 1)
        kc = seg.keywords.get(field) or seg.keywords.get(f"{field}.keyword")
        if kc is not None:
            o = int(kc.ords[d])
            if o < 0:
                return FieldHandle("", True, 0)
            return FieldHandle(kc.terms[o], False, 1)
        gc = getattr(seg, "geos", {}).get(field) if hasattr(seg, "geos") else None
        if gc is not None and gc.exists[d]:
            return FieldHandle(None, False, 1, lat=float(gc.lat[d]),
                               lon=float(gc.lon[d]))
        return FieldHandle(0.0, True, 0)


class ColumnDocAccessor(DocAccessor):
    """Device backend: doc['f'] -> the WHOLE column as a [cap] jax
    array (broadcasts against [B,1] params inside the jitted segment
    program). Missing docs read 0.0 like Lucene-expressions bindings."""

    def __init__(self, seg_dev: dict, xp):
        self.seg = seg_dev
        self.xp = xp

    def get(self, field: str) -> FieldHandle:
        num = self.seg.get("num", {}).get(field)
        if num is not None:
            # script_vals = natural units (dates epoch-millis, ip
            # unbiased); see executor.device_arrays
            vals = num.get("script_vals", num["values"]).astype(self.xp.float32)
            exists = num["exists"]
            return FieldHandle(self.xp.where(exists, vals, 0.0), ~exists)
        geo = self.seg.get("geo", {}).get(field)
        if geo is not None:
            return FieldHandle(None, ~geo["exists"],
                               lat=geo["lat"], lon=geo["lon"])
        # absent column: constant 0 / empty=True
        return FieldHandle(0.0, True)


def run_field_script(script: CompiledScript, segment, local_doc: int,
                     params: dict, score: float | None = None):
    """Evaluate a script host-side for one hit (script_fields, sort
    fallback). Returns a python value."""
    bindings = {}
    if score is not None:
        bindings["_score"] = score
    return script.run(doc=SegmentDocAccessor(segment, local_doc),
                      params=params, bindings=bindings)
