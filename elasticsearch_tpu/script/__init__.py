"""Scripting subsystem (ref: script/ScriptService.java).

`expression.py` holds the language; this module holds the service
(stored-script registry, script-spec parsing) and the doc accessors
binding scripts to segment columns on each backend.
"""

from .expression import (CompiledScript, compile_script, DocAccessor,
                         FieldHandle, referenced_fields)
from .service import (ScriptService, parse_script_spec, SegmentDocAccessor,
                      ColumnDocAccessor, run_field_script)

__all__ = [
    "CompiledScript", "compile_script", "DocAccessor", "FieldHandle",
    "referenced_fields", "ScriptService", "parse_script_spec",
    "SegmentDocAccessor", "ColumnDocAccessor", "run_field_script",
]
