"""Safe script/expression engine — the TPU framework's ScriptService core.

Reference analog: script/ScriptService.java (compile cache, pluggable
langs) with the *expression* language modeled on Lucene expressions +
a restricted statement layer for update scripts (the Groovy analog,
ref: script/groovy/GroovyScriptEngineService.java). There is no
arbitrary code execution: scripts parse to a closed AST evaluated by a
tree-walking interpreter; the only callables are a whitelisted math
table.

The same AST evaluates on TWO backends:
  * device  — variables bind to jax arrays (whole doc-value columns),
              operators trace through jnp, the ternary becomes
              `jnp.where`; this is how `script_score`, script filters
              and script sorts run INSIDE the jitted segment program.
  * host    — variables bind to python scalars/dicts (one doc at a
              time) for script_fields, update scripts and
              scripted_metric aggs.

Grammar (C-like, as in Lucene expressions):
  program   := stmt (';' stmt)* — statements only used by update scripts
  stmt      := target ('='|'+='|'-='|'*='|'/=') expr | expr
  expr      := ternary;  ternary := or ('?' expr ':' expr)?
  or/and    := && ||;  cmp := == != < <= > >=;  add/mul := + - * / %
  unary     := '-' | '!';  postfix := '.' name | '[' expr ']' | call
  primary   := number | 'string' | name | '(' expr ')'
Doc access: doc['field'].value / .empty / .length / .lat / .lon,
_score, _value, params.x or bare param names, ctx._source.field.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..utils.errors import ScriptException

# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_PUNCT2 = ("==", "!=", "<=", ">=", "&&", "||", "+=", "-=", "*=", "/=")
_PUNCT1 = "+-*/%()[].,;?:<>!=&|"


@dataclass
class Tok:
    kind: str   # num | str | name | punct | eof
    val: object
    pos: int


def tokenize(src: str) -> list[Tok]:
    toks: list[Tok] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c.isspace():
            i += 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            while j < n and (src[j].isdigit() or src[j] in ".eE" or
                             (src[j] in "+-" and src[j - 1] in "eE")):
                j += 1
            text = src[i:j]
            try:
                val = int(text)
            except ValueError:
                try:
                    val = float(text)
                except ValueError:
                    raise ScriptException(f"bad number [{text}] at {i}")
            toks.append(Tok("num", val, i))
            i = j
            continue
        if c in "'\"":
            j = i + 1
            while j < n and src[j] != c:
                j += 1
            if j >= n:
                raise ScriptException(f"unterminated string at {i}")
            toks.append(Tok("str", src[i + 1:j], i))
            i = j + 1
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            toks.append(Tok("name", src[i:j], i))
            i = j
            continue
        if src[i:i + 2] in _PUNCT2:
            toks.append(Tok("punct", src[i:i + 2], i))
            i += 2
            continue
        if c in _PUNCT1:
            toks.append(Tok("punct", c, i))
            i += 1
            continue
        raise ScriptException(f"unexpected character [{c}] at {i}")
    toks.append(Tok("eof", None, n))
    return toks


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Num:
    value: float


@dataclass(frozen=True)
class Str:
    value: str


@dataclass(frozen=True)
class Var:
    name: str


@dataclass(frozen=True)
class Attr:
    obj: object
    name: str


@dataclass(frozen=True)
class Index:
    obj: object
    key: object


@dataclass(frozen=True)
class Call:
    fn: object          # Var or Attr (Math.log)
    args: tuple


@dataclass(frozen=True)
class Unary:
    op: str
    x: object


@dataclass(frozen=True)
class Bin:
    op: str
    a: object
    b: object


@dataclass(frozen=True)
class Ternary:
    cond: object
    a: object
    b: object


@dataclass(frozen=True)
class Assign:
    target: object      # Var | Attr | Index
    op: str             # = += -= *= /=
    value: object


@dataclass(frozen=True)
class Block:
    stmts: tuple


class Parser:
    def __init__(self, src: str):
        self.toks = tokenize(src)
        self.i = 0

    def peek(self) -> Tok:
        return self.toks[self.i]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, val: str) -> None:
        t = self.next()
        if t.kind != "punct" or t.val != val:
            raise ScriptException(f"expected [{val}] at {t.pos}, got [{t.val}]")

    def parse_program(self):
        stmts = [self.parse_stmt()]
        while self.peek().kind == "punct" and self.peek().val == ";":
            self.next()
            if self.peek().kind == "eof":
                break
            stmts.append(self.parse_stmt())
        t = self.peek()
        if t.kind != "eof":
            raise ScriptException(f"unexpected [{t.val}] at {t.pos}")
        return stmts[0] if len(stmts) == 1 else Block(tuple(stmts))

    def parse_stmt(self):
        expr = self.parse_expr()
        t = self.peek()
        if t.kind == "punct" and t.val in ("=", "+=", "-=", "*=", "/="):
            self.next()
            if not isinstance(expr, (Var, Attr, Index)):
                raise ScriptException(f"invalid assignment target at {t.pos}")
            return Assign(expr, t.val, self.parse_expr())
        return expr

    def parse_expr(self):
        cond = self.parse_or()
        if self.peek().kind == "punct" and self.peek().val == "?":
            self.next()
            a = self.parse_expr()
            self.expect(":")
            b = self.parse_expr()
            return Ternary(cond, a, b)
        return cond

    def _binop(self, sub, ops):
        node = sub()
        while self.peek().kind == "punct" and self.peek().val in ops:
            op = self.next().val
            node = Bin(op, node, sub())
        return node

    def parse_or(self):
        return self._binop(self.parse_and, ("||",))

    def parse_and(self):
        return self._binop(self.parse_cmp, ("&&",))

    def parse_cmp(self):
        return self._binop(self.parse_add, ("==", "!=", "<", "<=", ">", ">="))

    def parse_add(self):
        return self._binop(self.parse_mul, ("+", "-"))

    def parse_mul(self):
        return self._binop(self.parse_unary, ("*", "/", "%"))

    def parse_unary(self):
        t = self.peek()
        if t.kind == "punct" and t.val in ("-", "!"):
            self.next()
            return Unary(t.val, self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self):
        node = self.parse_primary()
        while True:
            t = self.peek()
            if t.kind != "punct":
                return node
            if t.val == ".":
                self.next()
                name = self.next()
                if name.kind != "name":
                    raise ScriptException(f"expected name after '.' at {name.pos}")
                node = Attr(node, name.val)
            elif t.val == "[":
                self.next()
                key = self.parse_expr()
                self.expect("]")
                node = Index(node, key)
            elif t.val == "(":
                self.next()
                args = []
                if not (self.peek().kind == "punct" and self.peek().val == ")"):
                    args.append(self.parse_expr())
                    while self.peek().kind == "punct" and self.peek().val == ",":
                        self.next()
                        args.append(self.parse_expr())
                self.expect(")")
                node = Call(node, tuple(args))
            else:
                return node

    def parse_primary(self):
        t = self.next()
        if t.kind == "num":
            return Num(float(t.val))
        if t.kind == "str":
            return Str(t.val)
        if t.kind == "name":
            return Var(t.val)
        if t.kind == "punct" and t.val == "(":
            e = self.parse_expr()
            self.expect(")")
            return e
        raise ScriptException(f"unexpected token [{t.val}] at {t.pos}")


# ---------------------------------------------------------------------------
# Interpreter
# ---------------------------------------------------------------------------

_MATH1 = {
    "abs": abs, "ceil": math.ceil, "floor": math.floor, "exp": math.exp,
    "log": math.log, "ln": math.log, "log10": math.log10,
    "log2": lambda x: math.log2(x), "sqrt": math.sqrt, "sin": math.sin,
    "cos": math.cos, "tan": math.tan, "asin": math.asin, "acos": math.acos,
    "atan": math.atan, "sinh": math.sinh, "cosh": math.cosh,
    "tanh": math.tanh, "signum": lambda x: (x > 0) - (x < 0),
    "round": round, "log1p": math.log1p,
}
_MATH2 = {"pow": pow, "atan2": math.atan2, "min": min, "max": max,
          "hypot": math.hypot, "fmod": math.fmod}

# device (xp = jnp / np array) variants — name -> attr on xp
_XP1 = {"abs": "abs", "ceil": "ceil", "floor": "floor", "exp": "exp",
        "log": "log", "ln": "log", "log10": "log10", "log2": "log2",
        "sqrt": "sqrt", "sin": "sin", "cos": "cos", "tan": "tan",
        "asin": "arcsin", "acos": "arccos", "atan": "arctan",
        "sinh": "sinh", "cosh": "cosh", "tanh": "tanh", "signum": "sign",
        "round": "round", "log1p": "log1p"}
_XP2 = {"pow": "power", "atan2": "arctan2", "min": "minimum",
        "max": "maximum", "hypot": "hypot", "fmod": "fmod"}


class DocAccessor:
    """`doc['field']` handle. Host backend: per-doc scalars; device
    backend: whole columns. Subclasses implement value/empty/length."""

    def get(self, field: str):  # -> object with .value/.empty
        raise NotImplementedError


class FieldHandle:
    __slots__ = ("value", "empty", "length", "lat", "lon")

    def __init__(self, value, empty, length=None, lat=None, lon=None):
        self.value = value
        self.empty = empty
        if length is None:
            # derive from `empty`: 0 when missing, 1 when present —
            # elementwise for device arrays
            if hasattr(empty, "dtype"):
                length = 1 - empty.astype("int32")
            else:
                length = 0 if empty else 1
        self.length = length
        # geo accessors read 0.0 when absent (same missing-as-zero rule
        # as .value) so scripts never see None
        self.lat = 0.0 if lat is None else lat
        self.lon = 0.0 if lon is None else lon


class Env:
    """Variable bindings for one evaluation."""

    def __init__(self, doc: DocAccessor | None = None, params: dict | None = None,
                 bindings: dict | None = None, xp=None):
        self.doc = doc
        self.params = params or {}
        self.bindings = bindings or {}
        self.locals: dict[str, object] = {}
        self.xp = xp  # None = pure-host scalars; np/jnp = array backend

    def lookup(self, name: str):
        if name in self.locals:
            return self.locals[name]
        if name in self.bindings:
            return self.bindings[name]
        if name == "doc":
            if self.doc is None:
                raise ScriptException("doc values are not available in this context")
            return self.doc
        if name == "params":
            return self.params
        if name in self.params:
            return self.params[name]
        if name in ("Math", "math"):
            return _MATH_NS
        if name == "true":
            return True
        if name == "false":
            return False
        if name == "null":
            return None
        if name == "PI":
            return math.pi
        if name == "E":
            return math.e
        raise ScriptException(f"unknown variable [{name}]")


_MATH_NS = object()  # sentinel: Math.* namespace


def _truthy(v, xp):
    if xp is not None and hasattr(v, "dtype"):
        return v if v.dtype == bool else (v != 0)
    if isinstance(v, bool):
        return v
    if v is None:
        return False
    if isinstance(v, (int, float)):
        return v != 0
    return bool(v)


def _num(v):
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    return v


def evaluate(node, env: Env):
    xp = env.xp
    if isinstance(node, Num):
        return node.value
    if isinstance(node, Str):
        return node.value
    if isinstance(node, Var):
        return env.lookup(node.name)
    if isinstance(node, Attr):
        obj = evaluate(node.obj, env)
        if obj is _MATH_NS:
            if node.name in ("PI",):
                return math.pi
            if node.name in ("E",):
                return math.e
            return ("__mathfn__", node.name)
        if isinstance(obj, FieldHandle):
            v = getattr(obj, node.name, None)
            if v is None and node.name not in ("lat", "lon"):
                raise ScriptException(f"unknown doc-field property [{node.name}]")
            return v
        if isinstance(obj, DocAccessor):
            return obj.get(node.name)
        if isinstance(obj, dict):
            return obj.get(node.name)
        raise ScriptException(f"cannot access [.{node.name}]")
    if isinstance(node, Index):
        obj = evaluate(node.obj, env)
        key = evaluate(node.key, env)
        if isinstance(obj, DocAccessor):
            return obj.get(str(key))
        if isinstance(obj, dict):
            return obj.get(key)
        if isinstance(obj, (list, tuple)):
            return obj[int(key)]
        raise ScriptException("cannot index this value")
    if isinstance(node, Call):
        return _call(node, env)
    if isinstance(node, Unary):
        v = evaluate(node.x, env)
        if node.op == "-":
            return -_num(v)
        t = _truthy(v, xp)
        if xp is not None and hasattr(t, "dtype"):
            return ~t
        return not t
    if isinstance(node, Bin):
        return _binop(node, env)
    if isinstance(node, Ternary):
        c = _truthy(evaluate(node.cond, env), xp)
        if xp is not None and hasattr(c, "dtype"):
            return xp.where(c, evaluate(node.a, env), evaluate(node.b, env))
        return evaluate(node.a, env) if c else evaluate(node.b, env)
    if isinstance(node, Assign):
        return _assign(node, env)
    if isinstance(node, Block):
        out = None
        for s in node.stmts:
            out = evaluate(s, env)
        return out
    raise ScriptException(f"cannot evaluate node {node!r}")


def _call(node: Call, env: Env):
    fn = node.fn
    args = [evaluate(a, env) for a in node.args]
    name = None
    if isinstance(fn, Var):
        name = fn.name
    else:
        v = evaluate(fn, env)
        if isinstance(v, tuple) and len(v) == 2 and v[0] == "__mathfn__":
            name = v[1]
        elif callable(v):
            raise ScriptException("only math functions are callable")
    if name is None:
        raise ScriptException("unknown function")
    name_l = name
    xp = env.xp
    arrayish = xp is not None and any(hasattr(a, "dtype") for a in args)
    if len(args) == 1 and name_l in _MATH1:
        if arrayish:
            return getattr(xp, _XP1[name_l])(args[0])
        return _MATH1[name_l](_num(args[0]))
    if len(args) == 2 and name_l in _MATH2:
        if arrayish:
            return getattr(xp, _XP2[name_l])(args[0], args[1])
        return _MATH2[name_l](_num(args[0]), _num(args[1]))
    raise ScriptException(f"unknown function [{name}/{len(args)}]")


def _binop(node: Bin, env: Env):
    op = node.op
    xp = env.xp
    if op == "&&":
        a = _truthy(evaluate(node.a, env), xp)
        if xp is not None and hasattr(a, "dtype"):
            return a & _truthy(evaluate(node.b, env), xp)
        return bool(a) and bool(_truthy(evaluate(node.b, env), xp))
    if op == "||":
        a = _truthy(evaluate(node.a, env), xp)
        if xp is not None and hasattr(a, "dtype"):
            return a | _truthy(evaluate(node.b, env), xp)
        return bool(a) or bool(_truthy(evaluate(node.b, env), xp))
    a = evaluate(node.a, env)
    b = evaluate(node.b, env)
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if isinstance(a, str) or isinstance(b, str):
        if op == "+":
            return str(a) + str(b)
        if op in ("<", "<=", ">", ">="):
            pass  # fall through to comparisons below (string order)
        else:
            raise ScriptException(f"cannot apply [{op}] to strings")
    else:
        a = _num(a)
        b = _num(b)
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b
    if op == "%":
        # Java remainder semantics (sign of dividend) on both backends
        if xp is not None and (hasattr(a, "dtype") or hasattr(b, "dtype")):
            return xp.fmod(a, b)
        return math.fmod(a, b)
    try:
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
    except TypeError:
        raise ScriptException(
            f"cannot compare [{type(a).__name__}] with "
            f"[{type(b).__name__}] using [{op}]")
    raise ScriptException(f"unknown operator [{op}]")


def _assign(node: Assign, env: Env):
    val = evaluate(node.value, env)
    tgt = node.target
    if node.op != "=":
        cur = evaluate(tgt, env)
        binop = node.op[0]
        val = _binop(Bin(binop, _Const(cur), _Const(val)), env)
    if isinstance(tgt, Var):
        env.locals[tgt.name] = val
        return val
    # resolve container then set
    obj = evaluate(tgt.obj, env)
    if isinstance(tgt, Attr):
        if isinstance(obj, dict):
            obj[tgt.name] = val
            return val
        raise ScriptException(f"cannot assign [.{tgt.name}]")
    key = evaluate(tgt.key, env)
    if isinstance(obj, dict):
        obj[key] = val
        return val
    if isinstance(obj, list):
        obj[int(key)] = val
        return val
    raise ScriptException("cannot assign to this target")


@dataclass(frozen=True)
class _Const:
    """Pre-evaluated value wrapped as an AST node (compound assignment)."""
    value: object


# teach evaluate about _Const without a big if-chain rewrite
_orig_evaluate = evaluate


def evaluate(node, env: Env):  # noqa: F811
    if isinstance(node, _Const):
        return node.value
    return _orig_evaluate(node, env)


# ---------------------------------------------------------------------------
# Compiled script + field extraction
# ---------------------------------------------------------------------------


def referenced_fields(node) -> set[str]:
    """doc['field'] / doc.field references found in the AST."""
    out: set[str] = set()

    def walk(n):
        if isinstance(n, Index) and isinstance(n.obj, Var) and n.obj.name == "doc":
            if isinstance(n.key, Str):
                out.add(n.key.value)
        if isinstance(n, Attr) and isinstance(n.obj, Var) and n.obj.name == "doc":
            out.add(n.name)
        for f in getattr(n, "__dataclass_fields__", {}):
            v = getattr(n, f)
            if isinstance(v, tuple):
                for x in v:
                    walk(x) if hasattr(x, "__dataclass_fields__") else None
            elif hasattr(v, "__dataclass_fields__"):
                walk(v)

    walk(node)
    return out


def uses_score(node) -> bool:
    found = False

    def walk(n):
        nonlocal found
        if isinstance(n, Var) and n.name == "_score":
            found = True
        for f in getattr(n, "__dataclass_fields__", {}):
            v = getattr(n, f)
            if isinstance(v, tuple):
                for x in v:
                    if hasattr(x, "__dataclass_fields__"):
                        walk(x)
            elif hasattr(v, "__dataclass_fields__"):
                walk(v)

    walk(node)
    return found


class CompiledScript:
    """Parsed script ready to run against any backend."""

    def __init__(self, source: str):
        self.source = source
        self.ast = Parser(source).parse_program()
        self.fields = frozenset(referenced_fields(self.ast))
        self.needs_score = uses_score(self.ast)

    def run(self, *, doc: DocAccessor | None = None, params: dict | None = None,
            bindings: dict | None = None, xp=None):
        env = Env(doc=doc, params=params, bindings=bindings, xp=xp)
        return evaluate(self.ast, env)


_COMPILE_CACHE: dict[str, CompiledScript] = {}


def compile_script(source: str) -> CompiledScript:
    """Compile with caching (ref: ScriptService compile cache,
    script/ScriptService.java:220-239)."""
    cs = _COMPILE_CACHE.get(source)
    if cs is None:
        if len(_COMPILE_CACHE) > 500:
            # graftlint: ok(trace-purity): bounded memo keyed on the
            # STATIC script source — trace-time population is idempotent
            _COMPILE_CACHE.clear()
        cs = CompiledScript(source)
        # graftlint: ok(trace-purity): same memo as above — a retrace
        # recomputes the identical CompiledScript for the same key
        _COMPILE_CACHE[source] = cs
    return cs
