"""Document mapping: schema, field types, document parsing, dynamic mapping.

Reference analog: index/mapper/ (MapperService.java, DocumentMapper.java,
DocumentMapperParser.java, core/ type mappers, internal/ metadata fields).

TPU-first deviation: a parsed document does not become a Lucene Document;
it becomes columnar contributions — term lists per analyzed text field,
ordinal values per keyword field, numeric/date/bool doc values — that the
segment builder (index/segment.py) packs into device tensors. Metadata
fields collapse to what the columnar engine needs: _id (host dict),
_source (host bytes), _version (host int array); _field_names becomes the
per-column exists bitmask.
"""

from __future__ import annotations

import datetime as _dt
import json
import numbers
import re
from dataclasses import dataclass, field

from ..utils.errors import MapperParsingError, IllegalArgumentError
from ..utils.settings import Settings
from .analysis import AnalysisService, Analyzer

# ---------------------------------------------------------------------------
# Field types
# ---------------------------------------------------------------------------

TEXT = "text"          # analyzed full-text -> postings (reference: string/analyzed)
KEYWORD = "keyword"    # not-analyzed -> ordinal column (reference: string/not_analyzed)
LONG = "long"
INTEGER = "integer"
SHORT = "short"
BYTE = "byte"
DOUBLE = "double"
FLOAT = "float"
DATE = "date"
BOOLEAN = "boolean"
IP = "ip"

DENSE_VECTOR = "dense_vector"  # [dims] float embedding -> device matrix
GEO_POINT = "geo_point"        # (lat, lon) -> two float32 device columns
                               # (ref: index/mapper/geo/GeoPointFieldMapper)
                               # (MXU-batched exact kNN; no CPU-era ANN
                               # graph needed at these batch sizes)
GEO_SHAPE = "geo_shape"        # GeoJSON shapes -> prefix-tree cell tokens
                               # in standard postings (ops/geo_shape.py;
                               # ref: index/mapper/geo/GeoShapeFieldMapper)

NUMERIC_TYPES = {LONG, INTEGER, SHORT, BYTE, DOUBLE, FLOAT}
JOIN = "join"                  # parent/child relation column (replaces the
                               # reference's per-type _parent metadata field,
                               # index/mapper/internal/ParentFieldMapper.java;
                               # modern join-field shape since this framework
                               # is single-doc-type)

COMPLETION = "completion"      # suggest dictionary entries: host-resident
                               # per-segment input->entry lists (ref:
                               # index/mapper/core/CompletionFieldMapper.java
                               # + the FST-backed
                               # search/suggest/completion/ postings format;
                               # suggest never touches the device)

ALL_TYPES = NUMERIC_TYPES | {TEXT, KEYWORD, DATE, BOOLEAN, IP, DENSE_VECTOR,
                             GEO_POINT, GEO_SHAPE, JOIN, COMPLETION}

# reference "string" type maps by `index` attribute (analyzed|not_analyzed),
# ref: index/mapper/core/StringFieldMapper.java
_LEGACY_STRING = "string"

_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)

_DATE_FORMATS = (
    "%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z",
    "%Y-%m-%dT%H:%M:%S.%f", "%Y-%m-%dT%H:%M:%S",
    "%Y-%m-%d %H:%M:%S", "%Y-%m-%d", "%d/%b/%Y:%H:%M:%S %z",
)


def parse_date_millis(value) -> int:
    """Parse a date value to epoch millis.

    Ref: index/mapper/core/DateFieldMapper.java (joda `dateOptionalTime
    || epoch_millis`). Accepts epoch millis ints, ISO-8601 strings, and
    the common-log format used by the http_logs benchmark corpus.
    """
    if isinstance(value, bool):
        raise MapperParsingError(f"cannot parse boolean [{value}] as date")
    if isinstance(value, numbers.Number):
        return int(value)
    s = str(value).strip()
    if re.fullmatch(r"[+-]?\d{10,}", s):
        return int(s)
    for fmt in _DATE_FORMATS:
        try:
            dt = _dt.datetime.strptime(s, fmt)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return int(dt.timestamp() * 1000)
        except ValueError:
            continue
    raise MapperParsingError(f"failed to parse date value [{value}]")


def format_date_millis(millis: int) -> str:
    dt = _EPOCH + _dt.timedelta(milliseconds=int(millis))
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


_IP_RE = re.compile(r"^(\d{1,3})\.(\d{1,3})\.(\d{1,3})\.(\d{1,3})$")


def parse_ip(value) -> int:
    """IPv4 -> uint32 (stored as a numeric column, like the reference's
    IpFieldMapper which indexes IPs as longs)."""
    if isinstance(value, numbers.Number) and not isinstance(value, bool):
        return int(value)
    m = _IP_RE.match(str(value))
    if not m:
        raise MapperParsingError(f"failed to parse ip [{value}]")
    parts = [int(g) for g in m.groups()]
    if any(p > 255 for p in parts):
        raise MapperParsingError(f"failed to parse ip [{value}]")
    return (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]


def _geo_precision_chars(precision) -> int:
    """Geo-context precision -> geohash length: bare ints are geohash
    chars; distance strings pick the finest level whose cell still covers
    the distance (ref: GeoUtils.geoHashLevelsForPrecision)."""
    if precision is None:
        return 12
    if isinstance(precision, int):
        return max(1, min(12, precision))
    from ..ops.geo import parse_distance
    meters = parse_distance(precision)
    # approximate geohash cell heights in meters per level
    sizes = [5_009_400, 1_252_300, 156_500, 39_100, 4_890, 1_220,
             153, 38, 4.8, 1.2, 0.15, 0.037]
    for level, size in enumerate(sizes, start=1):
        if size <= meters:
            return level
    return 12


def _parse_shape_config(spec: dict) -> dict:
    """geo_shape mapping params -> normalized config (ref:
    GeoShapeFieldMapper.Builder: tree geohash|quadtree, tree_levels or
    precision distance, distance_error_pct default 0.025)."""
    from ..ops.geo_shape import make_tree
    tree_name = str(spec.get("tree", "geohash"))
    tree = make_tree(tree_name)  # validates the name
    cfg: dict = {"tree": tree_name}
    if spec.get("tree_levels") is not None:
        cfg["tree_levels"] = int(spec["tree_levels"])
    elif spec.get("precision") is not None:
        from ..ops.geo import parse_distance
        cfg["precision"] = str(spec["precision"])
        cfg["tree_levels"] = tree.levels_for_meters(
            parse_distance(spec["precision"]))
    else:
        cfg["tree_levels"] = tree.levels_for_meters(50.0)  # default "50m"
    cfg["distance_error_pct"] = float(
        spec.get("distance_error_pct", 0.025))
    return cfg


def shape_tree_config(fm: "FieldMapper"):
    """(tree, tree_levels, distance_error_pct) for a geo_shape field."""
    from ..ops.geo_shape import make_tree
    cfg = fm.shape or {}
    tree = make_tree(cfg.get("tree", "geohash"))
    levels = int(cfg.get("tree_levels") or tree.levels_for_meters(50.0))
    return tree, min(levels, tree.max_levels_cap), \
        float(cfg.get("distance_error_pct", 0.025))


@dataclass
class FieldMapper:
    """One field's schema entry. Ref: index/mapper/FieldMapper.java."""

    name: str
    type: str
    analyzer: str = "standard"
    search_analyzer: str | None = None
    index: bool = True          # ref: "index" attribute (no|analyzed|not_analyzed)
    doc_values: bool = True     # numeric/keyword/date columns resident on device
    store: bool = False
    boost: float = 1.0
    fmt: str | None = None      # date format hint
    ignore_malformed: bool = False
    dims: int | None = None     # dense_vector dimensionality
    similarity: str = "cosine"  # dense_vector: cosine|dot_product|l2_norm;
                                # text: similarity NAME resolved by
                                # index/similarity.py ("" = index default)
    relations: dict | None = None  # join: parent relation -> child(s)
    legacy_string: bool = False    # declared as 2.0 "string": echo it back
    context: dict | None = None    # completion: context mapping config
                                   # (ref: suggest/context/ContextMapping)
    shape: dict | None = None      # geo_shape: {tree, tree_levels,
                                   # precision, distance_error_pct}
                                   # (ref: GeoShapeFieldMapper.Builder)

    def to_dict(self) -> dict:
        if self.legacy_string:
            d: dict = {"type": "string"}
            if self.type == KEYWORD:
                d["index"] = "not_analyzed"
            if self.type == TEXT and self.analyzer != "standard":
                d["analyzer"] = self.analyzer
            if self.boost != 1.0:
                d["boost"] = self.boost
            if self.type == TEXT and self.similarity not in ("", "cosine"):
                d["similarity"] = self.similarity
            return d
        d: dict = {"type": self.type}
        if self.type == TEXT and self.analyzer != "standard":
            d["analyzer"] = self.analyzer
        if self.type == TEXT and self.similarity not in ("", "cosine"):
            d["similarity"] = self.similarity
        if not self.index:
            d["index"] = False
        if self.boost != 1.0:
            d["boost"] = self.boost
        if self.type == DENSE_VECTOR:
            d["dims"] = self.dims
            d["similarity"] = self.similarity
        if self.type == JOIN:
            d["relations"] = self.relations or {}
        if self.type == COMPLETION and self.context:
            d["context"] = self.context
        if self.type == GEO_SHAPE and self.shape:
            d.update(self.shape)
        return d


@dataclass
class ParsedField:
    """Columnar contribution of one field of one document."""

    name: str
    type: str
    tokens: list[str] | None = None   # TEXT: analyzed terms (postings input)
    value: object = None              # KEYWORD: str; numeric/date/bool/ip: number


@dataclass
class ParsedDocument:
    """Ref: index/mapper/ParsedDocument.java — but columnar. `nested`
    carries block-join sub-documents (ref: ParsedDocument.docs() — Lucene
    indexes nested objects as adjacent hidden docs before their parent):
    (path, fields, source_bytes) per nested object occurrence."""

    doc_id: str
    source: bytes
    fields: list[ParsedField] = field(default_factory=list)
    nested: list[tuple] = field(default_factory=list)


class DocumentMapper:
    """Schema for one index: field name -> FieldMapper; parses JSON docs.

    Ref: index/mapper/DocumentMapper.java + DocumentMapperParser.java.
    The reference's per-type mappings (doc types) were removed in later ES;
    we are single-type per index (type name kept only for API compat).
    """

    def __init__(self, analysis: AnalysisService, mapping: dict | None = None,
                 dynamic: bool = True):
        self.analysis = analysis
        self.dynamic = dynamic
        self._fields: dict[str, FieldMapper] = {}
        self._multi_fields: dict[str, list[str]] = {}  # parent -> sub names
        self._nested_paths: set[str] = set()
        self.parent_type: str | None = None
        self.routing_required = False
        self.ts_enabled = False
        self.ttl_enabled = False
        self.ttl_default_ms: int | None = None
        if mapping:
            self._parse_mapping(mapping)

    # -- schema ------------------------------------------------------------
    _META_KEYS = frozenset((
        "dynamic", "properties", "_meta", "_source", "_all", "_routing",
        "_parent", "_timestamp", "_ttl", "_size", "date_detection",
        "numeric_detection", "dynamic_templates", "dynamic_date_formats"))

    def _parse_mapping(self, mapping: dict) -> None:
        if "_timestamp" in mapping and isinstance(mapping["_timestamp"],
                                                  dict):
            # ref: index/mapper/internal/TimestampFieldMapper.java
            self.ts_enabled = bool(mapping["_timestamp"].get("enabled"))
        if "_ttl" in mapping and isinstance(mapping["_ttl"], dict):
            # ref: index/mapper/internal/TTLFieldMapper.java (default
            # ttl applies when the write supplies none)
            self.ttl_enabled = bool(mapping["_ttl"].get("enabled"))
            dflt = mapping["_ttl"].get("default")
            if dflt is not None:
                from ..utils.settings import parse_time_value
                self.ttl_default_ms = parse_time_value(dflt, 0)
        if "_parent" in mapping and isinstance(mapping["_parent"], dict):
            # _parent declares the parent type; children route by parent
            # id (ref: index/mapper/internal/ParentFieldMapper.java)
            self.parent_type = mapping["_parent"].get("type")
        if "_routing" in mapping and isinstance(mapping["_routing"], dict):
            self.routing_required = bool(
                mapping["_routing"].get("required", False))
        if "dynamic" in mapping:
            dyn = mapping["dynamic"]
            if isinstance(dyn, bool):
                self.dynamic = dyn
            elif str(dyn).lower() == "strict":
                self.dynamic = "strict"
            else:
                self.dynamic = str(dyn).lower() != "false"
        if "properties" in mapping:
            props = mapping["properties"]
        else:
            # bare form: treat non-meta keys as field specs
            props = {k: v for k, v in mapping.items() if k not in self._META_KEYS}
        if not isinstance(props, dict):
            raise MapperParsingError("mapping [properties] must be an object")
        for name, spec in props.items():
            self._add_field(name, spec)

    def _add_field(self, name: str, spec: dict) -> FieldMapper:
        if not isinstance(spec, dict):
            raise MapperParsingError(f"mapping for field [{name}] must be an object")
        if spec.get("type") == "nested":
            # nested object: children become block-join sub-documents
            # (ref: index/mapper/object/ObjectMapper.java Nested)
            self._nested_paths.add(name)
            for child, child_spec in (spec.get("properties") or {}).items():
                self._add_field(f"{name}.{child}", child_spec)
            return None  # type: ignore[return-value]
        if "properties" in spec and spec.get("type") in (None, "object"):
            # object field: flatten children as dotted names
            # (ref: index/mapper/object/ObjectMapper.java)
            for child, child_spec in spec["properties"].items():
                self._add_field(f"{name}.{child}", child_spec)
            return None  # type: ignore[return-value]
        typ = spec.get("type")
        if typ == "multi_field":
            # legacy multi_field (ref: index/mapper/core/
            # TypeParsers.parseMultiField legacy path): the sub-field
            # named like the parent is the primary; others are subs
            subs = dict(spec.get("fields") or {})
            primary = subs.pop(name.rsplit(".", 1)[-1], None)
            spec = dict(primary) if primary else {"type": "string"}
            spec["fields"] = subs
            typ = spec.get("type")
        if typ == JOIN and not isinstance(spec.get("relations"), dict):
            raise MapperParsingError(
                f"join field [{name}] requires a [relations] object")
        legacy_string = typ == _LEGACY_STRING
        if legacy_string:
            typ = KEYWORD if spec.get("index") == "not_analyzed" else TEXT
        if typ not in ALL_TYPES:
            raise MapperParsingError(f"no handler for type [{typ}] declared on field [{name}]")
        idx = spec.get("index", True)
        fm = FieldMapper(
            name=name, type=typ,
            analyzer=spec.get("analyzer", "standard"),
            search_analyzer=spec.get("search_analyzer"),
            index=idx not in (False, "no", "none"),
            doc_values=bool(spec.get("doc_values", True)),
            store=bool(spec.get("store", False)),
            boost=float(spec.get("boost", 1.0)),
            fmt=spec.get("format"),
            ignore_malformed=bool(spec.get("ignore_malformed", False)),
            dims=(int(spec["dims"]) if spec.get("dims") is not None else None),
            similarity=str(spec.get("similarity", "cosine")),
            relations=(dict(spec["relations"]) if typ == JOIN else None),
            legacy_string=legacy_string,
            shape=(_parse_shape_config(spec) if typ == GEO_SHAPE else None),
            context=(dict(spec["context"])
                     if typ == COMPLETION and isinstance(
                         spec.get("context"), dict) else None),
        )
        # multi-fields: {"fields": {"keyword": {"type": "keyword"}}} ->
        # sub-mapper at "<name>.<sub>" (ref: core/AbstractFieldMapper multiFields)
        for sub_name, sub_spec in (spec.get("fields") or {}).items():
            sub = self._add_field(f"{name}.{sub_name}", sub_spec)
            if sub is not None:
                self._multi_fields.setdefault(name, []).append(sub.name)
        existing = self._fields.get(name)
        if existing:
            # ref: merge conflict detection, index/mapper/MergeContext.java
            if existing.type != fm.type:
                raise MapperParsingError(
                    f"mapper [{name}] of different type, current_type "
                    f"[{existing.type}], merged_type [{fm.type}]")
            if existing.type == TEXT and existing.analyzer != fm.analyzer:
                raise MapperParsingError(
                    f"mapper [{name}] has different [analyzer]: "
                    f"[{existing.analyzer}] vs [{fm.analyzer}]")
            if existing.type == TEXT:
                # impacts are baked at index time (index/similarity.py),
                # so similarity is as immutable as the analyzer; a re-put
                # that omits it inherits the existing choice ("cosine" is
                # the unset sentinel shared with dense_vector)
                if fm.similarity in ("", "cosine"):
                    fm.similarity = existing.similarity
                else:
                    old = existing.similarity
                    # unset means the engine default; explicitly naming
                    # that default is not a change
                    if old in ("", "cosine"):
                        old = "BM25"
                    if old != fm.similarity and not (
                            old in ("BM25", "bm25")
                            and fm.similarity in ("BM25", "bm25")):
                        raise MapperParsingError(
                            f"mapper [{name}] has different [similarity]")
            if existing.index != fm.index:
                raise MapperParsingError(
                    f"mapper [{name}] has different [index] values")
        self._fields[name] = fm
        if "." in name:
            # a dotted leaf whose parent is itself a leaf field is a
            # multi-field (e.g. "s.keyword" under text "s") — re-link it
            # so values flow from the parent. This matters when mappings
            # round-trip flattened through the cluster-state side channel.
            parent = name.rsplit(".", 1)[0]
            if parent in self._fields:
                links = self._multi_fields.setdefault(parent, [])
                if name not in links:
                    links.append(name)
        return fm

    def merge(self, mapping: dict) -> None:
        """Merge an additional mapping (PUT _mapping); conflicts raise."""
        self._parse_mapping(mapping)

    def field(self, name: str) -> FieldMapper | None:
        return self._fields.get(name)

    @property
    def fields(self) -> dict[str, FieldMapper]:
        return dict(self._fields)

    def to_dict(self) -> dict:
        sub_names = {s for subs in self._multi_fields.values()
                     for s in subs}
        props = {}
        for n, f in sorted(self._fields.items()):
            if n in sub_names:
                continue  # multi-field subs render under parent "fields"
            d = f.to_dict()
            subs = self._multi_fields.get(n)
            if subs:
                d["fields"] = {
                    s.rsplit(".", 1)[-1]: self._fields[s].to_dict()
                    for s in sorted(subs) if s in self._fields}
            props[n] = d
        for path in sorted(self._nested_paths):
            props[path] = {"type": "nested"}
        return {"properties": props}

    # -- document parsing --------------------------------------------------
    def _dynamic_type(self, name: str, value) -> str:
        """Infer a field type from a JSON value.

        Ref: dynamic mapping in index/mapper/object/ObjectMapper.java
        (serializeValue): bool->boolean, int->long, float->double,
        date-parseable string->date, else string(text).
        """
        if isinstance(value, bool):
            return BOOLEAN
        if isinstance(value, int):
            return LONG
        if isinstance(value, float):
            return DOUBLE
        s = str(value)
        try:
            parse_date_millis(s)
            if re.match(r"^\d{4}-\d{2}-\d{2}", s) or re.match(r"^\d{2}/[A-Za-z]{3}/\d{4}", s):
                return DATE
        except MapperParsingError:
            pass
        return TEXT

    def _coerce(self, fm: FieldMapper, value):
        try:
            if fm.type == DATE:
                return parse_date_millis(value)
            if fm.type == BOOLEAN:
                if isinstance(value, bool):
                    return value
                return str(value).lower() in ("true", "1", "on", "yes")
            if fm.type == IP:
                return parse_ip(value)
            if fm.type in (LONG, INTEGER, SHORT, BYTE):
                if isinstance(value, str) and not value.strip().lstrip("+-").isdigit():
                    raise MapperParsingError(
                        f"failed to parse [{fm.name}] as {fm.type}: [{value}]")
                return int(value)
            if fm.type in (DOUBLE, FLOAT):
                return float(value)
        except (ValueError, TypeError):
            raise MapperParsingError(f"failed to parse [{fm.name}] value [{value}]")
        return value

    def parse(self, doc_id: str, source: dict | bytes | str) -> ParsedDocument:
        """JSON document -> columnar field contributions."""
        if isinstance(source, (bytes, str)):
            raw = source if isinstance(source, bytes) else source.encode()
            try:
                obj = json.loads(source)
            except json.JSONDecodeError as e:
                raise MapperParsingError(f"failed to parse document: {e}")
        else:
            obj = source
            raw = json.dumps(source, separators=(",", ":")).encode()
        if not isinstance(obj, dict):
            raise MapperParsingError("document root must be an object")
        out = ParsedDocument(doc_id=doc_id, source=raw)
        self._parse_object("", obj, out)
        self._resolve_completion_contexts(obj, out)
        return out

    def _resolve_completion_contexts(self, obj: dict,
                                     out: ParsedDocument) -> None:
        """Fill each completion entry's context values from the entry
        itself, a doc-field `path`, or the mapping `default` — in that
        order (ref: search/suggest/context/CategoryContextMapping
        parseContext + GeolocationContextMapping)."""
        for pf in out.fields:
            if pf.type != COMPLETION:
                continue
            fm = self._fields.get(pf.name)
            if fm is None or not fm.context:
                continue
            entry = pf.value
            supplied = entry.get("context") or {}
            resolved: dict = {}
            for ctx_name, cfg in fm.context.items():
                v = supplied.get(ctx_name)
                if v is None and cfg.get("path"):
                    v = obj
                    for part in str(cfg["path"]).split("."):
                        v = v.get(part) if isinstance(v, dict) else None
                        if v is None:
                            break
                if v is None:
                    v = cfg.get("default")
                if v is None:
                    continue
                if cfg.get("type") == "geo":
                    from ..ops.geo import parse_geo_point, geohash_encode
                    prec = _geo_precision_chars(cfg.get("precision"))
                    lat, lon = parse_geo_point(v)
                    resolved[ctx_name] = geohash_encode(lat, lon, prec)
                else:
                    vals = v if isinstance(v, list) else [v]
                    resolved[ctx_name] = [str(x) for x in vals]
            entry["context"] = resolved

    def _parse_object(self, prefix: str, obj: dict, out: ParsedDocument) -> None:
        for key, value in obj.items():
            name = f"{prefix}{key}"
            if name in self._nested_paths:
                # each element becomes a block-join sub-document (ref:
                # ObjectMapper nested=true -> Lucene child docs). Doubly-
                # nested children attach to the root doc, distinguished
                # by their full path.
                elements = value if isinstance(value, list) else [value]
                for el in elements:
                    if not isinstance(el, dict):
                        raise MapperParsingError(
                            f"nested field [{name}] elements must be objects")
                    src = json.dumps(el, separators=(",", ":")).encode()
                    sub = ParsedDocument(doc_id="", source=src)
                    self._parse_object(f"{name}.", el, sub)
                    out.nested.append((name, sub.fields, src))
                    out.nested.extend(sub.nested)
                continue
            if isinstance(value, dict):
                fm = self._fields.get(name)
                if fm is not None and fm.type in (GEO_POINT, GEO_SHAPE,
                                                  JOIN, COMPLETION):
                    # {"lat":..,"lon":..} point / GeoJSON shape / join /
                    # completion entry, not a sub-object
                    self._parse_value(name, value, out)
                    continue
                self._parse_object(f"{name}.", value, out)
                continue
            if isinstance(value, list):
                fm = self._fields.get(name)
                if fm is not None and fm.type == DENSE_VECTOR:
                    self._parse_value(name, value, out)
                    continue
                if fm is not None and fm.type == GEO_POINT and value and \
                        isinstance(value[0], (int, float)):
                    # bare [lon, lat] pair (GeoJSON order)
                    self._parse_value(name, value, out)
                    continue
            values = value if isinstance(value, list) else [value]
            for v in values:
                if v is None:
                    continue
                if isinstance(v, dict):
                    fm = self._fields.get(name)
                    if fm is not None and fm.type in (GEO_POINT, GEO_SHAPE):
                        self._parse_value(name, v, out)  # point/shape array
                    else:
                        self._parse_object(f"{name}.", v, out)
                    continue
                self._parse_value(name, v, out)

    def _parse_value(self, name: str, value, out: ParsedDocument) -> None:
        fm = self._fields.get(name)
        if fm is None:
            if self.dynamic == "strict":
                # ref: StrictDynamicMappingException (400)
                raise MapperParsingError(
                    f"mapping set to strict, dynamic introduction of [{name}] "
                    f"within [_doc] is not allowed")
            if not self.dynamic:
                return  # dynamic=false ignores unknown fields (ref behavior)
            fm = FieldMapper(name=name, type=self._dynamic_type(name, value))
            self._fields[name] = fm
            if fm.type == TEXT:
                # dynamic strings get a keyword twin (modern ES dynamic
                # template default: text + .keyword sub-field) so terms
                # aggs and sorts work out of the box
                twin = FieldMapper(name=f"{name}.keyword", type=KEYWORD)
                self._fields[twin.name] = twin
                self._multi_fields.setdefault(name, []).append(twin.name)
        self._emit_field(fm, value, out)
        # multi-fields index the same value under each sub-mapper's type
        # (ref: AbstractFieldMapper.MultiFields.parse)
        for sub_name in self._multi_fields.get(name, ()):
            sub = self._fields.get(sub_name)
            if sub is not None:
                self._emit_field(sub, value, out)

    def _emit_field(self, fm: FieldMapper, value, out: ParsedDocument) -> None:
        if fm.type == TEXT:
            if not fm.index:
                return  # index:false text is neither searchable nor columnar
            analyzer: Analyzer = self.analysis.analyzer(fm.analyzer)
            out.fields.append(ParsedField(name=fm.name, type=TEXT,
                                          tokens=analyzer.analyze(str(value))))
        elif fm.type == COMPLETION:
            # string | [strings] | {"input": ..., "output": ..., "weight":
            # ..., "payload": ..., "context": ...} -> one normalized entry
            # (ref: CompletionFieldMapper.parse)
            if isinstance(value, dict):
                inputs = value.get("input") or []
                inputs = inputs if isinstance(inputs, list) else [inputs]
                entry = {
                    "input": [str(i) for i in inputs],
                    "output": (str(value["output"])
                               if value.get("output") is not None else None),
                    "weight": int(value.get("weight", 1)),
                    "payload": value.get("payload"),
                    "context": (value.get("context")
                                if isinstance(value.get("context"), dict)
                                else {}),
                }
            else:
                entry = {"input": [str(value)], "output": None,
                         "weight": 1, "payload": None, "context": {}}
            out.fields.append(ParsedField(name=fm.name, type=COMPLETION,
                                          value=entry))
        elif not fm.index and not fm.doc_values:
            return
        elif fm.type == KEYWORD:
            if len(str(value)) <= 256 or "." not in fm.name:  # ignore_above on subs
                out.fields.append(ParsedField(name=fm.name, type=KEYWORD,
                                              value=str(value)))
        elif fm.type == JOIN:
            # {"name": relation, "parent": id} or bare relation string ->
            # relation ordinal column + "<field>#parent" id column (the
            # reference's _parent field data, ParentFieldMapper.java)
            if isinstance(value, dict):
                rel = value.get("name")
                parent = value.get("parent")
            else:
                rel, parent = str(value), None
            known = set()
            for p, c in (fm.relations or {}).items():
                known.add(p)
                known.update(c if isinstance(c, list) else [c])
            if rel not in known:
                raise MapperParsingError(
                    f"unknown join relation [{rel}] on field [{fm.name}]")
            out.fields.append(ParsedField(name=fm.name, type=KEYWORD,
                                          value=str(rel)))
            if parent is not None:
                out.fields.append(ParsedField(name=f"{fm.name}#parent",
                                              type=KEYWORD,
                                              value=str(parent)))
        elif fm.type == GEO_POINT:
            from ..ops.geo import parse_geo_point
            from ..utils.errors import QueryParsingError
            try:
                lat, lon = parse_geo_point(value)
            except QueryParsingError as e:
                if fm.ignore_malformed:
                    return
                raise MapperParsingError(str(e))
            out.fields.append(ParsedField(name=fm.name, type=GEO_POINT,
                                          value=(lat, lon)))
        elif fm.type == GEO_SHAPE:
            # GeoJSON -> prefix-tree cell tokens in the standard postings
            # layout, so shape queries are terms disjunctions on device
            # (ops/geo_shape.py; ref: GeoShapeFieldMapper.parse)
            from ..ops.geo_shape import (parse_shape, index_tokens,
                                         effective_levels)
            from ..utils.errors import QueryParsingError
            try:
                shp = parse_shape(value)
                tree, levels, err_pct = shape_tree_config(fm)
                toks = index_tokens(shp, tree,
                                    effective_levels(shp, tree, levels,
                                                     err_pct))
            except (QueryParsingError, TypeError, ValueError, IndexError,
                    KeyError) as e:
                if fm.ignore_malformed:
                    return
                raise MapperParsingError(
                    f"failed to parse [{fm.name}]: {e}")
            out.fields.append(ParsedField(name=fm.name, type=TEXT,
                                          tokens=toks))
        elif fm.type == DENSE_VECTOR:
            if not isinstance(value, list):
                raise MapperParsingError(
                    f"dense_vector [{fm.name}] requires an array of floats")
            vec = [float(x) for x in value]
            if fm.dims is not None and len(vec) != fm.dims:
                raise MapperParsingError(
                    f"dense_vector [{fm.name}] has {len(vec)} dims, "
                    f"mapping expects {fm.dims}")
            out.fields.append(ParsedField(name=fm.name, type=DENSE_VECTOR,
                                          value=vec))
        else:
            try:
                coerced = self._coerce(fm, value)
            except MapperParsingError:
                if fm.ignore_malformed:
                    return
                raise
            out.fields.append(ParsedField(name=fm.name, type=fm.type, value=coerced))


class MapperService:
    """Per-index mapper registry. Ref: index/mapper/MapperService.java.

    TPU-first deviation: the ENGINE is single-type — one merged field
    space, one columnar layout (`self.mapper`). The reference's per-type
    mappings survive as API metadata: `self.types` keeps one
    DocumentMapper VIEW per declared type, fed by create-index bodies
    and put-mapping calls, rendered by GET _mapping /
    _mapping/field/{fields}. Typed writes parse through the merged
    mapper; dynamic fields introduced by documents appear in the merged
    mapping (the view shows only declared fields)."""

    def __init__(self, index_settings: Settings = Settings.EMPTY,
                 mapping: dict | None = None,
                 type_mappings: dict | None = None):
        self.analysis = AnalysisService(index_settings)
        self.index_settings = index_settings
        self._sim_service = None  # built lazily (index/similarity.py)
        self.mapper = DocumentMapper(self.analysis, mapping)
        self.types: dict[str, DocumentMapper] = {}
        for tname, spec in (type_mappings or {}).items():
            self.put_type_mapping(tname, spec or {})

    def parse(self, doc_id: str, source) -> ParsedDocument:
        return self.mapper.parse(doc_id, source)

    def merge_mapping(self, mapping: dict) -> None:
        self.mapper.merge(mapping)

    def put_type_mapping(self, type_name: str, spec: dict) -> None:
        """Merge `spec` into the named type's view AND the engine's
        merged mapper (ref: MetaDataMappingService putMapping +
        DocumentMapper.merge)."""
        view = self.types.get(type_name)
        if view is None:
            self.types[type_name] = DocumentMapper(self.analysis, spec)
        else:
            view.merge(spec)
        self.mapper.merge(spec)

    def type_mapping_dict(self, type_name: str) -> dict:
        view = self.types.get(type_name)
        return view.to_dict() if view is not None else {"properties": {}}

    @property
    def parent_type(self) -> str | None:
        return self.mapper.parent_type

    @property
    def routing_required(self) -> bool:
        return self.mapper.routing_required

    def mapping_dict(self) -> dict:
        return self.mapper.to_dict()

    def field(self, name: str) -> FieldMapper | None:
        return self.mapper.field(name)

    def similarity_for(self, field: str):
        """The Similarity whose impacts are baked into `field`'s postings
        (ref: SimilarityService.similarity(fieldMapper))."""
        from .similarity import SimilarityService
        if self._sim_service is None:
            self._sim_service = SimilarityService(self.index_settings)
        return self._sim_service.for_field(self, field)

    @property
    def nested_paths(self) -> set[str]:
        return set(self.mapper._nested_paths)

    def join_field(self) -> FieldMapper | None:
        """The index's join field, if one is mapped (at most one, as with
        the reference's single _parent per type)."""
        for fm in self.mapper._fields.values():
            if fm.type == JOIN:
                return fm
        return None

    def search_analyzer_for(self, field_name: str) -> Analyzer:
        fm = self.mapper.field(field_name)
        if fm is None or fm.type != TEXT:
            return self.analysis.analyzer("keyword")
        return self.analysis.analyzer(fm.search_analyzer or fm.analyzer)
