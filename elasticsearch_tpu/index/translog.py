"""Translog: per-shard write-ahead log for durability and recovery.

Reference analog: index/translog/Translog.java (op types Create/Index/
Delete at :290/:432/:578, Snapshot streaming view :192) and the fs impl
(index/translog/fs/FsTranslog.java) with buffered/simple variants,
fsync policies, and rotation at flush.

Record format (binary, little-endian):
    [u32 length][u32 crc32-of-payload][payload: JSON]
A TORN TAIL (the file ends inside a record — the residue of a crash
mid-append) is truncated on open, counted under
`translog_truncated_bytes`, like the reference's recovery tolerating a
torn last write. A COMPLETE record that fails its crc or parse is NOT
a torn write — it is mid-log corruption of a durable record, and
replaying past it (or silently truncating everything after it) would
lose acked ops: that raises TranslogCorruptedError and the engine
CONTAINS the shard (ref: TranslogCorruptedException vs the tolerated
truncated-translog case).

Durability modes (`index.translog.durability`):
  * ``request`` (default) — fsync after every op: an op is on disk
    before its caller sees the ack. Survives kill -9 AND power loss.
  * ``async``  — flush (page cache) per op, fsync only at explicit
    sync()/flush/rotate: an op survives kill -9 (the page cache
    belongs to the OS, not the process) but power loss may drop the
    window since the last sync. `_synced_size` tracks the known-
    durable prefix; the crash_point `unsynced=drop` simulation
    truncates back to it — the deterministic power-loss adversary.

Generations: translog-<gen>.log; flush rotates to a new generation and
deletes the old ones once the segments it covers are durable. Every
append/fsync/rotate write boundary and every recovery read is hooked
into utils/faults.py.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass
from typing import Iterator

from ..utils import faults
from ..utils.errors import ElasticsearchTpuError
from . import durability as durability_stats

_HEADER = struct.Struct("<II")

OP_INDEX = "index"
OP_DELETE = "delete"

DURABILITY_REQUEST = "request"
DURABILITY_ASYNC = "async"


class TranslogCorruptedError(ElasticsearchTpuError):
    """A DURABLE translog record (complete on disk) failed its crc or
    parse — mid-log corruption, not a torn tail. Replay stops and the
    shard is contained instead of silently dropping acked ops."""

    status = 500


@dataclass
class TranslogOp:
    op: str                       # index | delete
    doc_id: str
    version: int
    source: bytes | None = None   # for index ops

    def to_payload(self) -> bytes:
        d = {"op": self.op, "id": self.doc_id, "v": self.version}
        if self.source is not None:
            d["src"] = self.source.decode("utf-8")
        return json.dumps(d, separators=(",", ":")).encode("utf-8")

    @classmethod
    def from_payload(cls, payload: bytes) -> "TranslogOp":
        d = json.loads(payload)
        src = d.get("src")
        return cls(op=d["op"], doc_id=d["id"], version=d["v"],
                   source=src.encode("utf-8") if src is not None else None)


class Translog:
    """Append-only op log with crc-checked records and generations.

    When the native layer is available (native/src/estnative.cpp), appends
    go through est_wal_append — one write() per record with C-side CRC and
    fdatasync control; the record format on disk is identical, so either
    implementation can recover the other's files.
    """

    def __init__(self, path: str, sync_each_op: bool = False,
                 durability: str | None = None,
                 index: str | None = None, shard: int | None = None):
        self.dir = path
        if durability is None:
            durability = (DURABILITY_REQUEST if sync_each_op
                          else DURABILITY_ASYNC)
        if durability not in (DURABILITY_REQUEST, DURABILITY_ASYNC):
            from ..utils.errors import IllegalArgumentError
            raise IllegalArgumentError(
                f"index.translog.durability must be "
                f"[{DURABILITY_REQUEST}] or [{DURABILITY_ASYNC}], "
                f"got [{durability}]")
        self.durability = durability
        self.sync_each_op = durability == DURABILITY_REQUEST
        self.index = index
        self.shard = shard
        self.truncated_bytes = 0
        os.makedirs(path, exist_ok=True)
        gens = self._generations()
        self.generation = gens[-1] if gens else 1
        self._ops_in_gen = 0
        self._size_in_gen = 0
        # recover tail sanity before appending
        existing = self._recover_file(self._file_for(self.generation))
        self._ops_in_gen = len(existing)
        self._fh = None
        self._wal = None
        self._lib = None
        try:
            from ..native import get_lib
            self._lib = get_lib()
        except Exception:
            self._lib = None
        if self._lib is not None:
            self._wal = self._lib.est_wal_open(
                self._file_for(self.generation).encode())
        if self._wal is None:
            self._lib = None
            self._fh = open(self._file_for(self.generation), "ab")
            self._size_in_gen = self._fh.tell()
        else:
            self._size_in_gen = self._lib.est_wal_size(self._wal)
        # the known-durable prefix: everything that existed at open is
        # on disk (the previous process flushed-or-died; what survived
        # IS the durable state), everything after only once fsynced
        self._synced_size = self._size_in_gen

    # -- paths -------------------------------------------------------------
    def _file_for(self, gen: int) -> str:
        return os.path.join(self.dir, f"translog-{gen}.log")

    def _generations(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("translog-") and name.endswith(".log"):
                try:
                    out.append(int(name[len("translog-"):-len(".log")]))
                except ValueError:
                    pass
        return sorted(out)

    def min_generation(self) -> int | None:
        """Oldest generation still on disk — the commit-coverage
        witness: recovery may fall back to a commit point C only when
        min_generation() <= C's recorded translog generation + 1
        (every op since C is then still replayable)."""
        gens = self._generations()
        return gens[0] if gens else None

    # -- write path --------------------------------------------------------
    def add(self, op: TranslogOp) -> None:
        payload = op.to_payload()
        rec = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload

        def torn_append():
            # the crash residue a real mid-append death leaves: the
            # record's prefix on disk, its tail missing — recovery's
            # torn-tail truncation is what chews this. The native WAL
            # has not written yet, so the tear lands via a throwaway
            # append fd (the process "dies" right after)
            half = rec[: max(len(rec) // 2, 1)]
            if self._fh is not None:
                self._fh.write(half)
                self._fh.flush()
            else:
                with open(self._file_for(self.generation), "ab") as f:
                    f.write(half)
        faults.on_storage_write("translog", "append", index=self.index,
                                shard=self.shard, partial=torn_append,
                                unsynced_drop=self._drop_unsynced)
        if self._wal is not None:
            if self.sync_each_op:
                # the native WAL fsyncs INSIDE est_wal_append, so the
                # fsync crash site fires here (record lost whole — the
                # pre-ack shape; the python path's fsync fires after
                # the buffered write, record present-but-unfsynced:
                # both are legal states for an un-acked op)
                faults.on_storage_write(
                    "translog", "fsync", index=self.index,
                    shard=self.shard,
                    unsynced_drop=self._drop_unsynced)
            size = self._lib.est_wal_append(
                self._wal, payload, len(payload),
                1 if self.sync_each_op else 0)
            if size < 0:
                raise OSError("translog append failed")
            self._size_in_gen = size
            self._ops_in_gen += 1
            if self.sync_each_op:
                self._synced_size = size
            return
        self._fh.write(rec)
        self._ops_in_gen += 1
        self._size_in_gen += len(rec)
        if self.sync_each_op:
            self.sync()
        else:
            self._fh.flush()

    def _drop_unsynced(self) -> None:
        """Power-loss simulation (crash_point `unsynced=drop`): the OS
        page cache dies with the machine, so everything written after
        the last fsync vanishes — truncate back to the known-durable
        prefix. In `request` mode the prefix IS the file, so this is a
        no-op: that asymmetry is the per-mode guarantee the durability
        tests pin."""
        if self._fh is not None:
            self._fh.flush()
        # works for the native WAL too: est_wal_append is one write()
        # per record, so unfsynced bytes live in the page cache (the
        # file), and the "power loss" truncates the file itself — the
        # process is dead right after, nobody writes through the stale
        # handle again
        path = self._file_for(self.generation)
        if os.path.exists(path) \
                and os.path.getsize(path) > self._synced_size:
            os.truncate(path, self._synced_size)

    def sync(self) -> None:
        faults.on_storage_write("translog", "fsync", index=self.index,
                                shard=self.shard,
                                unsynced_drop=self._drop_unsynced)
        if self._wal is not None:
            self._lib.est_wal_sync(self._wal)
            self._synced_size = self._size_in_gen
            return
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._synced_size = self._size_in_gen

    # -- snapshot / recovery ----------------------------------------------
    def snapshot(self) -> list[TranslogOp]:
        """All ops across live generations, in order (the recovery replay
        stream — ref Translog.Snapshot)."""
        if self._fh is not None:
            self._fh.flush()
        ops: list[TranslogOp] = []
        for gen in self._generations():
            ops.extend(self._recover_file(self._file_for(gen)))
        return ops

    def _recover_file(self, path: str) -> list[TranslogOp]:
        """Replay one generation file. A TORN TAIL (file ends inside a
        record) is truncated and counted; a COMPLETE record failing crc
        or parse is mid-log corruption of a durable record and raises
        TranslogCorruptedError — truncating past it would silently drop
        every acked op behind it."""
        ops: list[TranslogOp] = []
        if not os.path.exists(path):
            return ops
        faults.on_storage_read("translog", "read", path,
                               index=self.index, shard=self.shard)
        good_end = 0
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + _HEADER.size <= len(data):
            length, crc = _HEADER.unpack_from(data, off)
            start = off + _HEADER.size
            end = start + length
            if end > len(data):
                break  # torn tail: the record never finished hitting disk
            payload = data[start:end]
            if zlib.crc32(payload) != crc:
                durability_stats.on_corruption_detected()
                raise TranslogCorruptedError(
                    f"translog [{os.path.basename(path)}] record at "
                    f"offset {off} failed crc (durable record "
                    f"corrupted; {len(data) - off} bytes at risk)")
            try:
                ops.append(TranslogOp.from_payload(payload))
            except Exception as e:
                durability_stats.on_corruption_detected()
                raise TranslogCorruptedError(
                    f"translog [{os.path.basename(path)}] record at "
                    f"offset {off} unparseable: {e}") from e
            off = end
            good_end = end
        if good_end < len(data):
            torn = len(data) - good_end
            with open(path, "r+b") as f:  # truncate torn tail
                f.truncate(good_end)
            self.truncated_bytes += torn
            durability_stats.on_translog_truncated(torn)
        return ops

    # -- rotation (flush) --------------------------------------------------
    def rotate(self) -> None:
        """Start a new generation and drop old ones (called after a commit
        makes the covered ops durable in segments)."""
        # crash BEFORE the rotation: the commit is already durable and
        # every old generation survives — replay re-applies ops the
        # commit covers, which the versioned replay converges (same
        # ids, same versions); nothing is lost, nothing doubles
        faults.on_storage_write("translog", "rotate", index=self.index,
                                shard=self.shard,
                                unsynced_drop=self._drop_unsynced)
        old_gens = self._generations()
        if self._wal is not None:
            self._lib.est_wal_close(self._wal)
        else:
            self._fh.close()
        self.generation = (old_gens[-1] if old_gens else 0) + 1
        if self._lib is not None:
            self._wal = self._lib.est_wal_open(
                self._file_for(self.generation).encode())
        if self._wal is None:
            self._fh = open(self._file_for(self.generation), "ab")
        self._ops_in_gen = 0
        self._size_in_gen = 0
        self._synced_size = 0
        for gen in old_gens:
            try:
                os.remove(self._file_for(gen))
            except OSError:
                pass

    @property
    def num_ops(self) -> int:
        return self._ops_in_gen

    @property
    def size_in_bytes(self) -> int:
        return self._size_in_gen

    def close(self) -> None:
        try:
            if self._wal is not None:
                self._lib.est_wal_close(self._wal)
                self._wal = None
            elif self._fh is not None:
                self._fh.flush()
                self._fh.close()
        except Exception:
            pass

    def stats(self) -> dict:
        return {"operations": self._ops_in_gen,
                "size_in_bytes": self._size_in_gen,
                "generation": self.generation,
                "durability": self.durability,
                "truncated_bytes": self.truncated_bytes}
