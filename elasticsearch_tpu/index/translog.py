"""Translog: per-shard write-ahead log for durability and recovery.

Reference analog: index/translog/Translog.java (op types Create/Index/
Delete at :290/:432/:578, Snapshot streaming view :192) and the fs impl
(index/translog/fs/FsTranslog.java) with buffered/simple variants,
fsync policies, and rotation at flush.

Record format (binary, little-endian):
    [u32 length][u32 crc32-of-payload][payload: JSON]
A torn tail (partial record / crc mismatch) is truncated on open, like
the reference's translog recovery tolerating a torn last write.
Generations: translog-<gen>.log; flush rotates to a new generation and
deletes the old one once the segments it covers are durable.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass
from typing import Iterator

_HEADER = struct.Struct("<II")

OP_INDEX = "index"
OP_DELETE = "delete"


@dataclass
class TranslogOp:
    op: str                       # index | delete
    doc_id: str
    version: int
    source: bytes | None = None   # for index ops

    def to_payload(self) -> bytes:
        d = {"op": self.op, "id": self.doc_id, "v": self.version}
        if self.source is not None:
            d["src"] = self.source.decode("utf-8")
        return json.dumps(d, separators=(",", ":")).encode("utf-8")

    @classmethod
    def from_payload(cls, payload: bytes) -> "TranslogOp":
        d = json.loads(payload)
        src = d.get("src")
        return cls(op=d["op"], doc_id=d["id"], version=d["v"],
                   source=src.encode("utf-8") if src is not None else None)


class Translog:
    """Append-only op log with crc-checked records and generations.

    When the native layer is available (native/src/estnative.cpp), appends
    go through est_wal_append — one write() per record with C-side CRC and
    fdatasync control; the record format on disk is identical, so either
    implementation can recover the other's files.
    """

    def __init__(self, path: str, sync_each_op: bool = False):
        self.dir = path
        self.sync_each_op = sync_each_op
        os.makedirs(path, exist_ok=True)
        gens = self._generations()
        self.generation = gens[-1] if gens else 1
        self._ops_in_gen = 0
        self._size_in_gen = 0
        # recover tail sanity before appending
        existing = self._recover_file(self._file_for(self.generation))
        self._ops_in_gen = len(existing)
        self._fh = None
        self._wal = None
        self._lib = None
        try:
            from ..native import get_lib
            self._lib = get_lib()
        except Exception:
            self._lib = None
        if self._lib is not None:
            self._wal = self._lib.est_wal_open(
                self._file_for(self.generation).encode())
        if self._wal is None:
            self._lib = None
            self._fh = open(self._file_for(self.generation), "ab")
            self._size_in_gen = self._fh.tell()
        else:
            self._size_in_gen = self._lib.est_wal_size(self._wal)

    # -- paths -------------------------------------------------------------
    def _file_for(self, gen: int) -> str:
        return os.path.join(self.dir, f"translog-{gen}.log")

    def _generations(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("translog-") and name.endswith(".log"):
                try:
                    out.append(int(name[len("translog-"):-len(".log")]))
                except ValueError:
                    pass
        return sorted(out)

    # -- write path --------------------------------------------------------
    def add(self, op: TranslogOp) -> None:
        payload = op.to_payload()
        if self._wal is not None:
            size = self._lib.est_wal_append(
                self._wal, payload, len(payload),
                1 if self.sync_each_op else 0)
            if size < 0:
                raise OSError("translog append failed")
            self._size_in_gen = size
            self._ops_in_gen += 1
            return
        rec = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        self._fh.write(rec)
        self._ops_in_gen += 1
        self._size_in_gen += len(rec)
        if self.sync_each_op:
            self.sync()
        else:
            self._fh.flush()

    def sync(self) -> None:
        if self._wal is not None:
            self._lib.est_wal_sync(self._wal)
            return
        self._fh.flush()
        os.fsync(self._fh.fileno())

    # -- snapshot / recovery ----------------------------------------------
    def snapshot(self) -> list[TranslogOp]:
        """All ops across live generations, in order (the recovery replay
        stream — ref Translog.Snapshot)."""
        if self._fh is not None:
            self._fh.flush()
        ops: list[TranslogOp] = []
        for gen in self._generations():
            ops.extend(self._recover_file(self._file_for(gen)))
        return ops

    @staticmethod
    def _recover_file(path: str) -> list[TranslogOp]:
        ops: list[TranslogOp] = []
        if not os.path.exists(path):
            return ops
        good_end = 0
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + _HEADER.size <= len(data):
            length, crc = _HEADER.unpack_from(data, off)
            start = off + _HEADER.size
            end = start + length
            if end > len(data):
                break  # torn tail
            payload = data[start:end]
            if zlib.crc32(payload) != crc:
                break  # corrupt record: stop replay here
            try:
                ops.append(TranslogOp.from_payload(payload))
            except Exception:
                break
            off = end
            good_end = end
        if good_end < len(data):
            with open(path, "r+b") as f:  # truncate torn tail
                f.truncate(good_end)
        return ops

    # -- rotation (flush) --------------------------------------------------
    def rotate(self) -> None:
        """Start a new generation and drop old ones (called after a commit
        makes the covered ops durable in segments)."""
        old_gens = self._generations()
        if self._wal is not None:
            self._lib.est_wal_close(self._wal)
        else:
            self._fh.close()
        self.generation = (old_gens[-1] if old_gens else 0) + 1
        if self._lib is not None:
            self._wal = self._lib.est_wal_open(
                self._file_for(self.generation).encode())
        if self._wal is None:
            self._fh = open(self._file_for(self.generation), "ab")
        self._ops_in_gen = 0
        self._size_in_gen = 0
        for gen in old_gens:
            try:
                os.remove(self._file_for(gen))
            except OSError:
                pass

    @property
    def num_ops(self) -> int:
        return self._ops_in_gen

    @property
    def size_in_bytes(self) -> int:
        return self._size_in_gen

    def close(self) -> None:
        try:
            if self._wal is not None:
                self._lib.est_wal_close(self._wal)
                self._wal = None
            elif self._fh is not None:
                self._fh.flush()
                self._fh.close()
        except Exception:
            pass

    def stats(self) -> dict:
        return {"operations": self._ops_in_gen, "size_in_bytes": self._size_in_gen,
                "generation": self.generation}
