"""IVF coarse quantization for dense_vector fields (pack-time build).

The exact kNN scan (ops/knn.py) tops out around 1M x 256 per device:
every query streams the whole shard's vectors through the MXU. This
module adds the coarse stage that lets vector serving go an order of
magnitude further — k-means clustering at pack build, cluster pruning
at query time — grounded in "Faster Exact Search using Document
Clustering" and "Lucene for Approximate Nearest-Neighbors Search on
Arbitrary Dense Vectors" (PAPERS.md): cluster-local extrema prune
clusters exactly the way block-max tile summaries prune WAND tiles,
and a DECLARED recall target replaces HNSW's graph-tuning side
effects.

Build contract (the `pad_delta_shapes` convention): the cluster count
and per-cluster capacity are pow2-BUCKETED, so the pack's shape
signature — and with it every fingerprint-keyed cache and compiled
program — stays epoch-constant across rebuilds of similarly-sized
segments. Per cluster the index stores:

  * centroid [D] f32 — the query-time coarse matmul input;
  * radius f32 — max distance from centroid to any member in the
    similarity's working space (unit sphere for cosine, raw space
    otherwise), from which ops/ann.cluster_bounds derives an upper
    bound on the TRANSFORMED similarity of any member: the tile_max
    analog, one bound per cluster per query;
  * cluster-sorted member ordinals [cluster_cap] int32 (pad = -1).

Query-time pruning and probing live in ops/ann.py; the shard searcher
wires them in (search/shard_searcher.py). Delta segments always serve
the exact scan — IVF is a base-generation artifact, rebuilt by
compaction like the other pack summaries. Build failure (including an
injected `site=ann:phase=build` fault) degrades the segment to the
exact scan instead of failing the refresh: the index is an
accelerator, never a correctness input.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from .segment import next_pow2
from ..utils import faults

_TRUE = ("1", "true", "on", "yes")

# below this many vectors the exact scan wins outright (one small
# matmul — roughly the crossover where the exact path already switches
# to approx_max_k selection); also keeps clusters populated enough for
# the radius bound to prune meaningfully
DEFAULT_MIN_DOCS = 1 << 16
DEFAULT_RECALL = 0.95
# k-means training sample cap: IVF practice trains the coarse
# quantizer on a sample and assigns the full set in one pass
_TRAIN_CAP = 1 << 18
_KMEANS_ITERS = 10

# multiplicative slack on the transformed cluster bounds: member
# vectors are scored from their bf16-rounded device copies while the
# centroid geometry is computed in f32 — 1/64 covers the ~2^-8
# relative input rounding of both matmul operands with margin, and
# scores are nonnegative, so inflating the bound only makes pruning
# more conservative (never drops a cluster whose member could win)
ANN_BOUND_SLACK = np.float32(1.0 + 1.0 / 64.0)


# module config (node startup: Node plumbs index.ann.* through
# configure(); env vars override at read time — the tiering.py
# convention, ownership token and all)
_cfg_lock = threading.Lock()
_cfg_min_docs: int | None = None
_cfg_nprobe: int | None = None
_cfg_recall: float | None = None
_cfg_token: object | None = None


def configure(min_docs: int | None = None, nprobe: int | None = None,
              recall: float | None = None) -> object:
    """Node startup hook (process-global, last node wins). Returns an
    ownership token for reset(if_current=...)."""
    global _cfg_min_docs, _cfg_nprobe, _cfg_recall, _cfg_token
    with _cfg_lock:
        if min_docs is not None:
            _cfg_min_docs = int(min_docs)
        if nprobe is not None:
            _cfg_nprobe = int(nprobe)
        if recall is not None:
            _cfg_recall = float(recall)
        _cfg_token = object()
        return _cfg_token


def reset(if_current: object | None = None) -> None:
    global _cfg_min_docs, _cfg_nprobe, _cfg_recall, _cfg_token
    with _cfg_lock:
        if if_current is not None and if_current is not _cfg_token:
            return
        _cfg_min_docs = _cfg_nprobe = _cfg_recall = None
        _cfg_token = None


def min_docs() -> int:
    env = os.environ.get("ES_TPU_ANN_MIN_DOCS")
    if env is not None:
        return int(env)
    with _cfg_lock:
        return _cfg_min_docs if _cfg_min_docs is not None \
            else DEFAULT_MIN_DOCS


def declared_recall() -> float:
    env = os.environ.get("ES_TPU_ANN_RECALL")
    if env is not None:
        return float(env)
    with _cfg_lock:
        return _cfg_recall if _cfg_recall is not None else DEFAULT_RECALL


def default_nprobe(n_clusters: int, recall: float | None = None) -> int:
    """nprobe for a declared recall target, pow2-bucketed (nprobe is a
    jit-static of the probe program — the same recompile-hazard class
    as k, guarded the same way). The mapping is a documented heuristic
    (README "Vector search"): probe a recall-scaled fraction of the
    cluster count, floored at 8 — cluster sizes are sqrt(N)-ish, so a
    fraction of clusters is a fraction of the corpus scanned. The
    cluster-bound threshold prune then skips most probed clusters
    without scoring them, which is why over-probing is cheap.
    """
    env = os.environ.get("ES_TPU_ANN_NPROBE")
    if env is not None:
        return max(1, next_pow2(int(env), floor=1))
    with _cfg_lock:
        cfg = _cfg_nprobe
    if cfg is not None:
        return max(1, next_pow2(cfg, floor=1))
    r = declared_recall() if recall is None else float(recall)
    # fraction of clusters to probe: 1/8 at 0.95, 1/4 at 0.99+, 1/16
    # below 0.9 — empirically comfortable for sqrt(N) clusterings
    frac = 0.25 if r >= 0.99 else (0.125 if r >= 0.9 else 0.0625)
    return max(8, next_pow2(int(np.ceil(n_clusters * frac)), floor=1))


# serializes concurrent ensure_ann() installs; the k-means build itself
# runs OUTSIDE it (a lost race wastes one build, never corrupts state)
_ENSURE_LOCK = threading.Lock()


def ensure_ann(segment, field: str, similarity: str, *,
               index: str | None = None, shard: int | None = None):
    """Lazily build (once) and return `segment.ann[field]` — the
    ensure_* convention of the other pack summaries (executor
    ensure_num_tiles et al.). Returns None when the segment is below
    the exact-scan crossover, is a delta pack, or the build failed
    (injected `site=ann:phase=build` faults degrade to the exact scan
    — the index is an accelerator, never a correctness input; the
    failure is sticky per (segment, field) so a faulty build is not
    retried per search)."""
    ai = segment.ann.get(field)
    if ai is not None:
        return ai
    if getattr(segment, "delta_parent", None) is not None:
        return None
    skip = getattr(segment, "_ann_skip", None)
    if skip is not None and field in skip:
        return None
    vc = segment.vectors.get(field)
    if vc is None:
        return None
    try:
        built = build_ann(vc.values, vc.exists, similarity,
                          index=index, shard=shard)
    except Exception:
        # degrade to the exact scan, but VISIBLY: a real build bug
        # (not just an injected fault) would otherwise silently cost
        # every future search on this segment the exact-scan price
        import logging
        logging.getLogger(__name__).exception(
            "ANN build failed for [%s] on segment [%s]; serving the "
            "exact scan (sticky until rebuild)", field,
            getattr(segment, "seg_id", "?"))
        built = None
    with _ENSURE_LOCK:
        ai = segment.ann.get(field)
        if ai is not None:
            return ai          # lost the build race; first install wins
        if built is None:
            if getattr(segment, "_ann_skip", None) is None:
                segment._ann_skip = set()
            segment._ann_skip.add(field)
            return None
        # copy-on-write (the segment-dict convention): concurrent
        # searches iterate segment.ann without the lock
        segment.ann = {**segment.ann, field: built}
    return built


def ensure_ann_device(segment, field: str, similarity: str, *,
                      index: str | None = None, shard: int | None = None):
    """ensure_ann + (once) upload the IVF arrays. Returns (AnnIndex,
    device dict) or None. The upload lives on `segment._ann_device`,
    DELIBERATELY outside the segment's main device tree
    (executor.device_arrays): the ann arrays feed only the dedicated
    probe program (ops/ann.ivf_topk), and growing the main pytree would
    re-key every cached program for ordinary text queries. Bytes are
    fielddata-breaker-accounted with the standard weakref GC backstop;
    Segment.drop_device clears the attr (holds are idempotent)."""
    ai = ensure_ann(segment, field, similarity, index=index, shard=shard)
    if ai is None:
        return None
    cache = getattr(segment, "_ann_device", None)
    entry = None if cache is None else cache.get(field)
    if entry is None:
        import weakref

        import jax.numpy as jnp

        from ..utils.breaker import breaker_service
        hold = breaker_service().breaker("fielddata").hold(ai.nbytes())
        weakref.finalize(segment, hold.release)
        # counts stay host-side (they only shaped the members build);
        # the probe program consumes centroids/radii/members
        entry = {"centroids": jnp.asarray(ai.centroids),
                 "radii": jnp.asarray(ai.radii),
                 "members": jnp.asarray(ai.members),
                 "_breaker_hold": hold}
        with _ENSURE_LOCK:
            cache = getattr(segment, "_ann_device", None)
            if cache is None:
                cache = {}
                segment._ann_device = cache
            existing = cache.get(field)
            if existing is not None:
                # lost the upload race: release OUR hold now (the
                # winner's is the accounted one) instead of stranding
                # it until segment GC
                hold.release()
                entry = existing
            else:
                cache[field] = entry
    return ai, entry


class AnnIndex:
    """One field's IVF coarse index over a segment's vectors."""

    __slots__ = ("similarity", "centroids", "radii", "members", "counts")

    def __init__(self, similarity: str, centroids: np.ndarray,
                 radii: np.ndarray, members: np.ndarray,
                 counts: np.ndarray):
        self.similarity = similarity
        self.centroids = centroids      # [C, D] f32 (working space)
        self.radii = radii              # [C] f32
        self.members = members          # [C, cluster_cap] int32, pad -1
        self.counts = counts            # [C] int32

    @property
    def n_clusters(self) -> int:
        return self.centroids.shape[0]

    @property
    def cluster_cap(self) -> int:
        return self.members.shape[1]

    @property
    def dims(self) -> int:
        return self.centroids.shape[1]

    def nbytes(self) -> int:
        return (self.centroids.nbytes + self.radii.nbytes
                + self.members.nbytes + self.counts.nbytes)

    def arrays(self) -> dict[str, np.ndarray]:
        """Store round-trip payload (index/store.py `ann__<field>`)."""
        return {"centroids": self.centroids, "radii": self.radii,
                "members": self.members, "counts": self.counts}

    @classmethod
    def from_arrays(cls, similarity: str,
                    arrays: dict[str, np.ndarray]) -> "AnnIndex":
        return cls(similarity,
                   np.ascontiguousarray(arrays["centroids"],
                                        dtype=np.float32),
                   np.ascontiguousarray(arrays["radii"],
                                        dtype=np.float32),
                   np.ascontiguousarray(arrays["members"],
                                        dtype=np.int32),
                   np.ascontiguousarray(arrays["counts"],
                                        dtype=np.int32))


def _working_space(values: np.ndarray, similarity: str) -> np.ndarray:
    """Vectors in the geometry the cluster bound is argued in: the unit
    sphere for cosine (the bound is on q_hat . x_hat), raw space for
    dot_product / l2_norm (bounds via ||q|| r and ||q - c|| - r)."""
    x = values.astype(np.float32, copy=False)
    if similarity == "cosine":
        n = np.linalg.norm(x, axis=1, keepdims=True)
        return x / np.maximum(n, 1e-12)
    return x


def _kmeans(x: np.ndarray, n_clusters: int, seed: int,
            iters: int = _KMEANS_ITERS) -> np.ndarray:
    """Seeded Lloyd k-means on a training sample -> [C, D] f32
    centroids. When the device-parallel builder is enabled the WHOLE
    loop runs jitted (ops/build.kmeans_device — same init sample, same
    empty-cluster reseed rule; `_assign_full` below was already device-
    chunked), falling back here on any device error. Either path is
    deterministic per backend, and host-vs-device segment identity
    holds because both builds share whichever path is enabled."""
    from . import devbuild
    if devbuild.enabled():
        try:
            from ..ops.build import kmeans_device
            cent = kmeans_device(x, n_clusters, seed, iters=iters)
            devbuild._bump("kmeans_device")
            return cent
        except Exception as e:
            devbuild.on_fallback("kmeans", e)
    return _kmeans_host(x, n_clusters, seed, iters)


def _kmeans_host(x: np.ndarray, n_clusters: int, seed: int,
                 iters: int = _KMEANS_ITERS) -> np.ndarray:
    """Host reference Lloyd loop: empty clusters re-seed to the points
    farthest from their assigned centroid."""
    rng = np.random.default_rng(seed)
    n = x.shape[0]
    cent = x[rng.choice(n, size=n_clusters, replace=False)].copy()
    x2 = np.einsum("nd,nd->n", x, x)
    for _ in range(iters):
        # argmin_c ||x - c||^2 = argmin_c ||c||^2 - 2 x.c
        c2 = np.einsum("cd,cd->c", cent, cent)
        d = c2[None, :] - 2.0 * (x @ cent.T)          # [n, C] + const
        assign = np.argmin(d, axis=1)
        counts = np.bincount(assign, minlength=n_clusters)
        sums = np.zeros_like(cent)
        np.add.at(sums, assign, x)
        nonempty = counts > 0
        cent[nonempty] = sums[nonempty] / counts[nonempty, None]
        empty = np.nonzero(~nonempty)[0]
        if empty.size:
            # farthest points from their centroid re-seed the empties
            dmin = d[np.arange(n), assign] + x2
            far = np.argsort(-dmin)[: empty.size]
            cent[empty] = x[far]
    return cent.astype(np.float32)


def _assign_full(x: np.ndarray, cent: np.ndarray,
                 chunk: int = 1 << 17) -> tuple[np.ndarray, np.ndarray]:
    """Assign EVERY vector to its nearest centroid and measure each
    cluster's radius, chunked so the [chunk, C] distance slab stays
    bounded at 10M+ scale. Heavy half runs as jnp matmuls so a real
    accelerator does the assignment pass at device speed (CPU jax
    falls back to the host BLAS it would have used anyway)."""
    import jax
    import jax.numpy as jnp

    n, _d = x.shape
    c2 = np.einsum("cd,cd->c", cent, cent).astype(np.float32)

    @jax.jit
    def one_chunk(xc, centj, c2j):
        d = c2j[None, :] - 2.0 * jnp.dot(
            xc, centj.T, preferred_element_type=jnp.float32)
        a = jnp.argmin(d, axis=1)
        dmin = jnp.take_along_axis(d, a[:, None], axis=1)[:, 0]
        return a.astype(jnp.int32), dmin

    assign = np.empty(n, dtype=np.int32)
    dmin = np.empty(n, dtype=np.float32)
    centj = jnp.asarray(cent)
    c2j = jnp.asarray(c2)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        xc = x[lo:hi]
        if hi - lo < chunk and n > chunk:
            # pad to the chunk shape so the jitted program compiles once
            xc = np.concatenate(
                [xc, np.zeros((chunk - (hi - lo), x.shape[1]),
                              np.float32)])
        a, dm = one_chunk(jnp.asarray(xc), centj, c2j)
        assign[lo:hi] = np.asarray(a)[: hi - lo]
        dmin[lo:hi] = np.asarray(dm)[: hi - lo]
    x2 = np.einsum("nd,nd->n", x, x).astype(np.float32)
    d2 = np.maximum(dmin + x2, 0.0)       # true squared distance
    radii2 = np.zeros(cent.shape[0], dtype=np.float32)
    np.maximum.at(radii2, assign, d2)
    return assign, np.sqrt(radii2)


def build_ann(values: np.ndarray, exists: np.ndarray, similarity: str,
              *, index: str | None = None, shard: int | None = None,
              seed: int = 0) -> AnnIndex | None:
    """Build one field's IVF index at pack build, or None when the
    segment is below the exact-scan crossover (`index.ann.min_docs` /
    ES_TPU_ANN_MIN_DOCS). Raises on injected `site=ann:phase=build`
    faults — the caller (segment build) catches and degrades to the
    exact scan."""
    ords = np.nonzero(np.asarray(exists, dtype=bool))[0].astype(np.int32)
    n = int(ords.size)
    if n < min_docs():
        return None
    faults.on_dispatch("ann", index=index, shard=shard, phase="build")
    x = _working_space(np.asarray(values)[ords], similarity)
    # sqrt(N)-ish coarse stage, pow2-bucketed so the pack shape
    # signature is epoch-constant (the pad_delta_shapes convention);
    # every cluster keeps >= ~2 members on average at the floor
    c = next_pow2(int(np.sqrt(n)), floor=8)
    c = min(c, next_pow2(max(n // 2, 1), floor=1))
    train = x
    if n > _TRAIN_CAP:
        rng = np.random.default_rng(seed)
        train = x[rng.choice(n, size=_TRAIN_CAP, replace=False)]
    cent = _kmeans(train, c, seed)
    assign, radii = _assign_full(x, cent)
    # bf16 device rounding slack folded into the stored radius once
    # (see ANN_BOUND_SLACK — applied again on the transformed bound)
    counts = np.bincount(assign, minlength=c).astype(np.int32)
    ccap = next_pow2(int(counts.max()), floor=8)
    members = np.full((c, ccap), -1, dtype=np.int32)
    order = np.argsort(assign, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    for ci in range(c):
        lo = int(starts[ci])
        row = order[lo: lo + int(counts[ci])]
        members[ci, : row.size] = ords[row]
    return AnnIndex(similarity, cent, radii.astype(np.float32),
                    members, counts)
