"""Per-shard engine: indexing buffer, versioning, refresh, flush, recovery.

Reference analog: index/engine/InternalEngine.java — one writer + NRT
searcher + LiveVersionMap per shard: create/index (:234/:340 with per-uid
version checks :253-274), delete (:439), refresh (:549-555), flush =
commit + translog rotation (:574+), forceMerge (:715), plus
index/gateway/ local recovery (translog replay on restart).

TPU-first reinterpretation:
  * Lucene IndexWriter buffer -> host-side SegmentBuilder of parsed docs
  * NRT reader -> immutable list of device-resident Segments + live masks;
    refresh() builds a new segment, uploads its columns, publishes a new
    ShardReader (searches never block writes)
  * liveDocs -> numpy live masks (device copy refreshed on publish)
  * versioned optimistic concurrency preserved exactly (VersionConflict)
  * merge -> host-side columnar repack of the smallest segments
    (TieredMergePolicy-lite) to bound per-query segment count
"""

from __future__ import annotations

import itertools
import os
import threading
# module-scope clock (was an inline `import time` per delete/refresh):
# tombstone retention (index.gc_deletes) measures a RETENTION WINDOW,
# so it reads time.monotonic() — a wall-clock jump (NTP step, DST) must
# not prematurely GC a tombstone (late replicated deletes would
# resurrect docs) or immortalize one (the map would grow unbounded)
import time

import numpy as np

from ..utils.errors import (DocumentMissingError, IllegalArgumentError,
                            ShardFailedError, ShardNotFoundError,
                            VersionConflictError)
from ..utils.settings import Settings
from ..index.mapping import MapperService
from . import devbuild, durability
from .segment import (Segment, SegmentBuilder, concat_segments,
                      merge_segments, pad_delta_shapes)
from .store import CorruptIndexError, Store
from .translog import (Translog, TranslogCorruptedError, TranslogOp,
                       OP_INDEX, OP_DELETE)
from ..search.shard_searcher import ShardReader

_TRUE = ("1", "true", "on", "yes")


def delta_pack_default() -> bool:
    """Streaming delta-pack mode default (`ES_TPU_DELTA_PACK`); the
    per-index setting `index.streaming.delta` overrides. Opt-in, the
    resident-loop convention: unset keeps the legacy
    append-a-segment-per-refresh engine byte-for-byte."""
    return os.environ.get("ES_TPU_DELTA_PACK", "").lower() in _TRUE

_seg_counter = itertools.count(1)
_seg_counter_mx = threading.Lock()


def _ensure_seg_counter_above(n: int) -> None:
    """Advance the process-wide segment-id counter past `n`. Recovery
    calls this with the highest recovered sid ordinal: a restarted
    process otherwise counts from 1 again and a NEW segment eventually
    collides with a COMMITTED one's seg_id — the live-mask dict and
    the commit's file map are sid-keyed, so the collision silently
    drops committed docs (found by the kill -9 soak)."""
    global _seg_counter
    with _seg_counter_mx:
        cur = next(_seg_counter)
        _seg_counter = itertools.count(max(cur, n + 1))

_MERGE_POOL = None


def _merge_pool(settings: Settings):
    """Process-wide merge executor (ref: the merge thread pool behind
    ConcurrentMergeScheduler); first engine's
    index.merge.scheduler.max_thread_count wins."""
    global _MERGE_POOL
    if _MERGE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor
        _MERGE_POOL = ThreadPoolExecutor(
            max_workers=settings.get_int(
                "index.merge.scheduler.max_thread_count", 2),
            thread_name_prefix="merge")
    return _MERGE_POOL

_VERSION_TYPES = ("internal", "external", "external_gte", "external_gt",
                  "force")


def _validate_version_type(version: int | None, version_type: str) -> None:
    """Reject malformed version args up front (HTTP 400), regardless of
    whether the target doc exists (ref: VersionType.fromString +
    validateVersionForWrites)."""
    if version_type not in _VERSION_TYPES:
        raise IllegalArgumentError(
            f"version type [{version_type}] is not supported")
    if version is None and version_type != "internal":
        raise IllegalArgumentError(
            f"version type [{version_type}] requires an explicit version")


class Engine:
    """One shard's write path + searcher publication."""

    def __init__(self, index_name: str, shard_id: int, mapper: MapperService,
                 path: str | None = None, settings: Settings = Settings.EMPTY):
        self.index_name = index_name
        self.shard_id = shard_id
        self.mappers = mapper
        self.settings = settings
        self._lock = threading.RLock()
        self.max_segments = settings.get_int("index.merge.max_segment_count", 8)

        # device-parallel build (index/devbuild.py): route this shard's
        # pack builds (refresh + compaction) through the device builder;
        # the per-index `index.build.device` setting overrides the
        # process default (ES_TPU_DEVICE_BUILD / devbuild.configure)
        self._device_build = settings.get_bool(
            "index.build.device", devbuild.device_build_default())
        # IndexService points this at its IndexOpStats so refresh and
        # compaction surface build wall-time + docs/sec in the
        # indices_stats() indexing block
        self.op_stats = None

        # per-field similarity resolver, re-resolved at every segment
        # build so put-mapping'd fields take effect at next refresh
        # (ref: index/similarity/SimilarityService.java)
        self._sim_for = mapper.similarity_for

        self.segments: list[Segment] = []
        self.live: dict[str, np.ndarray] = {}
        self.buffer = SegmentBuilder(similarity=self._sim_for)
        self._buffer_docs: dict[str, tuple[int, bytes]] = {}  # id -> (version, src)
        # live version map (ref: LiveVersionMap.java): holds ONLY ids
        # written since the last refresh plus recent tombstones —
        # versions of refreshed docs load from the segments on demand,
        # and tombstones GC after index.gc_deletes (so the map stays
        # bounded under index/delete churn instead of growing forever)
        self.versions: dict[str, tuple[int, bool]] = {}
        self._tombstone_ts: dict[str, float] = {}
        self._gc_deletes_s = settings.get_time("index.gc_deletes", 60.0)
        self._commit_gen = 0

        # streaming write path (ROADMAP item 1, opt-in): ONE immutable
        # base generation + ONE small delta segment rebuilt per refresh
        # from the parsed docs written since the last compaction, so a
        # refresh is an epoch bump (every (base_generation, delta)
        # keyed cache survives) instead of an eviction. Background
        # compaction folds the delta into a new base via the
        # impact-preserving concat (segment.concat_segments) — the only
        # event that re-keys.
        self._delta_enabled = settings.get_bool("index.streaming.delta",
                                                delta_pack_default())
        self._delta_docs: dict[str, tuple] = {}   # id -> (parsed, version)
        self._delta_seg: Segment | None = None
        self._delta_epoch = 0
        self._base_gen: str | None = None
        self._compactions = 0
        self._compact_inflight = False
        self._compact_min = settings.get_int(
            "index.delta.min_compact_docs", 4096)
        self._compact_ratio = settings.get_float(
            "index.delta.compact_ratio", 0.5)

        # contained-shard state (ISSUE 15): a corruption that salvage
        # cannot prove lossless FAILS the shard — `failed` carries the
        # structured reason, the corruption marker stands in the store
        # dir, and every write/search answers ShardFailedError(503)
        # while the node keeps serving its healthy shards. `on_failed`
        # is the cluster path's containment callback
        # (cluster/distributed_node.py reports the failure to the
        # master so allocation promotes a surviving copy).
        self.failed: dict | None = None
        self.on_failed = None
        self._durability = settings.get_str("index.translog.durability",
                                            "request")
        # the index.shard.check_on_startup analog: verify the store
        # (commit + per-segment checksums) BEFORE serving it
        self._check_on_startup = settings.get_bool(
            "index.shard.check_on_startup", False)
        self.store = Store(path, index=index_name, shard=shard_id) \
            if path else None
        self.translog = None
        # seg_ids referenced by the last durable commit point: their
        # store files must survive until the NEXT commit is written
        # (cleanup_uncommitted reclaims them then) — deleting them at
        # refresh/compaction time would make the commit unrecoverable
        # after a crash, and the rotated translog no longer holds the
        # docs
        self._committed_seg_ids: set[str] = set()
        # sid -> (write-once file stem, live-mask hash) as of the last
        # commit: a flush re-saves a segment ONLY when its live mask
        # changed (segment content is immutable per sid), so committed
        # file pairs are never rewritten in place — the crash window
        # between npz replace and meta write can only ever hit a stem
        # no commit references
        self._committed_files: dict[str, tuple[str, str]] = {}
        self._reader: ShardReader | None = None
        # point-in-time view frozen at the last refresh: searches and
        # non-realtime gets read THIS, not the live bitmaps, so deletes/
        # updates after a refresh stay invisible until the next refresh
        # (ref: InternalEngine.get falls back to getFromSearcher)
        self._view_segments: list[Segment] = []
        self._view_live: dict[str, np.ndarray] = {}
        self._dirty = True
        if self.store is not None:
            # recovery errors must NEVER escape __init__ and poison
            # node startup: one flipped bit wedging shard creation is
            # exactly the failure mode this path contains. Salvage
            # first (_recover falls back per commit generation); what
            # salvage cannot prove lossless becomes a structured
            # contained shard failure. PowerLossError (an injected
            # crash) is deliberately NOT caught — a crashed process
            # runs no handlers.
            try:
                marker = self.store.corruption_marker()
                if marker is not None:
                    raise CorruptIndexError(
                        f"corruption marker present: {marker}")
                if self._check_on_startup:
                    report = self.store.verify_integrity()
                    if not report["clean"]:
                        raise CorruptIndexError(
                            "check_on_startup failed: "
                            f"{report['failures']}")
                self.translog = Translog(
                    f"{path}/translog", durability=self._durability,
                    index=index_name, shard=shard_id)
                self._recover()
            except (CorruptIndexError, TranslogCorruptedError,
                    OSError) as e:
                self._contain(e, during="recovery")

    # -- version map helpers ----------------------------------------------
    def _segment_version(self, doc_id: str) -> int | None:
        """Version of a refresh-published live copy (the LiveVersionMap
        loadFromIndex analog)."""
        for seg in reversed(self.segments):
            d = seg.id_map.get(doc_id)
            if d is not None and self.live[seg.seg_id][d]:
                return int(seg.versions[d])
        return None

    def _current_version(self, doc_id: str) -> int | None:
        v = self.versions.get(doc_id)
        if v is not None:
            return None if v[1] else v[0]
        return self._segment_version(doc_id)

    def _check_open(self) -> None:
        """Writes racing an engine swap (close) surface as
        shard-not-found, which every caller treats as retriable /
        covered-by-recovery rather than an internal error. A FAILED
        (contained) shard answers 503 instead: the data exists but
        this copy refuses to serve it — clients retry against a
        promoted copy (ref: writes to a corruption-failed shard)."""
        if getattr(self, "_engine_closed", False):
            raise ShardNotFoundError(self.index_name, self.shard_id)
        self._check_failed()

    # -- write path (ref: InternalEngine.index :340) -----------------------
    def index(self, doc_id: str, source: dict | bytes | str,
              version: int | None = None, _replay: bool = False,
              version_type: str = "internal") -> dict:
        with self._lock:
            self._check_open()
            current = self._current_version(doc_id)
            new_version = self._resolve_write_version(
                doc_id, current, version, version_type)
            parsed = self.mappers.parse(doc_id, source)
            self._delete_everywhere(doc_id)
            self.buffer.add(parsed, version=new_version)
            self._buffer_docs[doc_id] = (new_version, parsed.source)
            if self._delta_enabled:
                # the delta rebuild's doc set; re-inserts land at the
                # END (dict order), matching where a fresh segment
                # would have put the updated doc
                self._delta_docs[doc_id] = (parsed, new_version)
            self.versions[doc_id] = (new_version, False)
            self._tombstone_ts.pop(doc_id, None)  # re-index revives
            if self.translog is not None and not _replay:
                self.translog.add(TranslogOp(OP_INDEX, doc_id, new_version,
                                             parsed.source))
            self._dirty = True
            return {"_id": doc_id, "_version": new_version,
                    "created": current is None}

    def _resolve_write_version(self, doc_id: str, current: int | None,
                               version: int | None,
                               version_type: str) -> int:
        """Version check + next version (ref: common/lucene/uid/Versions
        + VersionType.{internal,external,external_gte,force}). External
        types take the PROVIDED version as the new version."""
        _validate_version_type(version, version_type)
        if version is None or version_type == "internal":
            if version is not None and current is not None \
                    and current != version:
                raise VersionConflictError(self.index_name, doc_id,
                                           current, version)
            return (current or 0) + 1
        if version_type in ("external", "external_gt"):
            # external_gt is an alias for EXTERNAL (strictly greater),
            # ref: index/VersionType.fromString
            if current is not None and version <= current:
                raise VersionConflictError(self.index_name, doc_id,
                                           current, version)
        elif version_type == "external_gte":
            if current is not None and version < current:
                raise VersionConflictError(self.index_name, doc_id,
                                           current, version)
        return version

    def delete(self, doc_id: str, version: int | None = None,
               _replay: bool = False,
               version_type: str = "internal") -> dict:
        with self._lock:
            self._check_open()
            _validate_version_type(version, version_type)
            current = self._current_version(doc_id)
            if current is None:
                if version is not None and version_type == "internal":
                    raise VersionConflictError(self.index_name, doc_id, -1, version)
                return {"_id": doc_id, "found": False}
            new_version = self._resolve_write_version(
                doc_id, current, version, version_type)
            self._delete_everywhere(doc_id)
            self.versions[doc_id] = (new_version, True)
            self._tombstone_ts[doc_id] = time.monotonic()
            if self.translog is not None and not _replay:
                self.translog.add(TranslogOp(OP_DELETE, doc_id, new_version))
            self._dirty = True
            return {"_id": doc_id, "found": True, "_version": new_version}

    def _delete_everywhere(self, doc_id: str) -> None:
        """Mark any prior copy of doc_id dead (buffer or any segment)."""
        self._delta_docs.pop(doc_id, None)
        if doc_id in self._buffer_docs:
            # rebuild buffer without the doc (rare within one refresh window)
            old = self.buffer
            self.buffer = SegmentBuilder(similarity=self._sim_for)
            for doc, ver in zip(old.docs, old.versions):
                if doc.doc_id != doc_id:
                    self.buffer.add(doc, ver)
            del self._buffer_docs[doc_id]
        for seg in self.segments:
            d = seg.id_map.get(doc_id)
            if d is not None:
                self.live[seg.seg_id][d] = False

    def apply_replicated(self, doc_id: str, source: bytes | None,
                         version: int, delete: bool = False) -> None:
        """Replica-side op application: the primary already resolved the
        version, so apply it verbatim; drop out-of-order older ops.
        Ref: TransportShardBulkAction.shardOperationOnReplica:551."""
        with self._lock:
            self._check_open()
            cur = self.versions.get(doc_id)
            cur_v = cur[0] if cur is not None \
                else self._segment_version(doc_id)
            if cur_v is not None and cur_v >= version:
                return
            self._delete_everywhere(doc_id)
            if delete:
                self.versions[doc_id] = (version, True)
                self._tombstone_ts[doc_id] = time.monotonic()
                if self.translog is not None:
                    self.translog.add(TranslogOp(OP_DELETE, doc_id, version))
            else:
                parsed = self.mappers.parse(doc_id, source)
                self.buffer.add(parsed, version=version)
                self._buffer_docs[doc_id] = (version, parsed.source)
                if self._delta_enabled:
                    self._delta_docs[doc_id] = (parsed, version)
                self.versions[doc_id] = (version, False)
                self._tombstone_ts.pop(doc_id, None)
                if self.translog is not None:
                    self.translog.add(TranslogOp(OP_INDEX, doc_id, version,
                                                 parsed.source))
            self._dirty = True

    def snapshot_docs(self) -> list[tuple[str, int, bytes]]:
        """All live (id, version, source) — the peer-recovery doc stream
        (ref: RecoverySourceHandler phase2 translog snapshot; we stream
        the live-doc set, which subsumes phases 1-2 for a columnar store
        whose segments are rebuilt device-side anyway)."""
        with self._lock:
            self._check_failed()  # a contained copy must never source
            #                       a recovery (its doc set is suspect)
            out: list[tuple[str, int, bytes]] = []
            for seg in self.segments:
                live = self.live[seg.seg_id]
                for d, did in enumerate(seg.ids):
                    if live[d]:
                        out.append((did, int(seg.versions[d]), seg.sources[d]))
            for did, (ver, src) in self._buffer_docs.items():
                out.append((did, ver, src))
            return out

    # -- realtime get (ref: index/get/ShardGetService.java) ----------------
    def get(self, doc_id: str, realtime: bool = True) -> dict:
        with self._lock:
            self._check_failed()
            if realtime:
                v = self.versions.get(doc_id)
                if v is not None and v[1]:
                    # recent tombstone: dead even if a stale segment
                    # copy is still live-masked pre-refresh
                    raise DocumentMissingError(self.index_name, doc_id)
                buffered = self._buffer_docs.get(doc_id)
                if buffered is not None:
                    return {"_id": doc_id, "_version": buffered[0],
                            "found": True, "_source": buffered[1]}
            # realtime reads see current bitmaps; non-realtime reads the
            # last-refresh snapshot (an unrefreshed delete/update must not
            # hide the previously refreshed copy)
            segs = self.segments if realtime else self._view_segments
            live = self.live if realtime else self._view_live
            for seg in segs:
                d = seg.id_map.get(doc_id)
                if d is not None and live[seg.seg_id][d]:
                    return {"_id": doc_id, "_version": int(seg.versions[d]),
                            "found": True, "_source": seg.sources[d]}
            raise DocumentMissingError(self.index_name, doc_id)

    # -- refresh (ref: InternalEngine.refresh :549) ------------------------
    def refresh(self) -> None:
        with self._lock:
            if self.failed is not None:
                return  # a contained shard has nothing to publish
            if not self._dirty:
                return  # nothing indexed/deleted since the last refresh
            if self._delta_enabled:
                self._refresh_delta()
            elif len(self.buffer):
                seg = self._build_segment(self.buffer)
                self.segments.append(seg)
                live = np.zeros(seg.capacity, dtype=bool)
                live[: seg.num_docs] = True
                self.live[seg.seg_id] = live
                self.buffer = SegmentBuilder(similarity=self._sim_for)
                self._buffer_docs = {}
                self._maybe_merge()
            self._prune_version_map()
            self._capture_view()
            self._reader = None  # next acquire builds a fresh point-in-time view
            self._dirty = False

    def _build_segment(self, builder: SegmentBuilder) -> Segment:
        """Build a refresh's pack — through the device-parallel builder
        when enabled (automatic host fallback inside) — and record
        build wall-time + docs for the indices_stats indexing block."""
        seg_id = f"{self.shard_id}_{next(_seg_counter)}"
        t0 = time.monotonic()
        if self._device_build:
            seg = devbuild.build_segment(builder, seg_id,
                                         index=self.index_name,
                                         shard=self.shard_id)
        else:
            seg = builder.build(seg_id)
        if self.op_stats is not None:
            self.op_stats.on_build((time.monotonic() - t0) * 1000.0,
                                   seg.num_docs,
                                   device=self._device_build)
        return seg

    # -- streaming delta pack (ROADMAP item 1) -----------------------------
    def base_generation(self) -> str:
        """Generation key of the immutable base segment set — what delta
        cache keys (Segment.cache_key) ride on. Changes only at
        compaction / force-merge / recovery, never at refresh."""
        if self._base_gen is None:
            import hashlib
            h = hashlib.blake2b(digest_size=8)
            for s in self.segments:
                if s is not self._delta_seg:
                    h.update(s.fingerprint().encode())
            self._base_gen = h.hexdigest()
        return self._base_gen

    def _refresh_delta(self) -> None:
        """Delta-mode refresh: rebuild the ONE delta segment from every
        doc written since the last compaction (caller holds the lock).
        The epoch bump — not an eviction: the new delta carries the
        same (base generation, pow2 capacity bucket) cache key, so
        autotune choices, pinned resident executables, and mesh
        programs all keep serving; deletions of base docs stay live-
        mask flips on the untouched base."""
        if len(self.buffer):
            builder = SegmentBuilder(similarity=self._sim_for)
            for did, (doc, ver) in self._delta_docs.items():
                builder.add(doc, ver)
            seg = self._build_segment(builder)
            seg.delta_parent = self.base_generation()
            seg.delta_epoch = self._delta_epoch + 1
            pad_delta_shapes(seg)
            self._drop_delta_segment()
            if seg.num_docs:
                live = np.zeros(seg.capacity, dtype=bool)
                live[: seg.num_docs] = True
                self.segments.append(seg)
                self.live[seg.seg_id] = live
                self._delta_seg = seg
            self._delta_epoch += 1
            self.buffer = SegmentBuilder(similarity=self._sim_for)
            self._buffer_docs = {}
            self._maybe_compact()

    def _drop_delta_segment(self) -> None:
        old = self._delta_seg
        if old is None:
            return
        if old in self.segments:
            self.segments.remove(old)
        self.live.pop(old.seg_id, None)
        if self.store is not None and old.seg_id not in self._committed_seg_ids:
            # a COMMITTED delta's file must outlive it: the last commit
            # point still lists it and the translog rotated at that
            # commit, so deleting here would lose its docs on a crash
            # before the next flush (cleanup_uncommitted reclaims it
            # once the next commit lands)
            self.store.delete_segment(old.seg_id)
        self._delta_seg = None

    def _maybe_compact(self) -> None:
        """Schedule (or, with the sync merge scheduler, run) background
        compaction once the delta outgrows
        max(index.delta.min_compact_docs,
            index.delta.compact_ratio * base docs)."""
        d = self._delta_seg
        if d is None or self._compact_inflight:
            return
        base_docs = sum(s.num_docs for s in self.segments if s is not d)
        threshold = max(self._compact_min,
                        int(base_docs * self._compact_ratio))
        if d.num_docs <= threshold:
            return
        if self.settings.get_bool("index.merge.scheduler.async", False):
            self._compact_inflight = True
            _merge_pool(self.settings).submit(self._compact_guarded)
        else:
            self._compact_now()

    def _compact_guarded(self) -> None:
        try:
            self._compact_now()
        except Exception:
            import logging
            logging.getLogger(__name__).exception(
                "[%s][%d] background compaction failed",
                self.index_name, self.shard_id)
        finally:
            self._compact_inflight = False

    def compact(self) -> bool:
        """Explicit synchronous compaction (test/bench hook)."""
        with self._lock:
            if self._delta_seg is None:
                if self._delta_enabled and self.segments:
                    # deletes-only window since the last fold: live-mask
                    # flips don't change the source column set, so a
                    # fold would rebuild a byte-equivalent base — skip
                    # the copy and count it (the build_skipped stat)
                    devbuild.count_skipped("compact")
                return False
        return self._compact_now()

    def _compact_now(self) -> bool:
        """Build-aside / keep-serving / atomic-swap compaction (the
        PR 7 repack substrate, parallel/repack.run_build_aside): the
        impact-preserving concat runs OFF the engine lock while the old
        generation serves every in-flight and new search; the swap
        re-validates under the lock (a refresh that replaced the delta
        mid-build aborts the fold — the next refresh retries), replays
        deletes that landed mid-build, and publishes the new base.
        Byte-identity: concat_segments preserves every surviving
        posting's impact, so responses before and after the swap are
        identical — only the fingerprint-keyed caches re-key, which is
        the ONE event that is allowed to."""
        from ..parallel.repack import run_build_aside
        with self._lock:
            snapshot = list(self.segments)
            snap_live = {s.seg_id: self.live[s.seg_id].copy()
                         for s in snapshot}
            # exactly the delta entries this build folds (by tuple
            # IDENTITY): only docs actually IN the snapshotted delta
            # segment (a still-buffered doc is not), and a doc indexed
            # or updated during the off-lock build replaces its entry —
            # the swap must keep both kinds for the next delta rebuild;
            # clearing the map wholesale would silently lose writes
            # that raced the build
            d = self._delta_seg
            folded = {did: e for did, e in self._delta_docs.items()
                      if d is not None and did in d.id_map}
        if not snapshot:
            return False
        seg_id = f"{self.shard_id}_{next(_seg_counter)}"

        def build():
            t0 = time.monotonic()
            if self._device_build:
                # the per-index setting rides to the _pack_layout seam
                # (and the k-means gate) on a thread-scoped override
                with devbuild.enable_scope():
                    merged = concat_segments(snapshot, seg_id, snap_live)
            else:
                merged = concat_segments(snapshot, seg_id, snap_live)
            if self.op_stats is not None:
                self.op_stats.on_build((time.monotonic() - t0) * 1000.0,
                                       merged.num_docs,
                                       device=self._device_build)
            return merged

        def swap(merged: Segment) -> bool:
            from ..search import resident
            with self._lock:
                if getattr(self, "_engine_closed", False):
                    return False
                if len(self.segments) != len(snapshot) or any(
                        a is not b for a, b in zip(self.segments,
                                                   snapshot)):
                    return False  # a refresh won the race; retry later
                m_live = np.zeros(merged.capacity, dtype=bool)
                m_live[: merged.num_docs] = True
                for s in snapshot:
                    flipped = snap_live[s.seg_id] & ~self.live[s.seg_id]
                    for d in np.nonzero(flipped)[0]:
                        row = merged.id_map.get(s.ids[int(d)])
                        if row is not None:
                            m_live[row] = False
                old_gen = self.base_generation()
                for old in snapshot:
                    self.live.pop(old.seg_id, None)
                    if (self.store is not None
                            and old.seg_id not in self._committed_seg_ids):
                        # committed files stay until the next commit's
                        # cleanup_uncommitted (crash-recovery safety,
                        # same rule as _drop_delta_segment)
                        self.store.delete_segment(old.seg_id)
                self.segments = [merged]
                self.live[merged.seg_id] = m_live
                self._delta_seg = None
                for did, entry in folded.items():
                    if self._delta_docs.get(did) is entry:
                        del self._delta_docs[did]
                self._delta_epoch = 0
                self._base_gen = None
                self._compactions += 1
                # compaction does not change visibility (same docs) but
                # NEW searches must read the compacted pack; in-flight
                # readers keep their refs to the retired generation
                self._capture_view()
                self._reader = None
            # the retired generation's fingerprint/generation-keyed
            # residue is reclaimed now — the ONLY re-key event
            resident.evict_generation(f"delta({old_gen})")
            resident.evict_segments(s.seg_id for s in snapshot)
            return True

        return run_build_aside(f"compact-{self.index_name}", build, swap)

    def _prune_version_map(self) -> None:
        """Refresh-time map pruning (ref: LiveVersionMap pruning at
        refresh + index.gc_deletes tombstone GC): every non-tombstone
        entry is now covered by a segment; tombstones survive one
        retention window (measured on the monotonic clock — wall-clock
        jumps must neither prematurely GC nor immortalize a tombstone)
        so late replicated ops still see the delete."""
        now = time.monotonic()
        keep: dict[str, tuple[int, bool]] = {}
        for did, v in self.versions.items():
            if not v[1]:
                continue   # live entry: the segment row covers it now
            ts = self._tombstone_ts.get(did, now)
            if now - ts <= self._gc_deletes_s:
                keep[did] = v
            else:
                self._tombstone_ts.pop(did, None)
        self.versions = keep

    def _capture_view(self) -> None:
        """Freeze the refresh-point snapshot searches/gets read from."""
        self._view_segments = list(self.segments)
        self._view_live = {s.seg_id: self.live[s.seg_id].copy()
                           for s in self.segments}

    def invalidate_reader(self) -> None:
        """Drop the cached point-in-time reader WITHOUT changing
        visibility (the next acquire rebuilds over the SAME refreshed
        view) — request-scoped state tied to the reader (request-cache
        entries, micro-batchers) dies with it. Ref: cache clear must
        never act like a refresh."""
        with self._lock:
            self._reader = None

    def acquire_searcher(self) -> ShardReader:
        """NRT searcher over the last refresh (ref: acquireSearcher).
        A FAILED shard raises ShardFailedError — the search path turns
        it into a structured `_shards.failures` entry and reduces over
        the survivors instead of 500ing the whole request."""
        with self._lock:
            self._check_failed()
            if self._reader is None:
                self._reader = ShardReader(
                    self.index_name, list(self._view_segments),
                    dict(self._view_live),
                    self.mappers, shard_id=self.shard_id)
            return self._reader

    # -- merge (ref: merge/policy/TieredMergePolicyProvider.java +
    # merge/scheduler/ConcurrentMergeSchedulerProvider.java) ---------------
    def _maybe_merge(self) -> None:
        if self.settings.get_bool("index.merge.scheduler.async", False):
            self._schedule_background_merge()
            return
        while len(self.segments) > self.max_segments:
            i = self._pick_merge_pair()
            self._apply_merge(self.segments[i: i + 2],
                              self._merge_pair(self.segments[i: i + 2]))

    def _pick_merge_pair(self) -> int:
        """Index of the smallest adjacent pair (keeps doc order stable)."""
        sizes = [s.num_docs for s in self.segments]
        return int(np.argmin([sizes[j] + sizes[j + 1]
                              for j in range(len(sizes) - 1)]))

    def _merge_pair(self, pair: list[Segment]) -> Segment:
        return merge_segments(
            pair, seg_id=f"{self.shard_id}_{next(_seg_counter)}",
            live_masks=self.live, similarity=self._sim_for)

    def _apply_merge(self, pair: list[Segment], merged: Segment) -> None:
        """Swap `pair` -> `merged` in the segment list (caller holds the
        lock on the sync path; the async path re-validates)."""
        i = self.segments.index(pair[0])
        for old in pair:
            self.live.pop(old.seg_id, None)
            if (self.store is not None
                    and old.seg_id not in self._committed_seg_ids):
                # committed files stay until the next commit's
                # cleanup_uncommitted (crash-recovery safety, same
                # rule as _drop_delta_segment)
                self.store.delete_segment(old.seg_id)
        live = np.zeros(merged.capacity, dtype=bool)
        live[: merged.num_docs] = True
        self.segments[i: i + 2] = [merged]
        self.live[merged.seg_id] = live

    def _schedule_background_merge(self) -> None:
        """Concurrent merge scheduling: the merge itself (a columnar
        rebuild) runs OFF the engine lock on the shared merge pool, so
        writes and refreshes proceed while it works; the swap
        re-validates under the lock and replays deletes that landed
        mid-merge (the liveDocs carry-over ConcurrentMergeScheduler
        relies on IndexWriter for). One merge in flight per engine;
        pool width = index.merge.scheduler.max_thread_count."""
        if len(self.segments) <= self.max_segments \
                or getattr(self, "_merge_inflight", False):
            return
        i = self._pick_merge_pair()
        pair = self.segments[i: i + 2]
        snapshot_live = {s.seg_id: self.live[s.seg_id].copy()
                         for s in pair}
        self._merge_inflight = True

        def run():
            ok = False
            try:
                merged = merge_segments(
                    pair, seg_id=f"{self.shard_id}_{next(_seg_counter)}",
                    live_masks=snapshot_live, similarity=self._sim_for)
                with self._lock:
                    if getattr(self, "_engine_closed", False):
                        return
                    if not all(s in self.segments for s in pair):
                        return  # sources vanished (force_merge/close won)
                    # deletes that raced the merge: any id whose live bit
                    # flipped since the snapshot dies in `merged` too
                    m_live = np.zeros(merged.capacity, dtype=bool)
                    m_live[: merged.num_docs] = True
                    for s in pair:
                        flipped = snapshot_live[s.seg_id] \
                            & ~self.live[s.seg_id]
                        for d in np.nonzero(flipped)[0]:
                            row = merged.id_map.get(s.ids[int(d)])
                            if row is not None:
                                m_live[row] = False
                    self._apply_merge(pair, merged)
                    self.live[merged.seg_id] = m_live
                    self._dirty = True
                    ok = True
            except Exception:
                # a persistently failing merge must not spin the pool:
                # log and stop; the next refresh retries at most once
                # per flush of new writes (ref: MergeScheduler handling
                # of merge exceptions)
                import logging
                logging.getLogger(__name__).exception(
                    "[%s][%d] background merge failed",
                    self.index_name, self.shard_id)
            finally:
                self._merge_inflight = False
                if ok:
                    with self._lock:
                        if not getattr(self, "_engine_closed", False) \
                                and len(self.segments) > self.max_segments:
                            self._schedule_background_merge()

        _merge_pool(self.settings).submit(run)

    def force_merge(self, max_num_segments: int = 1) -> None:
        """Ref: InternalEngine.forceMerge :715 / _optimize API."""
        with self._lock:
            self.refresh()
            if len(self.segments) > max_num_segments:
                merged = merge_segments(
                    self.segments, seg_id=f"{self.shard_id}_{next(_seg_counter)}",
                    live_masks=self.live, similarity=self._sim_for)
                from ..search import resident
                old_gen = self.base_generation()
                old_segs = list(self.segments)
                for old in old_segs:
                    self.live.pop(old.seg_id, None)
                    if (self.store is not None
                            and old.seg_id not in self._committed_seg_ids):
                        # committed files stay until the next commit's
                        # cleanup_uncommitted (crash-recovery safety,
                        # same rule as _drop_delta_segment)
                        self.store.delete_segment(old.seg_id)
                live = np.zeros(merged.capacity, dtype=bool)
                live[: merged.num_docs] = True
                self.segments = [merged]
                self.live = {merged.seg_id: live}
                # the merged segment IS the new base generation
                self._delta_seg = None
                self._delta_docs = {}
                self._delta_epoch = 0
                self._base_gen = None
                self._capture_view()
                self._reader = None
                # a force_merge is a re-key event exactly like
                # compaction: the retired generation's delta resident
                # entries carry no seg weakref (only evict_generation
                # reclaims them) and its per-segment entries would
                # otherwise wait on LRU pressure
                resident.evict_generation(f"delta({old_gen})")
                resident.evict_segments(s.seg_id for s in old_segs)

    # -- flush = commit + translog rotation (ref: :574+) -------------------
    def flush(self) -> None:
        with self._lock:
            if self.failed is not None:
                return  # a contained shard has nothing durable to add
            self.refresh()
            if self.store is None:
                return
            try:
                import hashlib
                stems: dict[str, str] = {}
                hashes: dict[str, str] = {}
                for seg in self.segments:
                    live = self.live[seg.seg_id]
                    h = hashlib.blake2b(live.tobytes(),
                                        digest_size=8).hexdigest()
                    hashes[seg.seg_id] = h
                    prev = self._committed_files.get(seg.seg_id)
                    if prev is not None and prev[1] == h:
                        # unchanged since the last commit: the
                        # write-once pair on disk stays authoritative
                        stems[seg.seg_id] = prev[0]
                    else:
                        stems[seg.seg_id] = self.store.save_segment(
                            seg, live, suffix=self._commit_gen + 1)
                self._commit_gen += 1
                # the commit records the exact write-once file stems
                # plus the translog generation ACTIVE at commit time:
                # every op acked after this commit lands in
                # generations >= it, so recovery can PROVE whether a
                # fallback to this commit is lossless (the salvage
                # walk's coverage check) instead of guessing
                self.store.write_commit(
                    self._commit_gen, [s.seg_id for s in self.segments],
                    extra={"files": stems,
                           "translog_gen": (self.translog.generation
                                            if self.translog is not None
                                            else 0)})
                self._committed_seg_ids = {s.seg_id
                                           for s in self.segments}
                self._committed_files = {
                    sid: (stems[sid], hashes[sid]) for sid in stems}
                self.store.cleanup_uncommitted(set(stems.values()))
                if self.translog is not None:
                    self.translog.sync()
                    self.translog.rotate()
            except OSError as e:
                # a flush that cannot make writes durable fails the
                # SHARD (ref: IndexShard failing on translog/store IO
                # errors): acked-but-uncommittable state must not keep
                # serving as if durable. PowerLossError (injected
                # crash) is not OSError and propagates — a crashed
                # process runs no handlers.
                self._contain(e, during="flush")
                raise ShardFailedError(self.index_name, self.shard_id,
                                       self.failed["reason"]) from e

    # -- recovery (ref: IndexShardGateway translog replay) -----------------
    def _salvage_commit(self) -> tuple[dict | None, list[tuple]]:
        """Pick the commit point recovery serves: walk generations
        newest→oldest, skipping torn/corrupt commit FILES and commits
        whose segments fail their checksums — each skip counted under
        `commits_fell_back`. A FALLBACK candidate (anything but the
        newest on-disk generation) is accepted only when the translog
        still covers every op acked since it: flush writes the commit
        STRICTLY before rotating the translog, and each commit records
        the translog generation active at commit time, so coverage
        holds iff the oldest on-disk translog generation <= recorded
        gen + 1. A fallback that cannot prove coverage — or a corrupt
        segment in a commit whose translog rotated — raises
        CorruptIndexError and the shard is CONTAINED: a structured
        failure beats silently serving with acked writes missing.
        Returns (commit, [(sid, segment, live), ...])."""
        gens = self.store.commit_generations()
        fell_back = False
        last_err: Exception | None = None
        for gen in gens:
            try:
                commit = self.store.read_commit(gen)
            except CorruptIndexError as e:
                durability.on_commit_fell_back()
                fell_back = True
                last_err = e
                continue
            if fell_back:
                tl_gen = commit.get("translog_gen")
                min_gen = (self.translog.min_generation()
                           if self.translog is not None else None)
                if tl_gen is None or min_gen is None \
                        or min_gen > int(tl_gen) + 1:
                    raise CorruptIndexError(
                        f"newest commit unusable ({last_err}) and the "
                        f"translog no longer covers commit [{gen}] "
                        "(rotated since) — refusing a fallback that "
                        "would silently lose acked writes")
            files = commit.get("files") or {}
            try:
                loaded = [(sid, *self.store.load_segment(
                              sid, stem=files.get(sid)))
                          for sid in commit["segments"]]
            except CorruptIndexError as e:
                durability.on_commit_fell_back()
                fell_back = True
                last_err = e
                continue
            # segment files NO readable commit references are crash
            # residue (saves of a commit that never landed, torn
            # half-pairs, retired files a crashed cleanup missed):
            # their docs re-enter via translog replay — drop the files
            # and count the salvage. Stems the RETAINED older commit
            # references stay: they are the fallback's data until the
            # next flush supersedes it
            orphans = (self.store.seg_stems_on_disk()
                       - self.store.referenced_stems())
            durability.on_segments_salvaged(len(orphans))
            for stem in orphans:
                for path in self.store._stem_paths(stem):
                    try:
                        os.remove(path)
                    except OSError:
                        pass
            return commit, loaded
        if gens:
            raise CorruptIndexError(
                f"no usable commit point among generations {gens}: "
                f"{last_err}")
        return None, []

    def _recover(self) -> None:
        commit, loaded = self._salvage_commit()
        if commit:
            import hashlib
            self._commit_gen = int(commit["generation"])
            self._committed_seg_ids = set(commit["segments"])
            tails = [sid.rsplit("_", 1)[-1]
                     for sid in commit["segments"]]
            ordinals = [int(t) for t in tails if t.isdigit()]
            if ordinals:
                _ensure_seg_counter_above(max(ordinals))
            files = commit.get("files") or {}
            self._committed_files = {
                sid: (files.get(sid, f"seg_{sid}"),
                      hashlib.blake2b(live.tobytes(),
                                      digest_size=8).hexdigest())
                for sid, seg, live in loaded}
            for sid, seg, live in loaded:
                self.segments.append(seg)
                self.live[sid] = live
                for d in range(seg.num_docs):
                    if live[d]:
                        self.versions[seg.ids[d]] = (int(seg.versions[d]), False)
                if self._delta_enabled and seg.delta_parent is not None:
                    # a recovered delta stays THE delta: future epoch
                    # bumps must keep rebuilding over its docs, so they
                    # re-enter the rebuild set (re-parsed from source —
                    # the same per-delta cost MeshIndex.refresh pays)
                    self._delta_seg = seg
                    self._delta_epoch = int(seg.delta_epoch)
                    for d in range(seg.num_docs):
                        if live[d] and (seg.parent_of is None
                                        or seg.parent_of[d] < 0):
                            self._delta_docs[seg.ids[d]] = (
                                self.mappers.parse(seg.ids[d],
                                                   seg.sources[d]),
                                int(seg.versions[d]))
        if self.translog is not None:
            for op in self.translog.snapshot():
                if op.op == OP_INDEX:
                    self.index(op.doc_id, op.source, _replay=True)
                    self.versions[op.doc_id] = (op.version, False)
                    self._buffer_docs[op.doc_id] = (op.version, op.source)
                    self.buffer.versions[-1] = op.version
                    if op.doc_id in self._delta_docs:
                        # replays carry the PERSISTED version, which
                        # must survive the next delta rebuild too
                        self._delta_docs[op.doc_id] = (
                            self._delta_docs[op.doc_id][0], op.version)
                elif op.op == OP_DELETE:
                    if self._current_version(op.doc_id) is not None:
                        self.delete(op.doc_id, _replay=True)
                    self.versions[op.doc_id] = (op.version, True)
        # recovery ends with a refresh so replayed ops are searchable
        # (ref: InternalEngine opens its searcher manager post-recovery)
        self.refresh()

    # -- shard-level containment (ref: Store.markStoreCorrupted +
    # IndexShard.failShard: corruption fails the SHARD, never the node) ----
    def _contain(self, exc: BaseException, during: str) -> None:
        """Fail this shard into a structured contained state: drop
        every in-memory structure (the data on disk stays put for
        forensics / peer re-source) and answer everything with
        ShardFailedError(503) from here on. The on-disk corruption
        marker is persisted ONLY for VERIFIED corruption (checksum /
        crc failures) — a transient OSError (EIO, disk full) fails the
        shard for this process but must not permanently brand an
        intact store corrupt: the next open retries cleanly once the
        condition clears (ref: the reference marks stores corrupted
        only on CorruptIndexException, never on plain IOExceptions)."""
        reason = f"{type(exc).__name__}: {exc}"
        marker = None
        if self.store is not None and isinstance(
                exc, (CorruptIndexError, TranslogCorruptedError)):
            try:
                marker = self.store.write_corruption_marker(reason)
            except OSError:
                pass   # a disk too broken to mark still fails in-memory
        self.failed = {"reason": reason, "during": during,
                       "marker": marker}
        self.segments = []
        self.live = {}
        self.buffer = SegmentBuilder(similarity=self._sim_for)
        self._buffer_docs = {}
        self.versions = {}
        self._tombstone_ts = {}
        self._delta_seg = None
        self._delta_docs = {}
        self._view_segments = []
        self._view_live = {}
        self._reader = None
        if self.translog is not None:
            self.translog.close()
            self.translog = None
        durability.on_shard_failed_corrupt()
        cb = self.on_failed
        if cb is not None:
            cb(self)

    def fail_shard(self, reason: str, exc: BaseException | None = None,
                   during: str = "runtime") -> None:
        """Public containment entry (corruption detected outside
        recovery — a failed flush, an external verify pass). Idempotent."""
        with self._lock:
            if self.failed is not None:
                return
            self._contain(exc or CorruptIndexError(reason), during)

    def _check_failed(self) -> None:
        if self.failed is not None:
            raise ShardFailedError(self.index_name, self.shard_id,
                                   self.failed["reason"])

    # -- stats / lifecycle -------------------------------------------------
    def doc_count(self) -> int:
        with self._lock:
            n = len(self.buffer)
            for seg in self.segments:
                n += int(self.live[seg.seg_id][: seg.num_docs].sum())
            return n

    def segment_stats(self) -> dict:
        with self._lock:
            out = {
                "count": len(self.segments),
                "docs": self.doc_count(),
                "memory_in_bytes": sum(s.nbytes() for s in self.segments),
                "buffered_docs": len(self.buffer),
            }
            if self.failed is not None:
                out["failed"] = dict(self.failed)
            if self._delta_enabled:
                d = self._delta_seg
                out["streaming"] = {
                    "base_generation": self.base_generation(),
                    "delta_epoch": self._delta_epoch,
                    "delta_docs": (d.num_docs if d is not None else 0),
                    "compactions": self._compactions,
                }
            return out

    def close(self) -> None:
        with self._lock:
            self._engine_closed = True
            if self.translog is not None:
                self.translog.close()
            gen = self.base_generation() if self.segments else None
            seg_ids = [s.seg_id for s in self.segments]
        if self._delta_enabled and gen is not None:
            # delta/pack resident entries carry NO seg weakref (the
            # epoch's segments are meant to die under them) — only an
            # explicit generation eviction reclaims their pinned
            # executables + breaker-accounted bytes; without this an
            # index close/delete strands them until LRU cap pressure
            from ..search import resident
            resident.evict_generation(f"delta({gen})")
            resident.evict_segments(seg_ids)
