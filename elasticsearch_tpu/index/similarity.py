"""Pluggable per-field similarities baked into index-time impacts.

Reference analog: index/similarity/SimilarityService.java +
SimilarityModule.java (ES 1.x exposes Lucene's TFIDF ("default"), BM25,
DFR, IB, LMDirichlet and LMJelinekMercer similarities, configured under
`index.similarity.<name>.type` and referenced per-field via the mapping's
`similarity` property).

TPU-first design: the reference scores postings one at a time through a
Similarity object inside the Lucene hot loop (BulkScorer). Here scoring
is eager (BM25S-style): every similarity is expressed as a *vectorized
per-posting impact function* evaluated once at segment build, so the
query-time path (gather -> weight -> scatter-add, ops/scoring.py) is
identical for every similarity — swapping similarity costs nothing at
search time. The per-(term,doc) score of every supported similarity is a
function of (tf, doc_len) plus per-term/corpus constants (df, ttf,
doc_count, avg_len, total_len), which is exactly what the segment builder
has in hand when it lays out posting blocks.

Two consequences, both documented divergences:
  * changing a field's similarity requires a reindex (the reference
    recomputes at query time; we bake at index time — the mapping API
    rejects in-place similarity changes the same way it rejects analyzer
    changes);
  * the DFS query-then-fetch global-stats rescale is exact for the
    df-ratio family (BM25, classic TF/IDF) and a no-op for similarities
    whose df-dependence is non-multiplicative (DFR/IB/LM) — see
    `df_scale`.

Impacts are clamped to a tiny positive floor because `score > 0` doubles
as the match mask in the executor (ops/scoring.py score_term).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..utils.settings import Settings
from ..utils.errors import IllegalArgumentError

# floor keeping matched postings strictly positive (match-mask semantics)
_IMPACT_FLOOR = 1e-6


@dataclass(frozen=True)
class FieldStats:
    """Per-term + per-field corpus statistics available at layout time.

    df: document frequency of the term; ttf: total term frequency
    (sum of tf over docs); doc_count: docs with the field; avg_len /
    total_len: average / total field length in tokens. Mirrors Lucene's
    TermStatistics + CollectionStatistics handed to
    SimilarityBase.score().
    """

    df: float
    ttf: float
    doc_count: float
    avg_len: float
    total_len: float


class Similarity:
    """Base: vectorized impact function over one term's postings."""

    name = "base"

    def impacts(self, tf: np.ndarray, dl: np.ndarray,
                st: FieldStats) -> np.ndarray:
        """Per-posting score contribution. tf, dl: float64 [n]."""
        raise NotImplementedError

    def df_scale(self, df_local: float, n_local: float,
                 df_global: float, n_global: float) -> float:
        """Multiplier turning a locally-idf'd impact into the global-stats
        score for DFS query-then-fetch (ref: dfs/AggregatedDfs consumed by
        TermWeight). 1.0 when the similarity's df-dependence is not a
        separable factor of the impact."""
        return 1.0

    def finish(self, imp: np.ndarray) -> np.ndarray:
        return np.maximum(imp, _IMPACT_FLOOR)


class BM25Similarity(Similarity):
    """Lucene BM25Similarity (the engine default; ref
    index/similarity/BM25SimilarityProvider.java)."""

    name = "BM25"

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = float(k1)
        self.b = float(b)

    @staticmethod
    def idf(df: float, n: float) -> float:
        return math.log(1.0 + (n - df + 0.5) / (df + 0.5))

    def impacts(self, tf, dl, st):
        idf = self.idf(st.df, st.doc_count)
        k_d = self.k1 * (1.0 - self.b + self.b * dl / st.avg_len)
        return self.finish(idf * tf * (self.k1 + 1.0) / (tf + k_d))

    def df_scale(self, df_local, n_local, df_global, n_global):
        lo = self.idf(df_local, n_local)
        if lo <= 0 or n_global <= 0:
            return 1.0
        return self.idf(df_global, n_global) / lo


class ClassicSimilarity(Similarity):
    """Lucene TFIDF DefaultSimilarity — the reference's "default"
    similarity (ref: index/similarity/DefaultSimilarityProvider.java).

    Practical scoring function per term: sqrt(tf) * idf^2 / sqrt(dl),
    idf = 1 + ln(N / (df + 1)). queryNorm is a per-query constant
    (rank-neutral) and coord was removed in later Lucene; both omitted.
    Unlike Lucene we keep the length norm exact rather than 8-bit
    quantized."""

    name = "default"

    @staticmethod
    def idf(df: float, n: float) -> float:
        return 1.0 + math.log(max(n, 1.0) / (df + 1.0))

    def impacts(self, tf, dl, st):
        idf = self.idf(st.df, st.doc_count)
        norm = 1.0 / np.sqrt(np.maximum(dl, 1.0))
        return self.finish(np.sqrt(tf) * (idf * idf) * norm)

    def df_scale(self, df_local, n_local, df_global, n_global):
        lo = self.idf(df_local, n_local)
        if lo <= 0 or n_global <= 0:
            return 1.0
        r = self.idf(df_global, n_global) / lo
        return r * r


def _tfn(normalization: str, c: float, mu: float, z: float,
         tf: np.ndarray, dl: np.ndarray, st: FieldStats) -> np.ndarray:
    """DFR/IB term-frequency normalizations (Lucene NormalizationH1/H2/H3/Z;
    ref: org.apache.lucene.search.similarities.Normalization*)."""
    dl = np.maximum(dl, 1.0)
    if normalization in ("h1", "H1"):
        return tf * (st.avg_len / dl) * c
    if normalization in ("h2", "H2", "", None):
        return tf * np.log2(1.0 + c * st.avg_len / dl)
    if normalization in ("h3", "H3"):
        p = (st.ttf + 1.0) / (st.total_len + 1.0)
        return (tf + mu * p) / (dl + mu) * mu
    if normalization in ("z", "Z"):
        return tf * np.power(st.avg_len / dl, z)
    if normalization in ("no", "none"):
        return tf.astype(np.float64)
    raise IllegalArgumentError(
        f"Unsupported Normalization [{normalization}]")


class DFRSimilarity(Similarity):
    """Divergence-from-randomness (Lucene DFRSimilarity; ref
    index/similarity/DFRSimilarityProvider.java). Configured by
    basic_model (g | if | in | ine), after_effect (no | b | l) and
    normalization (no | h1 | h2 | h3 | z)."""

    name = "DFR"

    def __init__(self, basic_model: str = "g", after_effect: str = "l",
                 normalization: str = "h2", c: float = 1.0,
                 mu: float = 800.0, z: float = 0.30):
        self.basic_model = str(basic_model).lower()
        self.after_effect = str(after_effect).lower()
        self.normalization = str(normalization).lower()
        self.c, self.mu, self.z = float(c), float(mu), float(z)
        if self.basic_model not in ("g", "if", "in", "ine"):
            raise IllegalArgumentError(
                f"Unsupported BasicModel [{basic_model}]")
        if self.after_effect not in ("no", "none", "b", "l"):
            raise IllegalArgumentError(
                f"Unsupported AfterEffect [{after_effect}]")

    def _basic(self, tfn: np.ndarray, st: FieldStats) -> np.ndarray:
        n, f, df = st.doc_count, max(st.ttf, 1.0), st.df
        if self.basic_model == "g":
            lam = f / (n + f)
            return np.log2(1.0 / (lam + 1.0)) \
                + tfn * np.log2((1.0 + lam) / lam)
        if self.basic_model == "if":
            return tfn * math.log2(1.0 + (n + 1.0) / (f + 0.5))
        if self.basic_model == "in":
            return tfn * math.log2(1.0 + (n + 1.0) / (df + 0.5))
        # ine: expected df under a random distribution of F occurrences
        ne = n * (1.0 - math.pow((n - 1.0) / n, f)) if n > 1 else n
        return tfn * math.log2(1.0 + (n + 1.0) / (ne + 0.5))

    def _after(self, tfn: np.ndarray, st: FieldStats) -> np.ndarray:
        if self.after_effect == "l":
            return 1.0 / (tfn + 1.0)
        if self.after_effect == "b":
            return (st.ttf + 1.0) / (max(st.df, 1.0) * (tfn + 1.0))
        return np.ones_like(tfn)

    def impacts(self, tf, dl, st):
        tfn = _tfn(self.normalization, self.c, self.mu, self.z, tf, dl, st)
        return self.finish(self._basic(tfn, st) * self._after(tfn, st))


class IBSimilarity(Similarity):
    """Information-based similarity (Lucene IBSimilarity; ref
    index/similarity/IBSimilarityProvider.java). distribution (ll | spl),
    lambda (df | ttf), normalization as DFR."""

    name = "IB"

    def __init__(self, distribution: str = "ll", lambda_: str = "df",
                 normalization: str = "h2", c: float = 1.0,
                 mu: float = 800.0, z: float = 0.30):
        self.distribution = str(distribution).lower()
        self.lambda_kind = str(lambda_).lower()
        self.normalization = str(normalization).lower()
        self.c, self.mu, self.z = float(c), float(mu), float(z)
        if self.distribution not in ("ll", "spl"):
            raise IllegalArgumentError(
                f"Unsupported Distribution [{distribution}]")
        if self.lambda_kind not in ("df", "ttf"):
            raise IllegalArgumentError(f"Unsupported Lambda [{lambda_}]")

    def impacts(self, tf, dl, st):
        if self.lambda_kind == "df":
            lam = (st.df + 1.0) / (st.doc_count + 1.0)
        else:
            lam = (st.ttf + 1.0) / (st.doc_count + 1.0)
        lam = min(max(lam, 1e-9), 1.0 - 1e-9)
        tfn = _tfn(self.normalization, self.c, self.mu, self.z, tf, dl, st)
        if self.distribution == "ll":
            imp = -np.log(lam / (tfn + lam))
        else:  # spl: smoothed power law
            num = np.power(lam, tfn / (tfn + 1.0)) - lam
            imp = -np.log(np.maximum(num, 1e-12) / (1.0 - lam))
        return self.finish(imp)


class LMDirichletSimilarity(Similarity):
    """Language model with Dirichlet smoothing (Lucene
    LMDirichletSimilarity; ref index/similarity/
    LMDirichletSimilarityProvider.java). Scores below zero are clamped,
    as in Lucene."""

    name = "LMDirichlet"

    def __init__(self, mu: float = 2000.0):
        self.mu = float(mu)

    def impacts(self, tf, dl, st):
        p = (st.ttf + 1.0) / (st.total_len + 1.0)
        imp = np.log(1.0 + tf / (self.mu * p)) \
            + math.log(self.mu) - np.log(dl + self.mu)
        return self.finish(np.maximum(imp, 0.0))


class LMJelinekMercerSimilarity(Similarity):
    """Language model, Jelinek-Mercer smoothing (Lucene
    LMJelinekMercerSimilarity; ref index/similarity/
    LMJelinekMercerSimilarityProvider.java)."""

    name = "LMJelinekMercer"

    def __init__(self, lambda_: float = 0.1):
        if not 0.0 < float(lambda_) <= 1.0:
            raise IllegalArgumentError(
                f"lambda must be in (0..1] but was [{lambda_}]")
        self.lambda_ = float(lambda_)

    def impacts(self, tf, dl, st):
        p = (st.ttf + 1.0) / (st.total_len + 1.0)
        dl = np.maximum(dl, 1.0)
        imp = np.log1p((1.0 - self.lambda_) * (tf / dl)
                       / (self.lambda_ * p))
        return self.finish(imp)


DEFAULT_SIMILARITY = BM25Similarity()


def _build(type_name: str, s: Settings) -> Similarity:
    t = str(type_name)
    if t in ("BM25", "bm25"):
        return BM25Similarity(k1=s.get_float("k1", 1.2),
                              b=s.get_float("b", 0.75))
    if t in ("default", "classic", "tfidf", "TF/IDF"):
        return ClassicSimilarity()
    if t == "DFR":
        return DFRSimilarity(
            basic_model=s.get_str("basic_model", "g"),
            after_effect=s.get_str("after_effect", "l"),
            normalization=s.get_str("normalization", "h2"),
            c=s.get_float("normalization.h1.c",
                          s.get_float("normalization.h2.c", 1.0)),
            mu=s.get_float("normalization.h3.mu", 800.0),
            z=s.get_float("normalization.z.z", 0.30))
    if t == "IB":
        return IBSimilarity(
            distribution=s.get_str("distribution", "ll"),
            lambda_=s.get_str("lambda", "df"),
            normalization=s.get_str("normalization", "h2"),
            c=s.get_float("normalization.h1.c",
                          s.get_float("normalization.h2.c", 1.0)),
            mu=s.get_float("normalization.h3.mu", 800.0),
            z=s.get_float("normalization.z.z", 0.30))
    if t == "LMDirichlet":
        return LMDirichletSimilarity(mu=s.get_float("mu", 2000.0))
    if t == "LMJelinekMercer":
        return LMJelinekMercerSimilarity(lambda_=s.get_float("lambda", 0.1))
    raise IllegalArgumentError(f"Unknown Similarity type [{t}]")


class SimilarityService:
    """Resolves similarity names -> instances for one index.

    Ref: index/similarity/SimilarityService.java — built-ins ("default",
    "BM25", ...) plus custom entries from `index.similarity.<name>.*`
    settings. The engine-wide default here is BM25 (the reference 1.x
    default is TFIDF "default"; BM25 is both this engine's eager-impact
    native form and the modern ES default — fields wanting classic
    scoring say `"similarity": "default"`)."""

    def __init__(self, index_settings: Settings = Settings.EMPTY):
        self._custom: dict[str, Similarity] = {}
        for name, group in index_settings.groups("index.similarity").items():
            t = group.get_str("type")
            if not t:
                raise IllegalArgumentError(
                    f"Similarity [{name}] must have an associated type")
            self._custom[name] = _build(t, group)

    def get(self, name: str | None, field: str = "") -> Similarity:
        if not name:
            return DEFAULT_SIMILARITY
        if name in self._custom:
            return self._custom[name]
        try:
            return _build(name, Settings.EMPTY)
        except IllegalArgumentError:
            where = f" for field [{field}]" if field else ""
            raise IllegalArgumentError(
                f"Unknown Similarity type [{name}]{where}")

    def for_field(self, mapper_service, field: str) -> Similarity:
        fm = mapper_service.field(field)
        sim_name = getattr(fm, "similarity", None) if fm is not None else None
        # "cosine" is the dense_vector-metric default riding the shared
        # mapping attribute; text fields treat it as unset
        if sim_name in (None, "", "cosine"):
            return DEFAULT_SIMILARITY
        return self.get(sim_name, field)
