"""Hunspell dictionaries + hunspell stemming token filter.

Reference analog: indices/analysis/HunspellService.java (scans
`<path.conf>/hunspell/<locale>/` for `*.aff` + `*.dic`, exposes named
dictionaries) and the `hunspell` token filter
(HunspellTokenFilterFactory.java) that reduces tokens to dictionary
stems via affix rules.

Scope: the affix features the stemming path exercises — SFX/PFX rule
groups with strip/affix/condition, cross-product flags, and the FLAG
`long`/`num` modes are NOT needed for stemming and are ignored. A token
stems to every dictionary word that can produce it by applying one
optional prefix and one optional suffix rule (matching hunspell's
single-affix stemming used by Lucene's HunspellStemmer); unknown tokens
pass through unchanged (the filter's dedup=true default keeps the
original only when nothing stems).
"""

from __future__ import annotations

import os
import re
import threading

from ..utils.errors import IllegalArgumentError


class AffixRule:
    __slots__ = ("strip", "affix", "condition")

    def __init__(self, strip: str, affix: str, condition: str,
                 kind: str = "SFX"):
        self.strip = "" if strip == "0" else strip
        self.affix = "" if affix == "0" else affix
        cond = condition if condition and condition != "." else ""
        # the condition tests the BASE word: end-anchored for suffixes,
        # start-anchored for prefixes (hunspell affix semantics)
        if not cond:
            self.condition = None
        elif kind == "SFX":
            self.condition = re.compile(cond + "$")
        else:
            self.condition = re.compile("^" + cond)


class HunspellDictionary:
    """One parsed .aff + .dic pair."""

    def __init__(self, aff_path: str, dic_path: str,
                 ignore_case: bool = True):
        self.ignore_case = ignore_case
        # flag -> ("SFX"|"PFX", [AffixRule])
        self.suffix_rules: dict[str, list[AffixRule]] = {}
        self.prefix_rules: dict[str, list[AffixRule]] = {}
        self.words: dict[str, set[str]] = {}  # word -> affix flags
        self._parse_aff(aff_path)
        self._parse_dic(dic_path)

    def _norm(self, w: str) -> str:
        return w.lower() if self.ignore_case else w

    def _parse_aff(self, path: str) -> None:
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                parts = line.split("#", 1)[0].split()
                if len(parts) < 4 or parts[0] not in ("SFX", "PFX"):
                    continue
                kind, flag = parts[0], parts[1]
                if len(parts) == 4 and parts[3].isdigit():
                    continue  # header line: SFX <flag> <cross> <count>
                strip, affix = parts[2], parts[3]
                affix = affix.split("/", 1)[0]  # continuation flags n/a
                cond = parts[4] if len(parts) > 4 else "."
                rule = AffixRule(strip, affix, cond, kind)
                target = (self.suffix_rules if kind == "SFX"
                          else self.prefix_rules)
                target.setdefault(flag, []).append(rule)

    def _parse_dic(self, path: str) -> None:
        with open(path, encoding="utf-8", errors="replace") as f:
            first = True
            for line in f:
                line = line.strip()
                if not line:
                    continue
                if first:
                    first = False
                    if line.isdigit():
                        continue  # entry-count header
                word, _, flags = line.partition("/")
                word = self._norm(word.strip())
                if word:
                    self.words.setdefault(word, set()).update(flags.strip())

    # -- stemming -----------------------------------------------------------

    def _suffix_candidates(self, token: str):
        for flag, rules in self.suffix_rules.items():
            for r in rules:
                if r.affix and token.endswith(r.affix):
                    cand = token[: len(token) - len(r.affix)] + r.strip
                    if cand and (r.condition is None
                                 or r.condition.search(cand)):
                        yield cand, flag

    def stem(self, token: str) -> list[str]:
        """All dictionary stems of `token` (empty when none)."""
        t = self._norm(token)
        out: list[str] = []
        if t in self.words:
            out.append(t)
        for cand, flag in self._suffix_candidates(t):
            if flag in self.words.get(cand, ()):
                if cand not in out:
                    out.append(cand)
            else:
                # prefix + suffix cross product
                for base, pflag in self._prefix_bases(cand):
                    flags = self.words.get(base, ())
                    if flag in flags and pflag in flags \
                            and base not in out:
                        out.append(base)
        for base, pflag in self._prefix_bases(t):
            if pflag in self.words.get(base, ()) and base not in out:
                out.append(base)
        return out

    def _prefix_bases(self, token: str):
        """(base, flag) pairs a prefix rule could have produced `token`
        from — the rule's start-anchored condition checked on the
        base."""
        for pflag, prules in self.prefix_rules.items():
            for pr in prules:
                if pr.affix and token.startswith(pr.affix):
                    base = pr.strip + token[len(pr.affix):]
                    if base and (pr.condition is None
                                 or pr.condition.search(base)):
                        yield base, pflag


class HunspellService:
    """Named dictionary registry (ref: HunspellService.java). Scans
    `<root>/<locale>/*.aff|*.dic` lazily per locale."""

    _instance: "HunspellService | None" = None

    def __init__(self):
        self._roots: list[str] = []
        self._dicts: dict[str, HunspellDictionary] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "HunspellService":
        if cls._instance is None:
            cls._instance = HunspellService()
        return cls._instance

    def add_root(self, path: str) -> None:
        if path and os.path.isdir(path) and path not in self._roots:
            self._roots.append(path)

    def available_locales(self) -> list[str]:
        out = set(self._dicts)
        for root in self._roots:
            for entry in os.listdir(root):
                if os.path.isdir(os.path.join(root, entry)):
                    out.add(entry)
        return sorted(out)

    def dictionary(self, locale: str) -> HunspellDictionary:
        with self._lock:
            d = self._dicts.get(locale)
            if d is not None:
                return d
            for root in self._roots:
                ldir = os.path.join(root, locale)
                if not os.path.isdir(ldir):
                    continue
                aff = [f for f in sorted(os.listdir(ldir))
                       if f.endswith(".aff")]
                dic = [f for f in sorted(os.listdir(ldir))
                       if f.endswith(".dic")]
                if not aff or not dic:
                    continue
                d = HunspellDictionary(os.path.join(ldir, aff[0]),
                                       os.path.join(ldir, dic[0]))
                self._dicts[locale] = d
                return d
        raise IllegalArgumentError(
            f"Unknown hunspell dictionary [{locale}]")


def hunspell_filter(locale: str, dedup: bool = True):
    """The `hunspell` token filter (ref:
    HunspellTokenFilterFactory.java). Each token is replaced by its
    dictionary stems; tokens with no stem pass through."""
    def run(tokens):
        d = HunspellService.instance().dictionary(locale)
        out = []
        for t in tokens:
            stems = d.stem(t)
            if not stems:
                out.append(t)
            elif dedup:
                # dedup removes DUPLICATE stems; every distinct stem is
                # still emitted (Lucene HunspellStemFilter semantics)
                seen = set()
                for s in stems:
                    if s not in seen:
                        seen.add(s)
                        out.append(s)
            else:
                out.extend(stems)
        return out
    return run
