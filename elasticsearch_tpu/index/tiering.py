"""Tiered tile residency: HBM as a cache over host-RAM pack tiles.

Today's pack contract is "everything uploads, once" (executor
`device_arrays`): a pack must fit in HBM, which caps corpus size per
device. This module relaxes that for the dominant fused-path column —
the per-field forward index (`fwd_tids`/`fwd_imps`, >= 64 bytes/doc at
the minimum slot width, vs ~4-5 bytes/doc for a doc-value column) — by
partitioning it into the SAME SCORE_TILE-aligned doc tiles the
block-max walk already reasons about:

  * the tiny per-tile summaries (`PostingsField.tile_max`, numeric
    tile extrema) stay PERMANENTLY device-resident — they are the
    pruning oracle and the paging oracle at once;
  * the bound computation runs over those summaries FIRST (host
    mirror: ops/scoring.bundle_tile_bounds_np) to produce the survivor
    tile set — a tile no query in the batch can match is never fetched
    at all, so WAND pruning becomes an I/O filter, not just a FLOP
    filter ("The Performance Envelope of Inverted Indexing on Modern
    Hardware", PAPERS.md);
  * cold survivor tiles stream host->device asynchronously
    (`jax.device_put` per tile slice), overlapped with scoring: the
    executor's chunked tiered walk uploads chunk N+1's tiles while
    chunk N's program executes;
  * residency is LRU per (segment, field, tile), every resident tile's
    bytes held on the fielddata breaker via `utils/breaker.Hold`, with
    a weakref GC backstop per segment (holds are idempotent, so the
    deterministic drop path and the finalizer can never double-release
    an evicted-then-GC'd tile).

Keying invariant: NOTHING here touches `Segment.fingerprint()` /
`Segment.cache_key()` — residency state is runtime-only, so autotune
choices, resident executables, and the shard request cache never
re-key on a page event (gated under trace_guarded in
tests/test_tiering.py).

Opt-in: `ES_TPU_TIERED_PACK` env or the `index.tiering.enabled` node
setting; when the whole pack fits the budget the fully-resident fast
path is preserved (counted, not paged). Stats surface under
`nodes_stats()["fused_scoring"]["tiering"]`, and the fielddata breaker
entry splits summary vs paged residency in `nodes_stats()["breakers"]`.
"""

from __future__ import annotations

import os
import threading
import weakref

import numpy as np

from .segment import Segment, next_pow2, score_tile_size, build_tile_minmax
from ..utils.metrics import CounterMetric, HighWaterMetric

_TRUE = ("1", "true", "on", "yes")

DEFAULT_CHUNK_TILES = 8

# module config (node startup: Node plumbs index.tiering.* through
# configure(); env vars override at read time so tests and the bench
# can flip modes without a node)
_cfg_lock = threading.Lock()
_cfg_enabled: bool | None = None
_cfg_budget: int | None = None
_cfg_chunk_tiles: int | None = None
# ownership token: minted fresh per configure() so a closing node can
# tear down ONLY its own install — value equality on the settings
# would alias two nodes configured identically
_cfg_token: object | None = None


def configure(enabled: bool | None = None,
              budget_bytes: int | None = None,
              chunk_tiles: int | None = None) -> object:
    """Node startup hook. Process-global (the executor serves every
    node in the process); last configured node wins. Returns an
    ownership token for reset(if_current=...) — the repack /
    process-stats teardown convention."""
    global _cfg_enabled, _cfg_budget, _cfg_chunk_tiles, _cfg_token
    with _cfg_lock:
        if enabled is not None:
            _cfg_enabled = bool(enabled)
        if budget_bytes is not None:
            _cfg_budget = int(budget_bytes)
        if chunk_tiles is not None:
            _cfg_chunk_tiles = max(1, int(chunk_tiles))
        _cfg_token = object()
        return _cfg_token


def config_snapshot() -> tuple:
    with _cfg_lock:
        return (_cfg_enabled, _cfg_budget, _cfg_chunk_tiles)


def reset(if_current: object | None = None) -> None:
    """Drop config AND every paged tile + counter (test/node-close
    hook). `if_current`: tear down only while the installed config is
    still the caller's own configure() token — a closing node must not
    clobber a later node's live tiering config (even an identically-
    valued one) or drop its paged tiles."""
    global _cfg_enabled, _cfg_budget, _cfg_chunk_tiles, _cfg_token, \
        stats
    with _cfg_lock:
        if if_current is not None and if_current is not _cfg_token:
            return
        _cfg_enabled = _cfg_budget = _cfg_chunk_tiles = None
        _cfg_token = None
        stats = TieringStats()
    pager.clear()


def enabled() -> bool:
    env = os.environ.get("ES_TPU_TIERED_PACK")
    if env is not None:
        return env.lower() in _TRUE
    return bool(_cfg_enabled)


def budget_bytes() -> int:
    """HBM byte budget for PAGED tile residency (summaries are not
    charged against it — they are the permanently-resident index of
    the tier). Default: half the fielddata breaker limit."""
    env = os.environ.get("ES_TPU_TIERED_BUDGET_BYTES")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if _cfg_budget is not None:
        return max(1, _cfg_budget)
    from ..utils.breaker import breaker_service
    return max(1, breaker_service().breaker("fielddata").limit // 2)


def chunk_tiles() -> int:
    """Tiles per chunked-walk upload+score step. POW2-BUCKETED: the
    chunk tile count is a static shape of the tiered chunk programs,
    so a raw setting value would mint one compiled program per value
    (the graftlint recompile-hazard family)."""
    env = os.environ.get("ES_TPU_TIERED_CHUNK_TILES")
    raw = None
    if env:
        try:
            raw = int(env)
        except ValueError:
            raw = None
    if raw is None:
        raw = _cfg_chunk_tiles
    return next_pow2(max(raw or DEFAULT_CHUNK_TILES, 1), floor=1)


class TieringStats:
    """Process-wide tiered-residency counters."""

    def __init__(self):
        self.tile_hits = CounterMetric()
        self.tile_misses = CounterMetric()
        self.tile_evictions = CounterMetric()
        # tiles the bound computation pruned BEFORE any fetch — the
        # I/O-filter win (never uploaded, never scored)
        self.prune_skipped_fetches = CounterMetric()
        self.tiered_dispatches = CounterMetric()
        # packs that fit the budget and kept the fully-resident path
        self.fast_path_full_resident = CounterMetric()
        # non-fused plans against a paged pack: the fallback uploads
        # the forward index after all (counted, breaker-accounted)
        self.unfused_full_uploads = CounterMetric()
        # mesh rows that stayed fully resident despite tiering (the
        # mesh pack is one SPMD array set; per-row paging is a
        # documented limitation, made observable here)
        self.mesh_full_resident_rows = CounterMetric()
        # ms a chunk's tile staging overlapped with the PREVIOUS
        # chunk's in-flight scoring — the upload/compute overlap the
        # stepped walk buys (high-water)
        self.prefetch_overlap_ms = HighWaterMetric()


stats = TieringStats()


class TileStore:
    """Host-side tile partition of one segment's pageable columns.

    Holds zero-copy views into the segment's forward-index arrays plus
    the host-side numeric tile extrema the survivor computation reads.
    Creating a store does NOT move bytes anywhere; the pager does."""

    __slots__ = ("seg_id", "capacity", "tile", "n_tiles", "fields",
                 "_fwd", "tile_nbytes", "paged_bytes", "summary_bytes",
                 "_extrema", "__weakref__")

    def __init__(self, segment: Segment):
        self.seg_id = segment.seg_id
        self.capacity = segment.capacity
        self.tile = score_tile_size(segment.capacity)
        self.n_tiles = segment.capacity // max(self.tile, 1)
        self.fields: tuple[str, ...] = tuple(sorted(
            f for f, pf in segment.text.items()
            if pf.fwd_tids is not None
            and getattr(pf, "tile_max", None) is not None))
        self._fwd = {}
        self.tile_nbytes = {}
        self.paged_bytes = 0
        self.summary_bytes = 0
        for f in self.fields:
            pf = segment.text[f]
            pos = getattr(pf, "fwd_pos", None)
            self._fwd[f] = (pf.fwd_tids, pf.fwd_imps, pos)
            self.tile_nbytes[f] = (pf.fwd_tids[: self.tile].nbytes
                                   + pf.fwd_imps[: self.tile].nbytes
                                   + (pos[: self.tile].nbytes
                                      if pos is not None else 0))
            self.paged_bytes += pf.fwd_tids.nbytes + pf.fwd_imps.nbytes \
                + (pos.nbytes if pos is not None else 0)
            self.summary_bytes += pf.tile_max.nbytes
            if pos is not None:
                # the positional length norms stay permanently
                # device-resident next to tile_max (they are per-doc
                # scalars the chunk walk gathers, not paged columns)
                self.summary_bytes += pf.k1ln.nbytes + pf.lnorm.nbytes
        self._extrema: dict[str, tuple | None] = {}

    def pageable(self) -> bool:
        return bool(self.fields) and self.n_tiles > 1

    def tile_slices(self, field: str, tile_id: int) -> tuple:
        tids, imps, pos = self._fwd[field]
        lo, hi = tile_id * self.tile, (tile_id + 1) * self.tile
        return (tids[lo:hi], imps[lo:hi],
                pos[lo:hi] if pos is not None else None)

    def extrema(self, segment: Segment, field: str):
        """Host numeric tile extrema for the survivor computation —
        the same build_tile_minmax product ensure_num_tiles uploads
        (and the SAME host arrays, via the shared per-segment cache),
        so the host filter and the device kernel prune from identical
        numbers without recomputing the O(capacity) pass."""
        if field not in self._extrema:
            mm = host_extrema(segment, field)
            self._extrema[field] = mm
            if mm is not None:
                self.summary_bytes += mm[0].nbytes + mm[1].nbytes
        return self._extrema[field]


def host_extrema(segment: Segment, field: str):
    """Per-segment host cache of build_tile_minmax — ONE computation
    shared by the device upload (executor.ensure_num_tiles) and the
    tiered survivor oracle (TileStore.extrema), so a range-filtered
    query never pays the O(capacity) min/max pass twice. None when the
    column cannot carry extrema (absent, multi-valued, degenerate tile
    grid). Host-derived state like _host_perms: lives with the segment,
    untouched by drop_device."""
    cache = getattr(segment, "_host_tile_minmax", None)
    if cache is None:
        cache = {}
        segment._host_tile_minmax = cache  # type: ignore[attr-defined]
    if field not in cache:
        nc = segment.numerics.get(field)
        cache[field] = (None if nc is None or nc.mv_values is not None
                        else build_tile_minmax(nc.values, nc.exists,
                                               segment.capacity))
    return cache[field]


class _ResidentTile:
    """One device-resident (segment, field, tile) slice pair with its
    breaker hold (class-managed: released exactly once by whichever of
    evict/drop/backstop runs first — Hold.release is idempotent)."""

    __slots__ = ("tids", "imps", "pos", "nbytes", "hold")

    def __init__(self, tids, imps, nbytes, hold, pos=None):
        self.tids = tids
        self.imps = imps
        self.pos = pos
        self.nbytes = nbytes
        self.hold = hold

    def retire(self) -> None:
        """Release the breaker hold when the tile's device buffers
        actually DIE, not when the pager forgets them: an evicted tile
        may still be referenced by an in-flight chunk program, and
        releasing while the buffers are live would let new uploads
        overcommit real HBM past what the breaker accounts. CPython
        refcounting makes the release immediate for an unreferenced
        tile; Hold.release stays idempotent either way."""
        try:
            weakref.finalize(self.tids, self.hold.release)
        except TypeError:
            self.hold.release()


class TilePager:
    """Process-global LRU of device-resident pack tiles.

    The lock guards only the residency map bookkeeping; uploads
    (`jax.device_put`) and breaker holds happen OUTSIDE it, so a slow
    host->device tunnel never convoys concurrent searches (graftlint
    lock-discipline: `tiering` is a hot-lock module)."""

    def __init__(self):
        from ..utils import race_guard
        self._mx = threading.Lock()
        # LRU order; every map is declared lock-guarded so the armed
        # race sanitizer trips on any mutation that slips the lock
        self._tiles: dict[tuple, _ResidentTile] = race_guard.guarded_dict(
            self._mx, "tiering.TilePager._tiles")
        self._resident_bytes = 0
        self._stores: dict[str, weakref.ref] = race_guard.guarded_dict(
            self._mx, "tiering.TilePager._stores")
        self._zero_tiles: dict[tuple, tuple] = race_guard.guarded_dict(
            self._mx, "tiering.TilePager._zero_tiles")

    # -- store registry (stats + GC backstop) ------------------------------

    def register_store(self, segment: Segment, store: TileStore) -> None:
        with self._mx:
            self._stores[store.seg_id] = weakref.ref(store)
        # GC backstop: a segment dropped without drop_device() still
        # releases every paged tile's breaker hold. seg_ids are minted
        # fresh per process, so a late finalizer can only ever drop
        # tiles of ITS segment; release is idempotent either way.
        weakref.finalize(segment, self.drop_segment, store.seg_id)

    # -- fetch / evict ------------------------------------------------------

    def fetch(self, store: TileStore, fields: tuple[str, ...],
              tiles: np.ndarray) -> dict:
        """Ensure `tiles` (int array, -1 = chunk padding) of every
        field are device-resident; returns {field: (tids_tuple,
        imps_tuple)} aligned with `tiles`. Misses upload asynchronously
        (device_put), hits reuse the LRU entry; eviction never touches
        the tiles of THIS fetch."""
        import jax
        from ..utils import faults
        from ..utils.breaker import breaker_service
        # fault boundary: breaker_trip / shard_error rules with
        # site=tiering fire here, BEFORE any hold is taken
        faults.on_dispatch("tiering", phase="fetch")
        want = [(f, int(t)) for f in fields for t in tiles if t >= 0]
        keep = {(store.seg_id, f, t) for f, t in want}
        hits: dict[tuple, _ResidentTile] = {}
        missing: list[tuple[str, int]] = []
        with self._mx:
            for f, t in want:
                key = (store.seg_id, f, t)
                if key in hits:
                    continue
                entry = self._tiles.pop(key, None)
                if entry is not None:
                    self._tiles[key] = entry           # LRU touch
                    hits[key] = entry
                else:
                    missing.append((f, t))
        stats.tile_hits.inc(len(hits))
        stats.tile_misses.inc(len(missing))
        fielddata = breaker_service().breaker("fielddata")
        uploaded: dict[tuple, _ResidentTile] = {}
        try:
            for f, t in dict.fromkeys(missing):
                slices = store.tile_slices(f, t)
                tids, imps = slices[0], slices[1]
                pos = slices[2] if len(slices) > 2 else None
                nb = store.tile_nbytes[f]
                hold = fielddata.hold(nb)
                try:
                    entry = _ResidentTile(
                        jax.device_put(tids), jax.device_put(imps), nb,
                        hold, pos=(jax.device_put(pos)
                                   if pos is not None else None))
                except BaseException:
                    hold.release()
                    raise
                uploaded[(store.seg_id, f, t)] = entry
        except BaseException:
            for entry in uploaded.values():
                entry.hold.release()
            raise
        evicted = []
        with self._mx:
            for key, entry in uploaded.items():
                old = self._tiles.pop(key, None)
                if old is not None:
                    # two threads raced the same miss: keep the winner,
                    # give the loser's bytes straight back
                    self._resident_bytes -= old.nbytes
                    evicted.append(old)
                self._tiles[key] = entry
                self._resident_bytes += entry.nbytes
            budget = budget_bytes()
            for key in list(self._tiles):
                if self._resident_bytes <= budget:
                    break
                if key in keep:
                    continue           # never evict the working chunk
                old = self._tiles.pop(key)
                self._resident_bytes -= old.nbytes
                evicted.append(old)
                stats.tile_evictions.inc()
        for old in evicted:
            old.retire()
        out = {}
        resident = {**hits, **uploaded}
        for f in fields:
            fwd = store._fwd[f]
            has_pos = len(fwd) > 2 and fwd[2] is not None
            tids_parts, imps_parts, pos_parts = [], [], []
            for t in tiles:
                if t < 0:
                    z_tids, z_imps, z_pos = self._zero_tile(store, f)
                    tids_parts.append(z_tids)
                    imps_parts.append(z_imps)
                    if has_pos:
                        pos_parts.append(z_pos)
                else:
                    entry = resident[(store.seg_id, f, int(t))]
                    tids_parts.append(entry.tids)
                    imps_parts.append(entry.imps)
                    if has_pos:
                        pos_parts.append(entry.pos)
            out[f] = (tuple(tids_parts), tuple(imps_parts),
                      tuple(pos_parts) if has_pos else None)
        return out

    def _zero_tile(self, store: TileStore, field: str):
        """Shared pad tile (tids -1 = absent term, imps 0, pos -1 =
        empty delta stream): scored docs there can never match, and the
        gathered live mask is False for pad slots anyway. Unaccounted:
        one tile per shape, bounded by the distinct (tile, slot-width,
        pos-width) triples in use."""
        fwd = store._fwd[field]
        tids = fwd[0]
        pos = fwd[2] if len(fwd) > 2 else None
        pos_w = pos.shape[1] if pos is not None else 0
        key = (store.tile, tids.shape[1], pos_w)
        with self._mx:
            z = self._zero_tiles.get(key)
        if z is None:
            import jax
            z = (jax.device_put(np.full((store.tile, tids.shape[1]), -1,
                                        np.int32)),
                 jax.device_put(np.zeros((store.tile, tids.shape[1]),
                                         np.float32)),
                 (jax.device_put(np.full((store.tile, pos_w), -1,
                                         pos.dtype))
                  if pos is not None else None))
            # upload OUTSIDE the lock (device_put under the pager lock
            # would convoy concurrent fetches), then publish under it:
            # two threads racing the same shape keep the first winner
            with self._mx:
                z = self._zero_tiles.setdefault(key, z)
        return z

    def drop_segment(self, seg_id: str) -> None:
        """Release every paged tile (and its breaker hold) of one
        segment — Segment.drop_device() / clear_cache path AND the
        per-segment weakref backstop. Idempotent."""
        with self._mx:
            dead = [k for k in self._tiles if k[0] == seg_id]
            dropped = []
            for k in dead:
                entry = self._tiles.pop(k)
                self._resident_bytes -= entry.nbytes
                dropped.append(entry)
            self._stores.pop(seg_id, None)
        for entry in dropped:
            entry.retire()

    def clear(self) -> None:
        with self._mx:
            dropped = list(self._tiles.values())
            self._tiles.clear()
            self._resident_bytes = 0
            self._stores.clear()
            self._zero_tiles.clear()
        for entry in dropped:
            entry.retire()

    # -- stats --------------------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        with self._mx:
            return self._resident_bytes

    def resident_tiles(self) -> int:
        with self._mx:
            return len(self._tiles)

    def summary_bytes(self) -> int:
        with self._mx:
            refs = list(self._stores.values())
        total = 0
        for r in refs:
            st = r()
            if st is not None:
                total += st.summary_bytes
        return total


pager = TilePager()


# ---------------------------------------------------------------------------
# Segment-level activation
#
# The page/don't-page decision is STICKY per segment, recorded at first
# dispatch (before the first device upload) — flipping the env mid-life
# must not strand a pack whose forward index was never uploaded on the
# non-tiered read path, or vice versa.
# ---------------------------------------------------------------------------


def activate(segment: Segment) -> frozenset:
    """Decide (once) and return the segment's paged field set. Empty
    set = fully resident. The decision compares the WHOLE pack footprint
    (resident columns + forward index) against the budget, so a pack
    that fits keeps the fully-resident fast path."""
    rec = getattr(segment, "_tiering_paged", None)
    if rec is not None:
        return rec
    paged: frozenset = frozenset()
    if enabled():
        store = store_for(segment)
        if store is not None and store.pageable():
            pack_bytes = segment.nbytes() + store.paged_bytes
            if pack_bytes > budget_bytes():
                paged = frozenset(store.fields)
            else:
                stats.fast_path_full_resident.inc()
    segment._tiering_paged = paged  # type: ignore[attr-defined]
    return paged


def paged_fields(segment: Segment) -> frozenset:
    """The recorded paged field set (empty when undecided or fully
    resident) — readers that must not trigger a decision."""
    rec = getattr(segment, "_tiering_paged", None)
    return rec if rec is not None else frozenset()


def clear_paged(segment: Segment) -> None:
    """Un-page a segment (the unfused full-residency fallback uploaded
    its forward index): drop its tiles and record the empty set so
    later dispatches take the ordinary path."""
    pager.drop_segment(segment.seg_id)
    segment._tiering_paged = frozenset()  # type: ignore[attr-defined]


def store_for(segment: Segment) -> TileStore | None:
    """The segment's (cached) TileStore; None when it has no pageable
    column. Registration attaches the GC backstop exactly once."""
    store = getattr(segment, "_tile_store", None)
    if store is None:
        store = TileStore(segment)
        if not store.pageable():
            segment._tile_store = store  # type: ignore[attr-defined]
            return None
        segment._tile_store = store  # type: ignore[attr-defined]
        pager.register_store(segment, store)
    return store if store.pageable() else None


def drop_segment_tiles(seg_id: str) -> None:
    pager.drop_segment(seg_id)


def note_prune_skipped(n: int) -> None:
    if n > 0:
        stats.prune_skipped_fetches.inc(n)


def record_overlap_ms(ms: float) -> None:
    stats.prefetch_overlap_ms.record(round(float(ms), 3))


def stats_snapshot() -> dict:
    """nodes_stats()["fused_scoring"]["tiering"] block."""
    return {
        "enabled": enabled(),
        "budget_bytes": budget_bytes() if enabled() else None,
        "chunk_tiles": chunk_tiles(),
        "resident_bytes": pager.resident_bytes,
        "resident_tiles": pager.resident_tiles(),
        "summary_bytes": pager.summary_bytes(),
        "tile_hits": stats.tile_hits.count,
        "tile_misses": stats.tile_misses.count,
        "tile_evictions": stats.tile_evictions.count,
        "prune_skipped_fetches": stats.prune_skipped_fetches.count,
        "tiered_dispatches": stats.tiered_dispatches.count,
        "fast_path_full_resident": stats.fast_path_full_resident.count,
        "unfused_full_uploads": stats.unfused_full_uploads.count,
        "mesh_full_resident_rows": stats.mesh_full_resident_rows.count,
        "prefetch_overlap_ms": {
            "high_water": round(float(stats.prefetch_overlap_ms.max), 3),
            "last": round(float(stats.prefetch_overlap_ms.last), 3),
        },
    }


def breaker_split() -> dict:
    """Summary-vs-paged residency split for the fielddata breaker's
    node-stats entry (the summaries ride the ordinary device_arrays
    hold; the paged bytes ride per-tile pager holds)."""
    return {"summary_bytes": pager.summary_bytes(),
            "paged_bytes": pager.resident_bytes}
