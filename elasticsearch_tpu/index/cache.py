"""Shard request (query-result) cache.

Reference analog: indices/cache/query/IndicesQueryCache.java (the 1.x
ShardQueryCache): caches the whole shard-level result of size=0
(aggregation/count) requests, keyed on the request bytes, invalidated
when the shard refreshes. Enabled per index via
`index.cache.query.enable` or per request via the `query_cache`
parameter; results containing date-math "now" are never cached.

TPU-first adaptation: entries hang off the ShardReader (the immutable
point-in-time view published at refresh) through a WeakKeyDictionary, so
invalidation is structural — a refresh publishes a new reader and the
old reader's entries vanish with it, no epoch bookkeeping. The cached
value is the shard response INCLUDING agg partials (numpy arrays), so a
hit skips the whole bind/execute path; copies guard both store and load
against downstream mutation.
"""

from __future__ import annotations

import copy
import json
import threading
import weakref
from collections import OrderedDict

import numpy as np


def canonical_key(body: dict) -> str:
    """Stable request identity (the reference hashes request bytes)."""
    return json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str)


def _estimate_bytes(obj) -> int:
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes) + 64
    if isinstance(obj, dict):
        return 64 + sum(_estimate_bytes(k) + _estimate_bytes(v)
                        for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return 32 + sum(_estimate_bytes(v) for v in obj)
    if isinstance(obj, (bytes, str)):
        return len(obj) + 40
    return 24


class ShardRequestCache:
    """One index's request cache + its lifetime stats.

    Stats survive refreshes (ref: ShardRequestCache stats in
    CommonStats), entries do not.
    """

    def __init__(self, max_entries_per_reader: int = 256):
        self._readers: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._lock = threading.Lock()
        self.max_entries = max_entries_per_reader
        self.hit_count = 0
        self.miss_count = 0
        self.evictions = 0

    def get(self, reader, key: str):
        with self._lock:
            entries = self._readers.get(reader)
            hit = entries.get(key) if entries is not None else None
            if hit is None:
                self.miss_count += 1
                return None
            entries.move_to_end(key)
            self.hit_count += 1
            stored = hit[0]
        # deepcopy OUTSIDE the lock: agg partials can be large numpy
        # arrays and concurrent hits must not serialize on each other
        return copy.deepcopy(stored)

    def put(self, reader, key: str, response: dict) -> None:
        stored = copy.deepcopy(response)
        nbytes = len(key) + _estimate_bytes(stored)
        with self._lock:
            entries = self._readers.get(reader)
            if entries is None:
                entries = OrderedDict()
                self._readers[reader] = entries
            entries[key] = (stored, nbytes)
            entries.move_to_end(key)
            while len(entries) > self.max_entries:
                entries.popitem(last=False)
                self.evictions += 1

    def memory_size_in_bytes(self) -> int:
        with self._lock:
            return sum(nb for entries in self._readers.values()
                       for _, nb in entries.values())

    def entry_count(self) -> int:
        with self._lock:
            return sum(len(e) for e in self._readers.values())

    def clear(self) -> None:
        with self._lock:
            self._readers.clear()

    def stats(self) -> dict:
        return {"memory_size_in_bytes": self.memory_size_in_bytes(),
                "evictions": self.evictions,
                "hit_count": self.hit_count,
                "miss_count": self.miss_count}


def cacheable(shard_body: dict, index_enabled: bool) -> bool:
    """Ref: IndicesQueryCache.canCache — only whole-shard size=0
    results, no per-request randomness, request override wins. The
    body-serializing "now" scan runs only after the cheap gates, so
    cache-disabled indexes never pay it."""
    override = shard_body.get("query_cache",
                              shard_body.get("request_cache"))
    if override is False or str(override).lower() == "false":
        return False
    if override not in (True, "true") and not index_enabled:
        return False
    if int(shard_body.get("size", 10)) != 0:
        return False
    if "_dfs_stats" in shard_body:
        return False  # global stats vary with the shard set
    # date-math "now" resolves per execution: only VALUE strings that
    # are exactly "now" or start a date-math expression ("now-1d",
    # "now+1h", "now/d") block caching — not words like "nowhere"
    import re
    return not re.search(r':"now(["+\-/|]|\\)', canonical_key(shard_body))
