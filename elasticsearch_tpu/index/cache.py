"""Shard request (query-result) cache — generation-exact, device-skip.

Reference analog: indices/cache/query/IndicesQueryCache.java (the 1.x
ShardQueryCache): caches whole shard-level results keyed on the request
bytes, invalidated when the shard refreshes. Enabled per index via
`index.cache.query.enable` or per request via the `query_cache`
parameter; results containing date-math "now" are never cached.

TPU-first adaptation (traffic control plane, ROADMAP item 5): entries
key on the reader's **generation key** — per segment,
`Segment.cache_key()` (content fingerprint for bases; `(base
generation, pow2 delta extent)` for streaming deltas) + the delta
epoch + a live-mask digest — plus the canonical request body. That
key is exact by construction:

  * a warm repeat of an identical query is a pure host-side dict copy:
    ZERO device dispatches, transfers, or compiles (the scheduler
    never sees a job);
  * a delta refresh (`ES_TPU_DELTA_PACK`) bumps the delta epoch — the
    new generation misses and re-executes (correct fresh results)
    while the cache itself is NOT flushed: other shards'/generations'
    entries and all stats survive, stale generations age out via LRU;
  * a compaction / force-merge re-keys the base fingerprint — exactly
    the invalidation signal, nothing else evicts;
  * deletes flip live masks, which changes the digest — a masked-out
    doc can never be served from cache.

Device-skip: with `index.cache.query.include_hits` (or request
`query_cache=true` on a sized request) the cache stores FULL top-k
responses, not just size=0 agg results — a hot dashboard query repays
its one device dispatch across every repeat. The cached value is the
shard response INCLUDING agg partials (numpy arrays); copies guard
both store and load against downstream mutation.

Caveat shared with every fingerprint-keyed cache in this codebase
(autotune store, resident entries): `Segment.fingerprint()` hashes the
pack's shape-and-statistics signature, not raw bytes — the established
identity convention since PR 1.
"""

from __future__ import annotations

import copy
import json
import threading
import weakref
from collections import OrderedDict

import numpy as np


def canonical_key(body: dict) -> str:
    """Stable request identity (the reference hashes request bytes)."""
    return json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str)


def _estimate_bytes(obj) -> int:
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes) + 64
    if isinstance(obj, dict):
        return 64 + sum(_estimate_bytes(k) + _estimate_bytes(v)
                        for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return 32 + sum(_estimate_bytes(v) for v in obj)
    if isinstance(obj, (bytes, str)):
        return len(obj) + 40
    return 24


def _anchor(reader):
    """Generation anchor for a reader: the content-exact generation key
    when the reader provides one (ShardReader.generation_key), else a
    weakref to the object (unit-test stand-ins) — identity keying like
    the pre-generation cache, but reuse-proof: a dead reader's ref can
    never equal a new reader's, even at the same recycled address
    (a raw id() key could serve another reader's stale entries)."""
    gk = getattr(reader, "generation_key", None)
    if callable(gk):
        return gk()
    try:
        return weakref.ref(reader)
    except TypeError:
        return ("__obj__", id(reader))


class ShardRequestCache:
    """One index's request cache + its lifetime stats.

    Flat LRU over (generation anchor, request key): touching any entry
    refreshes it; stale generations stop being touched and fall off
    the cold end. Stats survive refreshes AND re-keys (ref:
    ShardRequestCache stats in CommonStats); nothing short of
    clear()/_cache API wipes entries wholesale."""

    def __init__(self, max_entries: int = 1024,
                 max_bytes: int = 64 * 1024 * 1024):
        from ..utils import race_guard
        self._lock = threading.Lock()
        # (anchor, key) -> (stored_response, nbytes)
        self._entries: "OrderedDict[tuple, tuple]" = \
            race_guard.guarded_odict(
                self._lock, "cache.ShardRequestCache._entries")
        self.max_entries = max_entries
        # byte cap (ref: indices.requests.cache.size): include_hits
        # entries carry full top-k payloads, so a count-only bound
        # could pin unbounded memory across stale generations
        self.max_bytes = max_bytes
        self._bytes = 0
        self.hit_count = 0
        self.miss_count = 0
        self.evictions = 0

    def get(self, reader, key: str):
        full_key = (_anchor(reader), key)
        with self._lock:
            hit = self._entries.get(full_key)
            if hit is None:
                self.miss_count += 1
                return None
            self._entries.move_to_end(full_key)
            self.hit_count += 1
            stored = hit[0]
        # deepcopy OUTSIDE the lock: agg partials can be large numpy
        # arrays and concurrent hits must not serialize on each other
        return copy.deepcopy(stored)

    def put(self, reader, key: str, response: dict) -> None:
        stored = copy.deepcopy(response)
        nbytes = len(key) + _estimate_bytes(stored)
        full_key = (_anchor(reader), key)
        with self._lock:
            old = self._entries.get(full_key)
            if old is not None:
                self._bytes -= old[1]
            self._entries[full_key] = (stored, nbytes)
            self._entries.move_to_end(full_key)
            self._bytes += nbytes
            while self._entries and (
                    len(self._entries) > self.max_entries
                    or self._bytes > self.max_bytes):
                _, (_v, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                self.evictions += 1

    def memory_size_in_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def generation_count(self) -> int:
        """Distinct generation anchors currently holding entries — how
        many point-in-time views (incl. stale ones not yet aged out)
        the cache spans."""
        with self._lock:
            return len({a for a, _k in self._entries})

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        # one lock for the whole snapshot: counters move together under
        # _lock, so reading them piecemeal could tear (hits + misses
        # from different get() generations)
        with self._lock:
            return {"memory_size_in_bytes": self._bytes,
                    "evictions": self.evictions,
                    "hit_count": self.hit_count,
                    "miss_count": self.miss_count}


def cacheable(shard_body: dict, index_enabled: bool,
              include_hits: bool = False) -> bool:
    """Ref: IndicesQueryCache.canCache — request override wins, then
    the index enable flag. size=0 (agg/count) results always qualify
    once enabled; sized (top-k hits) results qualify only when the
    index opted into device-skip hit caching (`include_hits`) or the
    request itself said `query_cache=true` — the generation key makes
    them exactly as safe, but hit payloads are bigger, so the wider
    mode is opt-in. The body-serializing "now" scan runs only after
    the cheap gates, so cache-disabled indexes never pay it."""
    override = shard_body.get("query_cache",
                              shard_body.get("request_cache"))
    if override is False or str(override).lower() == "false":
        return False
    forced = override in (True, "true")
    if not forced and not index_enabled:
        return False
    if int(shard_body.get("size", 10)) != 0 \
            and not (include_hits or forced):
        return False
    if "_dfs_stats" in shard_body:
        return False  # global stats vary with the shard set
    key = canonical_key(shard_body)
    # per-request randomness: an unseeded random_score re-draws per
    # execution; conservatively refuse any random_score body
    if '"random_score"' in key:
        return False
    # date-math "now" resolves per execution: only VALUE strings that
    # are exactly "now" or start a date-math expression ("now-1d",
    # "now+1h", "now/d") block caching — not words like "nowhere"
    import re
    return not re.search(r':"now(["+\-/|]|\\)', key)
