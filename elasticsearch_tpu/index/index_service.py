"""Per-index service: settings + mapper + shard engines.

Reference analog: the per-index injector the reference builds
(index/IndexService via indices/IndicesService.java) holding
MapperService, AnalysisService and the index's IndexShards.
"""

from __future__ import annotations

import os

from ..utils.settings import Settings
from ..utils.errors import ShardNotFoundError, DocumentMissingError
from ..cluster.routing import shard_id as route_shard
from .mapping import MapperService
from .engine import Engine


class IndexService:
    def __init__(self, name: str, settings: Settings = Settings.EMPTY,
                 mapping: dict | None = None, data_path: str | None = None):
        self.name = name
        self.settings = settings
        self.num_shards = settings.get_int("index.number_of_shards", 1)
        self.num_replicas = settings.get_int("index.number_of_replicas", 0)
        self.mappers = MapperService(settings, mapping)
        self.data_path = data_path
        self.shards: dict[int, Engine] = {}
        for s in range(self.num_shards):
            path = None
            if data_path:
                path = os.path.join(data_path, name, str(s))
                os.makedirs(path, exist_ok=True)
            self.shards[s] = Engine(name, s, self.mappers, path=path,
                                    settings=settings)
        from ..percolator import PercolatorRegistry
        self.percolator = PercolatorRegistry(
            os.path.join(data_path, name) if data_path else None)

    def percolate(self, doc: dict, percolate_filter: dict | None = None,
                  size: int | None = None) -> dict:
        from ..percolator import percolate as run
        return run(self.percolator, self.mappers, self.name, doc,
                   percolate_filter, size, index_settings=self.settings)

    def shard(self, sid: int) -> Engine:
        eng = self.shards.get(sid)
        if eng is None:
            raise ShardNotFoundError(self.name, sid)
        return eng

    def shard_for(self, doc_id: str, routing: str | None = None) -> Engine:
        return self.shard(route_shard(doc_id, self.num_shards, routing))

    # -- write path --------------------------------------------------------
    def index_doc(self, doc_id: str, source, version: int | None = None,
                  routing: str | None = None) -> dict:
        r = self.shard_for(doc_id, routing).index(doc_id, source, version)
        r.update({"_index": self.name, "_type": "_doc",
                  "_shards": {"total": 1 + self.num_replicas,
                              "successful": 1, "failed": 0}})
        return r

    def delete_doc(self, doc_id: str, version: int | None = None,
                   routing: str | None = None) -> dict:
        r = self.shard_for(doc_id, routing).delete(doc_id, version)
        r["_index"] = self.name
        return r

    def get_doc(self, doc_id: str, routing: str | None = None) -> dict:
        r = self.shard_for(doc_id, routing).get(doc_id)
        r["_index"] = self.name
        r["_type"] = "_doc"
        return r

    # -- maintenance -------------------------------------------------------
    def refresh(self) -> None:
        for eng in self.shards.values():
            eng.refresh()

    def flush(self) -> None:
        for eng in self.shards.values():
            eng.flush()

    def force_merge(self, max_num_segments: int = 1) -> None:
        for eng in self.shards.values():
            eng.force_merge(max_num_segments)

    def doc_count(self) -> int:
        return sum(e.doc_count() for e in self.shards.values())

    def stats(self) -> dict:
        seg = [e.segment_stats() for e in self.shards.values()]
        return {
            "docs": {"count": self.doc_count()},
            "segments": {"count": sum(s["count"] for s in seg),
                         "memory_in_bytes": sum(s["memory_in_bytes"] for s in seg)},
            "shards": {str(i): s for i, s in enumerate(seg)},
        }

    def close(self) -> None:
        for eng in self.shards.values():
            eng.close()
