"""Per-index service: settings + mapper + shard engines.

Reference analog: the per-index injector the reference builds
(index/IndexService via indices/IndicesService.java) holding
MapperService, AnalysisService and the index's IndexShards.
"""

from __future__ import annotations

import os
import threading

from ..utils.settings import Settings
from ..utils.errors import ShardNotFoundError, DocumentMissingError
from ..cluster.routing import shard_id as route_shard
from .mapping import MapperService
from .engine import Engine
from .stats import IndexOpStats


class IndexService:
    def __init__(self, name: str, settings: Settings = Settings.EMPTY,
                 mapping: dict | None = None, data_path: str | None = None,
                 type_mappings: dict | None = None):
        self.name = name
        self.settings = settings
        self.num_shards = settings.get_int("index.number_of_shards", 1)
        self.num_replicas = settings.get_int("index.number_of_replicas", 0)
        self.mappers = MapperService(settings, mapping,
                                     type_mappings=type_mappings)
        self.data_path = data_path
        self.shards: dict[int, Engine] = {}
        for s in range(self.num_shards):
            path = None
            if data_path:
                path = os.path.join(data_path, name, str(s))
                os.makedirs(path, exist_ok=True)
            self.shards[s] = Engine(name, s, self.mappers, path=path,
                                    settings=settings)
        from ..percolator import PercolatorRegistry
        self.percolator = PercolatorRegistry(
            os.path.join(data_path, name) if data_path else None)
        # per-doc mapping type (ref: the _uid = type#id identity of
        # index/mapper/internal/UidFieldMapper.java; we keep a single
        # type per id — last write wins — which covers the REST
        # contract: typed get/delete must match, _all returns it)
        self.doc_types: dict[str, str] = {}
        # per-doc routing value when one was supplied at index time
        # (ref: index/mapper/internal/RoutingFieldMapper.java)
        self.doc_routing: dict[str, str] = {}
        # per-doc parent id (ref: ParentFieldMapper; parent routes the doc)
        self.doc_parent: dict[str, str] = {}
        # per-doc index timestamp millis (ref: TimestampFieldMapper)
        self.doc_ts: dict[str, int] = {}
        # mapping type names declared via create-index/put-mapping
        # (rendered in GET _mapping; distinct from per-doc types above)
        self.mapping_types: set[str] = set()
        # operation counters feeding the _stats API
        # (ref: action/admin/indices/stats/CommonStats.java)
        self.op_stats = IndexOpStats()
        # engines record pack-build wall-time/docs here (the
        # indices_stats indexing block's build_* fields)
        for eng in self.shards.values():
            eng.op_stats = self.op_stats
        # shard request cache (ref: indices/cache/query/
        # IndicesQueryCache.java) — generation-keyed (index/cache.py):
        # entries are invalidated exactly by compaction / delta-epoch
        # re-keys, never flushed by refresh; stats live here
        from .cache import ShardRequestCache
        self.request_cache = ShardRequestCache(
            max_entries=self.settings.get_int(
                "index.cache.query.max_entries", 1024),
            max_bytes=self.settings.get_int(
                "index.cache.query.max_bytes", 64 * 1024 * 1024))
        # engine-write + metadata updates for ONE doc id must be atomic
        # (a concurrent delete interleaving between them could pop
        # metadata a write just recorded), but writes to DIFFERENT ids
        # must stay parallel across shards — so stripe locks by id and
        # keep a single lock only for the shared _types.json tmp file
        self._id_locks = [threading.Lock() for _ in range(16)]
        self._meta_lock = threading.Lock()
        self._types_path = (os.path.join(data_path, name, "_types.json")
                            if data_path else None)
        if self._types_path and os.path.exists(self._types_path):
            import json
            with open(self._types_path) as f:
                meta = json.load(f)
            if "types" in meta or "routing" in meta or "parent" in meta:
                self.doc_types = meta.get("types", {})
                self.doc_routing = meta.get("routing", {})
                self.doc_parent = meta.get("parent", {})
                self.doc_ts = meta.get("ts", {})
            else:   # legacy flat {id: type} layout
                self.doc_types = meta

    def _id_lock(self, doc_id: str) -> threading.Lock:
        return self._id_locks[hash(doc_id) % len(self._id_locks)]

    def percolate(self, doc: dict, percolate_filter: dict | None = None,
                  size: int | None = None) -> dict:
        from ..percolator import percolate as run
        return run(self.percolator, self.mappers, self.name, doc,
                   percolate_filter, size, index_settings=self.settings)

    def shard(self, sid: int) -> Engine:
        eng = self.shards.get(sid)
        if eng is None:
            raise ShardNotFoundError(self.name, sid)
        return eng

    def shard_for(self, doc_id: str, routing: str | None = None) -> Engine:
        return self.shard(route_shard(doc_id, self.num_shards, routing))

    # -- write path --------------------------------------------------------
    def index_doc(self, doc_id: str, source, version: int | None = None,
                  routing: str | None = None,
                  doc_type: str | None = None,
                  version_type: str = "internal",
                  parent: str | None = None,
                  timestamp_ms: int | None = None) -> dict:
        routing = routing if routing is not None else parent
        with self._id_lock(doc_id):
            r = self.shard_for(doc_id, routing).index(
                doc_id, source, version, version_type=version_type)
            meta_dirty = False
            if timestamp_ms is not None:
                # recorded under the id lock so the persisted snapshot
                # always includes the triggering write's timestamp
                meta_dirty |= self.doc_ts.get(doc_id) != timestamp_ms
                self.doc_ts[doc_id] = timestamp_ms
            if parent is not None:
                meta_dirty |= self.doc_parent.get(doc_id) != str(parent)
                self.doc_parent[doc_id] = str(parent)
            else:
                meta_dirty |= self.doc_parent.pop(doc_id, None) is not None
            if doc_type and doc_type != "_doc":
                meta_dirty |= self.doc_types.get(doc_id) != doc_type
                self.doc_types[doc_id] = doc_type
            else:
                meta_dirty |= self.doc_types.pop(doc_id, None) is not None
            if routing is not None:
                meta_dirty |= self.doc_routing.get(doc_id) != str(routing)
                self.doc_routing[doc_id] = str(routing)
            else:
                meta_dirty |= self.doc_routing.pop(doc_id, None) is not None
            # response type must be read under the same lock, or a
            # concurrent delete could make a typed write report _doc
            resp_type = self.doc_types.get(doc_id, "_doc")
            if meta_dirty:
                # write-through: the engine's translog made the DOC durable
                # at this point, so its type/routing metadata must be
                # durable too (crash between here and flush must not turn
                # a typed get into a 404 after replay)
                self._save_types()
        r.update({"_index": self.name,
                  "_type": resp_type,
                  "_shards": {"total": 1 + self.num_replicas,
                              "successful": 1, "failed": 0}})
        self.op_stats.on_index(doc_type)
        return r

    def _check_type(self, doc_id: str, doc_type: str | None) -> str:
        stored = self.doc_types.get(doc_id, "_doc")
        if doc_type not in (None, "_all", stored):
            raise DocumentMissingError(self.name, doc_id)
        return stored

    def delete_doc(self, doc_id: str, version: int | None = None,
                   routing: str | None = None,
                   doc_type: str | None = None,
                   version_type: str = "internal") -> dict:
        with self._id_lock(doc_id):
            # type check + stored-type read belong under the same lock as
            # the engine op (symmetric with index_doc's resp_type read)
            stored = self._check_type(doc_id, doc_type)
            r = self.shard_for(doc_id, routing).delete(
                doc_id, version, version_type=version_type)
            # only clear metadata when the engine actually removed the doc:
            # a routed doc deleted without routing hits the wrong shard and
            # returns found:false — its type/routing must survive
            if r.get("found"):
                dirty = self.doc_types.pop(doc_id, None) is not None
                dirty |= self.doc_routing.pop(doc_id, None) is not None
                dirty |= self.doc_parent.pop(doc_id, None) is not None
                self.doc_ts.pop(doc_id, None)
                if dirty:
                    self._save_types()
        r["_index"] = self.name
        r["_type"] = stored
        r["_shards"] = {"total": 1 + self.num_replicas,
                        "successful": 1, "failed": 0}
        self.op_stats.on_delete()
        return r

    def get_doc(self, doc_id: str, routing: str | None = None,
                doc_type: str | None = None, realtime: bool = True) -> dict:
        try:
            stored = self._check_type(doc_id, doc_type)
            r = self.shard_for(doc_id, routing).get(doc_id,
                                                    realtime=realtime)
        except DocumentMissingError:
            self.op_stats.on_get(found=False)
            raise
        self.op_stats.on_get(found=bool(r.get("found", True)))
        r["_index"] = self.name
        r["_type"] = stored
        if doc_id in self.doc_routing:
            r["_routing"] = self.doc_routing[doc_id]
        if doc_id in self.doc_parent:
            r["_parent"] = self.doc_parent[doc_id]
        return r

    def doc_type_of(self, doc_id: str) -> str:
        return self.doc_types.get(doc_id, "_doc")

    def _save_types(self) -> None:
        if self._types_path is None:
            return
        import json
        with self._meta_lock:
            # snapshot INSIDE the file lock so the last write always
            # reflects every previously completed mutation (a snapshot
            # taken before the lock could overwrite a newer file with
            # older state); dict() of a str-keyed dict is GIL-atomic, so
            # concurrent id-stripe holders can't corrupt the copy
            snap = {"types": dict(self.doc_types),
                    "routing": dict(self.doc_routing),
                    "parent": dict(self.doc_parent),
                    "ts": dict(self.doc_ts)}
            tmp = self._types_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, self._types_path)

    # -- maintenance -------------------------------------------------------
    def refresh(self) -> None:
        from .stats import timed
        with timed() as t:
            for eng in self.shards.values():
                eng.refresh()
        self.op_stats.on_refresh(t.ms)

    def flush(self) -> None:
        from .stats import timed
        with timed() as t:
            for eng in self.shards.values():
                eng.flush()
            self._save_types()
        self.op_stats.on_flush(t.ms)

    def force_merge(self, max_num_segments: int = 1) -> None:
        from .stats import timed
        with timed() as t:
            for eng in self.shards.values():
                eng.force_merge(max_num_segments)
        self.op_stats.on_merge(t.ms)

    def doc_count(self) -> int:
        return sum(e.doc_count() for e in self.shards.values())

    def stats(self) -> dict:
        seg = [e.segment_stats() for e in self.shards.values()]
        return {
            "docs": {"count": self.doc_count()},
            "segments": {"count": sum(s["count"] for s in seg),
                         "memory_in_bytes": sum(s["memory_in_bytes"] for s in seg)},
            "shards": {str(i): s for i, s in enumerate(seg)},
        }

    def close(self) -> None:
        for eng in self.shards.values():
            eng.close()
        self._save_types()
