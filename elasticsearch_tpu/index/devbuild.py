"""Device-parallel pack build — the host driver (ops/build.py holds
the jitted programs).

Pack build was the last single-host-thread stage of the engine: every
refresh, compaction, mesh repack and ANN build funneled through the
per-term Python loops of `segment._pack_layout` and the per-doc dict
accumulation of `SegmentBuilder.build`. This module moves the heavy
half onto the hardware as batched JAX programs:

  host   tokenizes, hashes terms (np.unique) and finalizes term dicts;
  device sorts the (term-id, doc) occurrence stream, segments it into
         postings, packs 128-lane blocks + the forward index, and
         scatter-maxes the block-max tile summary;
  host   computes eager BM25 impacts in the CANONICAL path
         (`segment._flat_impacts`) — float math stays where its bits
         are already defined.

Identity contract: a device-built Segment is BYTE-IDENTICAL to the
host builder's — same `fingerprint()`/`cache_key()`, same eager
impacts bit-for-bit, same tile_max/extrema — because every device
program is exact (see ops/build.py). Every fingerprint-keyed cache,
the autotune store, resident entries and the streaming-delta keying
invariant are therefore untouched by the builder swap.

One path feeds all three consumers: `SegmentBuilder.build` (refresh +
merge_segments, which repack's build-aside uses) and
`concat_segments` (compaction) route their layout pass through
`segment._pack_layout`, whose dispatch seam lands here; the IVF
k-means of `ann.build_ann` promotes through `ops.build.kmeans_device`.

Opt-in: `index.build.device` setting / `ES_TPU_DEVICE_BUILD` env (the
`ann.configure` convention). Any device error falls back to the host
builder automatically (fault-injectable at `site=build`), counted
under `nodes_stats()["indexing"]["device_build"]`.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

from ..utils import faults

logger = logging.getLogger("elasticsearch_tpu.devbuild")

_TRUE = ("1", "true", "on", "yes")

# guards the module config (configure/reset tokens, the ann.py idiom)
_cfg_lock = threading.Lock()
_cfg_enabled: bool | None = None
_cfg_token = 0

# per-thread scope override: the engine's compaction wraps its
# build-aside in enable_scope() so the per-index `index.build.device`
# setting reaches the _pack_layout dispatch seam without flipping the
# process-global flag under concurrent engines
_tls = threading.local()

# guards the build counters surfaced in nodes_stats
_stats_lock = threading.Lock()
_stats = {
    "builds_device": 0,        # full builder.build runs on the device path
    "builds_fallback": 0,      # device errors that fell back to host
    "build_skipped": 0,        # rebuilds short-circuited (deletes-only)
    "docs_device": 0,          # rows ingested through device builds
    "build_device_ms": 0.0,    # wall-time of device builds
    "pack_layout_device": 0,   # _pack_layout calls served by the device
    "kmeans_device": 0,        # IVF k-means loops run on the device
    "tile_minmax_device": 0,   # numeric tile summaries on the device
    "pack_positions_device": 0,  # positional column packs on the device
}


def configure(enabled: bool | None = None) -> int:
    """Set the process-global device-build default; returns a token for
    scoped reset (the ann.configure convention)."""
    global _cfg_enabled, _cfg_token
    with _cfg_lock:
        _cfg_enabled = enabled
        _cfg_token += 1
        return _cfg_token


def reset(if_current: int | None = None) -> None:
    global _cfg_enabled, _cfg_token
    with _cfg_lock:
        if if_current is not None and if_current != _cfg_token:
            return
        _cfg_enabled = None
        _cfg_token += 1


def device_build_default() -> bool:
    """The configured/env default — what an engine without an explicit
    `index.build.device` setting uses. Env wins (read at call time so
    tests can flip it)."""
    env = os.environ.get("ES_TPU_DEVICE_BUILD")
    if env is not None:
        return env.strip().lower() in _TRUE
    with _cfg_lock:
        return bool(_cfg_enabled)


def enabled() -> bool:
    """Whether the _pack_layout/_kmeans dispatch seams take the device
    path right now: a thread-scoped override (enable_scope) beats the
    process default."""
    ov = getattr(_tls, "override", None)
    if ov is not None:
        return bool(ov)
    return device_build_default()


class enable_scope:
    """Thread-scoped device-build override (nestable): the engine's
    per-index setting rides through module-level seams on this."""

    def __init__(self, on: bool = True):
        self._on = bool(on)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "override", None)
        _tls.override = self._on
        return self

    def __exit__(self, *exc):
        _tls.override = self._prev
        return False


def _bump(key: str, dv=1) -> None:
    with _stats_lock:
        _stats[key] += dv


def count_skipped(stage: str = "") -> None:
    """A rebuild that was short-circuited because only deletes changed
    (live-mask flips): the source column set is unchanged, so the
    existing pack/ANN index is still exact."""
    _bump("build_skipped")


def on_fallback(stage: str, err: BaseException | None = None) -> None:
    _bump("builds_fallback")
    logger.warning("device build fell back to host at %s: %s", stage,
                   err if err is not None else "error", exc_info=err)


def stats() -> dict:
    """Snapshot for nodes_stats()["indexing"]["device_build"]."""
    with _stats_lock:
        out = dict(_stats)
    ms = out["build_device_ms"]
    out["docs_per_s"] = (out["docs_device"] / (ms / 1000.0)) if ms else 0.0
    return out


def reset_stats() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0 if k != "build_device_ms" else 0.0


# ---------------------------------------------------------------------------
# full builder path (engine refresh / merge rebuild)
# ---------------------------------------------------------------------------


def build_segment(builder, seg_id: str | None = None, *,
                  index: str | None = None, shard: int | None = None):
    """Device-parallel SegmentBuilder.build: same accumulation
    semantics, postings construction on the device, automatic host
    fallback on any device error (fault site=build, phase=build)."""
    from .segment import SegmentBuilder
    if seg_id is None:
        SegmentBuilder._counter += 1
        seg_id = f"seg_{SegmentBuilder._counter}"
    try:
        faults.on_dispatch("build", index=index, shard=shard,
                           phase="build")
        t0 = time.monotonic()
        seg = _build_device(builder, seg_id)
        with _stats_lock:
            _stats["builds_device"] += 1
            _stats["docs_device"] += seg.num_docs
            _stats["build_device_ms"] += (time.monotonic() - t0) * 1000.0
        return seg
    except Exception as e:
        on_fallback("build_segment", e)
        return builder.build(seg_id)


def _build_device(builder, seg_id: str):
    """Mirror of SegmentBuilder.build with text fields accumulated as
    flat occurrence streams (the device sort's input) instead of
    per-doc posting dicts. Every non-text column delegates to the
    vectorized builders below (or the host statics for the rare
    multi-valued/ragged shapes), so the resulting Segment is
    byte-identical to `builder.build(seg_id)`."""
    from .mapping import TEXT, KEYWORD, DENSE_VECTOR, GEO_POINT
    from .segment import (
        BLOCK, CompletionColumn, Segment, SegmentBuilder, next_pow2,
    )
    n = len(builder.docs)
    cap = next_pow2(n, floor=BLOCK)

    ids: list[str] = []
    id_map: dict[str, int] = {}
    sources: list[bytes] = []
    occ_tokens: dict[str, list[str]] = {}
    occ_docs: dict[str, list[np.ndarray]] = {}
    occ_pos: dict[str, list[np.ndarray]] = {}
    text_doclen: dict[str, np.ndarray] = {}
    kw_values: dict[str, dict[int, list[str]]] = {}
    num_values: dict[str, tuple[str, dict[int, list]]] = {}
    vec_values: dict[str, dict[int, list[float]]] = {}
    geo_values: dict[str, dict[int, tuple[float, float]]] = {}
    comp_values: dict[str, list[tuple[int, dict]]] = {}

    for d, doc in enumerate(builder.docs):
        ids.append(doc.doc_id)
        id_map[doc.doc_id] = d
        sources.append(doc.source)
        # same multi-field semantics as the host builder: text
        # concatenates tokens per doc; keyword/numeric accumulate
        # value lists; vector/geo keep first; completion appends
        doc_tokens: dict[str, list[str]] = {}
        for pf in doc.fields:
            if pf.type == TEXT:
                doc_tokens.setdefault(pf.name, []).extend(pf.tokens or [])
            elif pf.type == KEYWORD:
                col = kw_values.setdefault(pf.name, {})
                col.setdefault(d, []).append(str(pf.value))
            elif pf.type == DENSE_VECTOR:
                vcol = vec_values.setdefault(pf.name, {})
                if d not in vcol:
                    vcol[d] = pf.value  # type: ignore[assignment]
            elif pf.type == GEO_POINT:
                gcol = geo_values.setdefault(pf.name, {})
                if d not in gcol:
                    gcol[d] = pf.value
            elif pf.type == "completion":
                comp_values.setdefault(pf.name, []).append((d, pf.value))
            else:
                kind, col = num_values.setdefault(pf.name, (pf.type, {}))
                col.setdefault(d, []).append(pf.value)
        for fname, toks in doc_tokens.items():
            if fname not in text_doclen:
                text_doclen[fname] = np.zeros(cap, dtype=np.float32)
                occ_tokens[fname] = []
                occ_docs[fname] = []
                occ_pos[fname] = []
            text_doclen[fname][d] += float(len(toks))
            occ_tokens[fname].extend(toks)
            occ_docs[fname].append(np.full(len(toks), d, dtype=np.int32))
            occ_pos[fname].append(np.arange(len(toks), dtype=np.int32))

    text = {
        name: _build_postings_device(
            name, occ_tokens[name], occ_docs[name], occ_pos[name],
            text_doclen[name], n, cap, builder._sim_for(name))
        for name in occ_tokens
    }
    keywords = {
        name: _build_keyword_columnar(name, col, cap)
        for name, col in kw_values.items()
    }
    numerics = {
        name: _build_numeric_columnar(name, kind, col, cap)
        for name, (kind, col) in num_values.items()
    }
    vectors = {
        name: _build_vector_columnar(name, col, cap)
        for name, col in vec_values.items()
    }
    geos = {
        name: SegmentBuilder._build_geo(name, col, cap)
        for name, col in geo_values.items()
    }
    completions = {
        name: CompletionColumn(name=name, entries=entries)
        for name, entries in comp_values.items()
    }

    parent_of = None
    if any(p >= 0 for p in builder.parent_of):
        parent_of = np.full(cap, -1, dtype=np.int32)
        parent_of[:n] = builder.parent_of
    return Segment(
        seg_id=seg_id, num_docs=n, capacity=cap,
        ids=ids, id_map=id_map, sources=sources,
        versions=np.asarray(builder.versions, dtype=np.int64),
        text=text, keywords=keywords, numerics=numerics, vectors=vectors,
        geos=geos, completions=completions, parent_of=parent_of,
    )


def _build_postings_device(name: str, tokens: list[str],
                           doc_parts: list[np.ndarray],
                           pos_parts: list[np.ndarray],
                           doc_len: np.ndarray, n_docs: int, cap: int,
                           sim=None):
    """Postings for one text field from its flat occurrence stream:
    host np.unique interns the term dict ('<U' code-point order ==
    the host builder's sorted()), the device sorts + segments the
    (term-id, doc) stream, the host computes canonical impacts and the
    device packs the layouts."""
    from .segment import BLOCK, PostingsField, _flat_impacts, next_pow2
    from ..ops import build as ob

    doc_count = int(np.count_nonzero(doc_len[:n_docs])) or n_docs
    total_len = float(doc_len.sum())
    avg_len = (total_len / doc_count) if doc_count else 1.0
    n_occ = len(tokens)
    if n_occ == 0:
        # degenerate field (present but no tokens anywhere): nothing to
        # sort — emit the host builder's empty shapes directly
        pf = PostingsField(
            name=name, terms=[], term_index={},
            df=np.array([], dtype=np.int32),
            indptr=np.zeros(1, dtype=np.int64),
            doc_ids=np.empty(0, dtype=np.int32),
            tfs=np.empty(0, dtype=np.float32),
            doc_len=doc_len, doc_count=doc_count,
            avg_len=max(avg_len, 1e-9),
            pos_data=np.empty(0, dtype=np.int32),
            pos_indptr=np.zeros(1, dtype=np.int64),
        )
        pack_layout_device(pf, cap, np.empty(0, dtype=np.float32))
        return pf

    tok_arr = np.asarray(tokens, dtype=np.str_)
    terms_arr, tids = np.unique(tok_arr, return_inverse=True)
    terms = [str(t) for t in terms_arr]
    term_index = {t: i for i, t in enumerate(terms)}
    T = len(terms)
    doc_occ = np.concatenate(doc_parts)
    pos_occ = np.concatenate(pos_parts)

    pad = np.iinfo(np.int32).max
    batch_cap = next_pow2(n_occ, floor=BLOCK)
    vocab_buckets = next_pow2(T, floor=8)
    tid_p = np.full(batch_cap, pad, dtype=np.int32)
    tid_p[:n_occ] = tids
    doc_p = np.full(batch_cap, pad, dtype=np.int32)
    doc_p[:n_occ] = doc_occ
    pos_p = np.zeros(batch_cap, dtype=np.int32)
    pos_p[:n_occ] = pos_occ

    pos_s, tf, df_pad, _p_tid, p_doc = ob.sort_segment_postings(
        tid_p, doc_p, pos_p, batch_cap=batch_cap,
        vocab_buckets=vocab_buckets)
    df = np.asarray(df_pad)[:T].astype(np.int32, copy=False)
    indptr = np.zeros(T + 1, dtype=np.int64)
    np.cumsum(df, out=indptr[1:])
    nnz = int(indptr[-1])
    tf_h = np.asarray(tf)[:nnz]
    pf = PostingsField(
        name=name, terms=terms, term_index=term_index, df=df,
        indptr=indptr,
        doc_ids=np.asarray(p_doc)[:nnz].astype(np.int32, copy=False),
        tfs=tf_h.astype(np.float32),
        doc_len=doc_len, doc_count=doc_count,
        avg_len=max(avg_len, 1e-9),
        pos_data=np.asarray(pos_s)[:n_occ].astype(np.int32, copy=False),
        pos_indptr=np.concatenate(
            [np.zeros(1, dtype=np.int64),
             np.cumsum(tf_h.astype(np.int64))]),
    )
    # eager impacts: the canonical host path — bit-for-bit the numbers
    # the host builder would bake (see module docstring)
    pack_layout_device(pf, cap, _flat_impacts(pf, sim))
    return pf


# ---------------------------------------------------------------------------
# layout pass (the segment._pack_layout dispatch seam)
# ---------------------------------------------------------------------------


def pack_layout_device(pf, cap: int, imps: np.ndarray) -> None:
    """Device mirror of segment._pack_layout_host: 128-lane blocks,
    forward index and block-max tile summary, all as scatters over
    host-computed unique target indices — byte-identical output.
    Raises on any device error; the caller's seam falls back to the
    host loops."""
    from .segment import (
        BLOCK, MAX_FWD_SLOTS, TILE_SUMMARY_BUDGET, next_pow2,
        score_tile_size,
    )
    from ..ops import build as ob

    faults.on_dispatch("build", phase="pack")
    T = len(pf.terms)
    nnz = len(pf.doc_ids)
    n_blocks_per_term = (np.diff(pf.indptr) + BLOCK - 1) // BLOCK
    block_start = np.zeros(T + 1, dtype=np.int32)
    np.cumsum(n_blocks_per_term, out=block_start[1:])
    nb = int(block_start[-1])
    nb_pad = next_pow2(nb, floor=1)
    if nb_pad * BLOCK >= np.iinfo(np.int32).max:
        raise OverflowError("pack exceeds int32 flat block indexing")

    # per-posting target lanes (host integer vector math, exact)
    tid_pp = np.repeat(np.arange(T, dtype=np.int64), np.diff(pf.indptr))
    r = np.arange(nnz, dtype=np.int64) - pf.indptr[tid_pp]
    flat = ((block_start[tid_pp].astype(np.int64) + r // BLOCK) * BLOCK
            + r % BLOCK)

    batch_cap = next_pow2(max(nnz, 1), floor=BLOCK)
    idx_p = np.full(batch_cap, nb_pad * BLOCK, dtype=np.int32)  # pad: OOB
    idx_p[:nnz] = flat
    docs_p = np.full(batch_cap, cap, dtype=np.int32)
    docs_p[:nnz] = pf.doc_ids
    imps_p = np.zeros(batch_cap, dtype=np.float32)
    imps_p[:nnz] = imps
    bd, bi = ob.pack_block_lanes(idx_p, docs_p, imps_p,
                                 np.int32(cap), nb_cap=nb_pad)
    pf.block_docs = np.asarray(bd).reshape(nb_pad, BLOCK)
    pf.block_imps = np.asarray(bi).reshape(nb_pad, BLOCK)
    pf.block_start = block_start
    _bump("pack_layout_device")

    lengths = np.bincount(pf.doc_ids, minlength=cap) if nnz else \
        np.zeros(cap, dtype=np.int64)
    n_slots = next_pow2(int(lengths.max(initial=1)), floor=8)
    if n_slots > MAX_FWD_SLOTS:
        pf.fwd_tids = None
        pf.fwd_imps = None
        return
    slot_in = np.full(batch_cap, np.iinfo(np.int32).max, dtype=np.int32)
    slot_in[:nnz] = pf.doc_ids
    slots = np.asarray(ob.forward_slots(slot_in))
    # pads ride doc = cap: the row index is out of bounds, so the whole
    # (row, slot) pair is dropped whatever garbage slot they carry
    ft, fi = ob.scatter_forward(docs_p, slots, _padded_i32(tid_pp, batch_cap),
                                imps_p, cap=cap, n_slots=n_slots)
    pf.fwd_tids = np.asarray(ft)
    pf.fwd_imps = np.asarray(fi)

    tile = score_tile_size(cap)
    if cap % tile != 0 or (tile < BLOCK and tile < cap):
        pf.tile_max = None
        _pack_positions_device(pf, cap, n_slots)
        return
    n_tiles = cap // tile
    if T <= 0 or T * n_tiles > TILE_SUMMARY_BUDGET:
        pf.tile_max = None
        _pack_positions_device(pf, cap, n_slots)
        return
    term_cap = next_pow2(T, floor=8)
    tids_p = np.full(batch_cap, term_cap, dtype=np.int32)  # pad: OOB row
    tids_p[:nnz] = tid_pp
    tiles_p = np.zeros(batch_cap, dtype=np.int32)
    tiles_p[:nnz] = pf.doc_ids // tile
    tm = ob.scatter_tile_max(tids_p, tiles_p, imps_p,
                             term_cap=term_cap, n_tiles=n_tiles)
    pf.tile_max = np.asarray(tm)[:T].copy()
    _pack_positions_device(pf, cap, n_slots)


def _pack_positions_device(pf, cap: int, n_slots: int) -> None:
    """Device twin of segment.pack_positions: the same host-computed
    (doc, slot*P + k) unique targets, scattered by
    ops/build.scatter_positions — integer set, byte-identical to the
    host fill. The norm columns are two f64->f32 rounds over doc_len
    (segment.bm25_norms, the one shared op order)."""
    from .segment import (BLOCK, bm25_norms, next_pow2, pos_pack_width,
                          position_deltas, _position_targets)
    from ..ops import build as ob
    pf.fwd_pos = None
    pf.pos_width = 0
    pf.lnorm = None
    pf.k1ln = None
    if pf.fwd_tids is None:
        return
    P = pos_pack_width(pf, cap, n_slots)
    if P is None:
        return
    deltas = position_deltas(pf)
    doc_pp, flat_pp = _position_targets(pf, P)
    npos = len(deltas)
    pos_cap = next_pow2(max(npos, 1), floor=BLOCK)
    docs_p = np.full(pos_cap, cap, dtype=np.int32)
    docs_p[:npos] = doc_pp
    cols_p = np.zeros(pos_cap, dtype=np.int32)
    cols_p[:npos] = flat_pp
    vals_p = np.full(pos_cap, -1, dtype=np.int16)
    vals_p[:npos] = deltas
    fp = ob.scatter_positions(docs_p, cols_p, vals_p,
                              cap=cap, pos_cols=n_slots * P)
    pf.fwd_pos = np.asarray(fp)
    pf.pos_width = P
    pf.lnorm, pf.k1ln = bm25_norms(pf.doc_len, pf.avg_len)
    _bump("pack_positions_device")


def _padded_i32(vals: np.ndarray, batch_cap: int,
                fill: int = 0) -> np.ndarray:
    out = np.full(batch_cap, fill, dtype=np.int32)
    out[:len(vals)] = vals
    return out


def extract_flat_impacts_fast(pf) -> np.ndarray:
    """Vectorized mirror of segment.extract_flat_impacts: one gather
    over the flat block-impacts array at the same lane indices the
    packer wrote — exact by construction (no float math)."""
    from .segment import BLOCK
    nnz = len(pf.doc_ids)
    T = len(pf.terms)
    tid_pp = np.repeat(np.arange(T, dtype=np.int64), np.diff(pf.indptr))
    r = np.arange(nnz, dtype=np.int64) - pf.indptr[tid_pp]
    flat = ((pf.block_start[tid_pp].astype(np.int64) + r // BLOCK) * BLOCK
            + r % BLOCK)
    return pf.block_imps.ravel()[flat]


def tile_minmax_device(values: np.ndarray, exists: np.ndarray, cap: int,
                       tile: int) -> tuple[np.ndarray, np.ndarray]:
    """Device half of segment.build_tile_minmax (caller already did the
    degenerate-grid gating): same NaN exclusion, same identity
    sentinels, min/max reductions are order-free → byte-identical."""
    from ..ops import build as ob
    n_tiles = cap // tile
    v = values[:cap]
    e = exists[:cap]
    if values.dtype == np.float32:
        lo_pad = np.float32(np.inf)
        hi_pad = np.float32(-np.inf)
        e = e & ~np.isnan(v)
    else:
        lo_pad = values.dtype.type(np.iinfo(values.dtype).max)
        hi_pad = values.dtype.type(np.iinfo(values.dtype).min)
    lo, hi = ob.tile_minmax(v, e, lo_pad, hi_pad, n_tiles=n_tiles)
    _bump("tile_minmax_device")
    return (np.asarray(lo).astype(values.dtype, copy=False),
            np.asarray(hi).astype(values.dtype, copy=False))


# ---------------------------------------------------------------------------
# vectorized doc-value builders (columnar layout without per-doc loops)
# ---------------------------------------------------------------------------


def _build_keyword_columnar(name: str, col: dict[int, list[str]],
                            cap: int):
    """Single-valued fast path: np.unique interns the dictionary
    ('<U' order == sorted()) and one scatter lays out the ordinal
    column. Multi-valued docs take the host static (identical by
    definition)."""
    from .segment import KeywordColumn, SegmentBuilder
    if any(len(vs) != 1 for vs in col.values()):
        return SegmentBuilder._build_keyword(name, col, cap)
    rows = np.fromiter(col.keys(), dtype=np.int64, count=len(col))
    vals = np.asarray([vs[0] for vs in col.values()], dtype=np.str_)
    terms_arr, inv = np.unique(vals, return_inverse=True)
    terms = [str(t) for t in terms_arr]
    ords = np.full(cap, -1, dtype=np.int32)
    ords[rows] = inv.astype(np.int32)
    df = np.bincount(inv, minlength=len(terms)).astype(np.int32)
    return KeywordColumn(name=name, terms=terms,
                         term_index={t: i for i, t in enumerate(terms)},
                         ords=ords, df=df, mv_ords=None)


def _build_numeric_columnar(name: str, kind: str, col: dict[int, list],
                            cap: int):
    """Single-valued fast path for the numeric doc-value layout. The
    host-exact int64/float64 raw column stays on the host — jax
    without x64 would downcast it, and `raw` backs fetch/stats
    exactness. Multi-valued docs take the host static."""
    from .mapping import BOOLEAN, BYTE, DATE, INTEGER, IP, LONG, SHORT
    from .segment import NumericColumn, SegmentBuilder, _device_vals
    if any(len(vs) != 1 for vs in col.values()):
        return SegmentBuilder._build_numeric(name, kind, col, cap)
    is_int = kind in (LONG, INTEGER, SHORT, BYTE, DATE, BOOLEAN, IP)
    dt = np.int64 if is_int else np.float64
    rows = np.fromiter(col.keys(), dtype=np.int64, count=len(col))
    if kind == BOOLEAN:
        flat = np.asarray([1 if vs[0] else 0 for vs in col.values()],
                          dtype=dt)
    else:
        flat = np.asarray([vs[0] for vs in col.values()], dtype=dt)
    exists = np.zeros(cap, dtype=bool)
    exists[rows] = True
    raw = np.zeros(cap, dtype=dt)
    raw[rows] = flat
    bias = 1 << 31 if kind == IP else 0
    return NumericColumn(name=name, kind=kind,
                         values=_device_vals(raw, kind, bias, is_int),
                         exists=exists, raw=raw, bias=bias,
                         mv_values=None, mv_raw=None, mv_exists=None)


def _build_vector_columnar(name: str, col: dict[int, list], cap: int):
    """Row-block copy of the embedding column (one assignment, no
    per-doc loop). Ragged inputs (shorter vectors zero-padded by the
    host builder) fall back to the host static."""
    from .segment import SegmentBuilder, VectorColumn
    dims = len(next(iter(col.values())))
    if any(len(v) != dims for v in col.values()):
        return SegmentBuilder._build_vector(name, col, cap)
    rows = np.fromiter(col.keys(), dtype=np.int64, count=len(col))
    mat = np.asarray(list(col.values()), dtype=np.float32)
    values = np.zeros((cap, dims), dtype=np.float32)
    values[rows] = mat
    exists = np.zeros(cap, dtype=bool)
    exists[rows] = True
    norms = np.linalg.norm(values, axis=1).astype(np.float32)
    return VectorColumn(name=name, values=values, exists=exists,
                        norms=norms)
