"""Language analysis: stopword sets, light stemmers, language analyzers.

Reference analog: the per-language analyzer providers under
index/analysis/ (FrenchAnalyzerProvider, GermanAnalyzerProvider, ... ~30
of them) wrapping Lucene's language analyzers, plus the `stemmer` and
`stop` token-filter factories (StemmerTokenFilterFactory.java,
StopTokenFilterFactory.java with the `_lang_` named stopword sets).

Composition follows the reference: standard tokenizer -> (elision /
normalization where the language needs it) -> lowercase -> language
stopwords -> language stemmer. The stemmers are light suffix strippers
in the spirit of Lucene's *LightStemmer classes (savary/jacquemin-style
rules) — they collapse inflectional families (plural/gender/verb
endings), not full Snowball derivational stemming; English keeps the
existing Porter implementation. CJK uses the reference's bigram
approach; Thai has no segmenter here (documented divergence — tokens
come from the unicode word tokenizer).

All public sets/functions register into the analysis registries at
import (analysis.py imports this module at the bottom), so language
analyzers resolve by name in mappings and `stemmer`/`stop` filters
accept every language listed in SUPPORTED_LANGUAGES.
"""

from __future__ import annotations

import re
from typing import Callable

# ---------------------------------------------------------------------------
# Stopword sets (ref: Lucene analysis stopword lists — function words per
# language; `_lang_` names accepted by the `stop` filter factory)
# ---------------------------------------------------------------------------

STOPWORDS: dict[str, frozenset] = {k: frozenset(v.split()) for k, v in {
    "arabic": "في من على و ان الى عن مع هذا هذه ذلك التي الذي هو هي ما لا "
              "لم كان كانت قد و ايضا كل بعد غير حتى اذا ثم او أو إلى أن إن",
    "armenian": "եւ է են ու այս այդ որ նա ես դու մենք իր նրա համար մեջ "
                "վրա հետ որպես էր էին",
    "basque": "eta edo ez da du dute ere bat batzuk hau hori zen ziren "
              "baina dago daude izan ditu",
    "brazilian": "a o e de da do em um uma para com não por os as dos das "
                 "que se na no mais mas ao às aos pelo pela como",
    "bulgarian": "и в на с за от по не са е да се това той тя то те като "
                 "или но а при до след през който която което",
    "catalan": "i el la els les de a en un una per amb no és que es al "
               "del com més o si són hi ho aquest aquesta",
    "cjk": "a and are as at be but by for if in into is it no not of on "
           "or such that the their then there these they this to was will "
           "with",
    "czech": "a se na je že v z s do o k i ale jako za by to ten tato "
             "který která které pro po při nebo jsem jsou byl byla",
    "danish": "og i at det en den til er som på de med af for ikke der "
              "var han hun men et har om vi min havde sig hvad",
    "dutch": "de het een en van in is dat die op te zijn met voor niet "
             "aan er ook als maar om dan zou wat bij uit nog naar heeft",
    "english": "a an and are as at be but by for if in into is it no not "
               "of on or such that the their then there these they this "
               "to was will with",
    "finnish": "ja on ei se että hän oli ovat mutta kun niin kuin myös "
               "joka jos tai sen ole sitä olla mitä nyt vain",
    "french": "le la les de des du un une et à au aux en dans pour par "
              "sur avec ne pas que qui est sont ce cette ces il elle ils "
              "elles nous vous je tu se sa son ses leur leurs ou où mais "
              "plus si être avoir été était",
    "galician": "a o e as os un unha de do da en para con non que se por "
                "como máis pero ao á é son",
    "german": "der die das den dem des ein eine einen einem eines und "
              "oder aber in im an auf für von mit zu zum zur bei nach "
              "ist sind war waren wird werden nicht als auch es ich du "
              "er sie wir ihr aus dass sich",
    "greek": "ο η το οι τα του της των και να με για από σε που δεν ειναι "
             "ήταν θα αυτό αυτή αλλά ως κατά ή ένα μία",
    "hindi": "का के की में है और को से पर यह वह ने कि जो भी था थी हैं नहीं "
             "तो ही हो कर एक इस उस",
    "hungarian": "a az és hogy nem is van volt egy ez azt de ha meg már "
                 "csak mint el vagy még lesz ki mi ők",
    "indonesian": "yang dan di ke dari untuk pada dengan dalam ini itu "
                  "adalah tidak akan atau juga sudah saya kami mereka "
                  "ada bisa oleh karena",
    "irish": "agus an na is i ar le do go bhí sé sí tá ag ach nach mar ó "
             "a ní",
    "italian": "il lo la i gli le di a da in con su per tra fra un uno "
               "una e o ma se che chi non più come anche è sono era del "
               "della dei delle al alla nel nella",
    "latvian": "un ir uz no ar par ka vai bet kā pēc pie šis šī tas tā "
               "viņš viņa es tu mēs jūs nav bija",
    "norwegian": "og i at det en den til er som på de med av for ikke "
                 "der var han hun men et har om vi seg så fra ble",
    "persian": "و در به از که این آن را با برای است بود شد می ها های تا "
               "بر یا هم نیز اگر اما",
    "portuguese": "a o e de da do em um uma para com não por os as dos "
                  "das que se na no mais mas ao como foi são ser está",
    "romanian": "și în de la a al ale cu pe un o este sunt că nu se din "
                "pentru mai dar sau dacă fi fost care ce",
    "russian": "и в не на я что он она оно они с как а то все это так его "
               "её их но да ты мы вы же бы по из у за от для о при был "
               "была были есть",
    "sorani": "و لە بە بۆ کە ئەو ئەم لەگەڵ هەر وەک یان بەڵام ئەگەر دوای "
              "سەر ناو",
    "spanish": "el la los las de a en un una y o que es son fue por para "
               "con no se su sus del al como más pero si este esta estos "
               "estas ese esa lo le les mi tu nos",
    "swedish": "och i att det en den till är som på de med av för inte "
               "der var han hon men ett har om vi sig så från jag du",
    "thai": "และ ใน ของ ที่ เป็น มี ไม่ ให้ ได้ ว่า จะ กับ แต่ หรือ นี้ นั้น",
    "turkish": "ve bir bu da de için ile olarak olan daha çok en gibi ama "
               "veya ki ne o şu ise değil var yok",
}.items()}

SUPPORTED_LANGUAGES = sorted(STOPWORDS)


# ---------------------------------------------------------------------------
# Light stemmers — ordered longest-suffix-first (suffix, replacement)
# rules with a minimum remaining-stem length, in the spirit of Lucene's
# *LightStemmer family
# ---------------------------------------------------------------------------


def _suffix_stemmer(rules: list[tuple[str, str]], min_stem: int = 3,
                    prelude: Callable[[str], str] | None = None,
                    repeat: int = 1) -> Callable[[str], str]:
    rules = sorted(rules, key=lambda r: -len(r[0]))

    def stem(w: str) -> str:
        if prelude is not None:
            w = prelude(w)
        for _ in range(repeat):
            matched = False
            for suf, rep in rules:
                if w.endswith(suf) and len(w) - len(suf) + len(rep) \
                        >= min_stem:
                    w = w[: len(w) - len(suf)] + rep
                    matched = True
                    break
            if not matched:
                break
        return w
    return stem


def _fold(mapping: dict[str, str]) -> Callable[[str], str]:
    def fold(w: str) -> str:
        for a, b in mapping.items():
            w = w.replace(a, b)
        return w
    return fold


_FRENCH_RULES = [
    ("issements", "iss"), ("issement", "iss"), ("atrices", "ateur"),
    ("atrice", "ateur"), ("ateurs", "ateur"), ("logies", "logie"),
    ("ements", "e"), ("ement", "e"), ("ités", "ité"), ("ences", "ence"),
    ("istes", "iste"), ("ables", "able"), ("eaux", "eau"),
    ("aux", "al"), ("euses", "eux"), ("euse", "eux"), ("ives", "if"),
    ("ive", "if"), ("ées", "é"), ("ée", "é"), ("és", "é"),
    ("ers", "er"), ("ions", "ion"), ("es", ""), ("s", ""), ("x", ""),
    ("e", ""),
]

_GERMAN_RULES = [("heiten", "heit"), ("keiten", "keit"), ("ungen", "ung"),
                 ("isch", ""), ("ern", ""), ("em", ""), ("en", ""),
                 ("er", ""), ("es", ""), ("e", ""), ("s", ""), ("n", "")]

_SPANISH_RULES = [
    ("amientos", "a"), ("imientos", "i"), ("amiento", "a"),
    ("imiento", "i"), ("aciones", "ación"), ("idades", "idad"),
    ("encias", "encia"), ("istas", "ista"), ("ables", "able"),
    ("ibles", "ible"), ("mente", ""), ("anzas", "anza"), ("ces", "z"),
    ("ciones", "ción"), ("osos", "oso"), ("osas", "oso"),
    ("es", ""), ("s", ""), ("a", ""), ("o", ""), ("e", ""),
    ("í", ""), ("ó", ""), ("á", ""),
]

_ITALIAN_RULES = [
    ("azioni", "azione"), ("uzioni", "uzione"), ("amenti", "amento"),
    ("imenti", "imento"), ("logie", "logia"), ("mente", ""),
    ("ità", "ità"), ("che", "c"), ("chi", "c"), ("ghe", "g"),
    ("ghi", "g"), ("ie", ""), ("i", ""), ("e", ""), ("a", ""), ("o", ""),
]

_PORTUGUESE_RULES = [
    ("amentos", "amento"), ("imentos", "imento"), ("aço~es", "aço"),
    ("ações", "ação"), ("idades", "idade"), ("ismos", "ismo"),
    ("istas", "ista"), ("mente", ""), ("ões", "ão"), ("ães", "ão"),
    ("ais", "al"), ("éis", "el"), ("óis", "ol"), ("is", "il"),
    ("les", "l"), ("res", "r"), ("es", ""), ("s", ""), ("a", ""),
    ("o", ""), ("e", ""),
]

_DUTCH_RULES = [("heden", "heid"), ("ingen", "ing"), ("eren", "eer"),
                ("en", ""), ("e", ""), ("s", ""), ("je", "")]

_SWEDISH_RULES = [("heterna", "het"), ("heten", "het"), ("heter", "het"),
                  ("arna", ""), ("erna", ""), ("orna", ""), ("ande", ""),
                  ("arne", ""), ("aste", ""), ("arnas", ""), ("ades", ""),
                  ("are", ""), ("ade", ""), ("ad", ""), ("ar", ""),
                  ("er", ""), ("or", ""), ("en", ""), ("at", ""),
                  ("a", ""), ("e", ""), ("s", "")]

_NORWEGIAN_RULES = [("hetene", "het"), ("heten", "het"), ("heter", "het"),
                    ("endes", "ende"), ("ande", ""), ("ende", ""),
                    ("edes", ""), ("enes", ""), ("ene", ""), ("ane", ""),
                    ("ede", ""), ("ers", ""), ("ets", ""), ("et", ""),
                    ("er", ""), ("ar", ""), ("en", ""), ("a", ""),
                    ("e", ""), ("s", "")]

_DANISH_RULES = [("erendes", "er"), ("erende", "er"), ("hedens", "hed"),
                 ("ethed", ""), ("heden", "hed"), ("heder", "hed"),
                 ("ernes", ""), ("erens", ""), ("erne", ""), ("eren", ""),
                 ("erer", "er"), ("enes", ""), ("eres", "er"), ("ende", ""),
                 ("ene", ""), ("ens", ""), ("ers", ""), ("ets", ""),
                 ("en", ""), ("er", ""), ("es", ""), ("et", ""),
                 ("e", ""), ("s", "")]

_FINNISH_RULES = [("isuuksien", "isuus"), ("isuuden", "isuus"),
                  ("llinen", "llinen"), ("ssa", ""), ("ssä", ""),
                  ("sta", ""), ("stä", ""), ("lla", ""), ("llä", ""),
                  ("lta", ""), ("ltä", ""), ("lle", ""), ("ksi", ""),
                  ("ien", "i"), ("iden", "i"), ("itten", "i"),
                  ("ina", "i"), ("inä", "i"), ("eja", ""), ("ejä", ""),
                  ("it", "i"), ("et", "i"), ("at", "a"), ("ät", "ä"),
                  ("t", ""), ("n", ""), ("a", ""), ("ä", "")]

_RUSSIAN_RULES = [
    ("иями", "ия"), ("иях", "ия"), ("ями", ""), ("ами", ""), ("иям", "ия"),
    ("иями", "ия"), ("ость", "ость"), ("ости", "ость"), ("остью", "ость"),
    ("ение", "ение"), ("ения", "ение"), ("ению", "ение"), ("ами", ""),
    ("ыми", ""), ("его", ""), ("ого", ""), ("ему", ""), ("ому", ""),
    ("ая", ""), ("яя", ""), ("ой", ""), ("ый", ""), ("ий", ""),
    ("ые", ""), ("ие", ""), ("ов", ""), ("ев", ""), ("ей", ""),
    ("ам", ""), ("ям", ""), ("ах", ""), ("ях", ""), ("ом", ""),
    ("ем", ""), ("ет", ""), ("ут", ""), ("ют", ""), ("ат", ""),
    ("ят", ""), ("ть", ""), ("ы", ""), ("и", ""), ("а", ""), ("я", ""),
    ("о", ""), ("е", ""), ("у", ""), ("ю", ""), ("ь", ""),
]

_CZECH_RULES = [("atech", "at"), ("ětem", "ě"), ("atům", "at"),
                ("ech", ""), ("ich", ""), ("ích", ""), ("ého", ""),
                ("ěmi", ""), ("emi", ""), ("ému", ""), ("ěte", "ě"),
                ("ům", ""), ("ám", ""), ("ách", ""), ("ami", ""),
                ("ové", ""), ("ovi", ""), ("ých", ""), ("ým", ""),
                ("at", ""), ("ů", ""), ("y", ""), ("a", ""), ("e", ""),
                ("i", ""), ("í", ""), ("é", ""), ("ý", ""), ("ě", ""),
                ("u", ""), ("o", "")]

_HUNGARIAN_RULES = [("okkal", ""), ("ekkel", ""), ("ökkel", ""),
                    ("oknak", ""), ("eknek", ""), ("öknek", ""),
                    ("okat", ""), ("eket", ""), ("öket", ""),
                    ("nak", ""), ("nek", ""), ("val", ""), ("vel", ""),
                    ("ban", ""), ("ben", ""), ("ból", ""), ("ből", ""),
                    ("nál", ""), ("nél", ""), ("hoz", ""), ("hez", ""),
                    ("höz", ""), ("ok", ""), ("ek", ""), ("ök", ""),
                    ("ak", ""), ("ot", ""), ("et", ""),
                    ("öt", ""), ("on", ""), ("en", ""), ("ön", ""),
                    ("ra", ""), ("re", ""), ("ba", ""), ("be", ""),
                    ("t", ""), ("k", ""), ("i", ""), ("a", ""), ("e", "")]

_ROMANIAN_RULES = [("ilor", ""), ("ului", ""), ("elor", ""), ("iile", "i"),
                   ("iilor", "i"), ("atei", "at"), ("aţie", "aţi"),
                   ("ația", "ați"), ("ele", ""), ("eaua", "ea"),
                   ("ea", ""), ("ii", "i"), ("ul", ""), ("le", ""),
                   ("uri", ""), ("ă", ""), ("a", ""), ("e", ""),
                   ("i", ""), ("u", "")]

_BULGARIAN_RULES = [("ията", "ия"), ("ият", "ия"), ("овете", ""),
                    ("овци", "о"), ("ище", ""), ("ът", ""), ("та", ""),
                    ("то", ""), ("те", ""), ("ите", ""), ("ия", ""),
                    ("ът", ""), ("ове", ""), ("ен", ""), ("на", ""),
                    ("ни", ""), ("и", ""), ("а", ""), ("я", ""),
                    ("е", ""), ("о", "")]

_CATALAN_RULES = [("aments", "ament"), ("acions", "ació"),
                  ("itats", "itat"), ("ismes", "isme"), ("istes", "ista"),
                  ("ments", "ment"), ("cions", "ció"), ("ques", "c"),
                  ("res", "r"), ("ons", "ó"), ("es", ""), ("s", ""),
                  ("a", ""), ("o", ""), ("e", ""), ("í", ""), ("à", "")]

_GALICIAN_RULES = [("amentos", "amento"), ("acións", "ación"),
                   ("idades", "idade"), ("mente", ""), ("cións", "ción"),
                   ("eiras", "eira"), ("eiros", "eiro"), ("ois", "ol"),
                   ("áns", "án"), ("es", ""), ("s", ""), ("a", ""),
                   ("o", ""), ("e", "")]

_INDONESIAN_RULES = [("kannya", ""), ("annya", ""), ("kan", ""),
                     ("an", ""), ("i", ""), ("nya", ""), ("lah", ""),
                     ("kah", ""), ("pun", "")]

_TURKISH_RULES = [("larının", ""), ("lerinin", ""), ("larında", ""),
                  ("lerinde", ""), ("larından", ""), ("lerinden", ""),
                  ("ların", ""), ("lerin", ""), ("lara", ""), ("lere", ""),
                  ("larda", ""), ("lerde", ""), ("lardan", ""),
                  ("lerden", ""), ("ları", ""), ("leri", ""),
                  ("lar", ""), ("ler", ""), ("ında", ""), ("inde", ""),
                  ("unda", ""), ("ünde", ""), ("ını", ""), ("ini", ""),
                  ("unu", ""), ("ünü", ""), ("ın", ""), ("in", ""),
                  ("un", ""), ("ün", ""), ("ı", ""), ("i", ""),
                  ("u", ""), ("ü", ""), ("a", ""), ("e", ""),
                  ("da", ""), ("de", ""), ("dan", ""), ("den", "")]

_HINDI_RULES = [("ियों", "ी"), ("ाओं", "ा"), ("ुओं", "ु"), ("ियां", "ी"),
                ("ियाँ", "ी"), ("ाएं", "ा"), ("ाएँ", "ा"), ("ों", ""),
                ("ें", ""), ("ीं", ""), ("ाँ", ""), ("ां", ""),
                ("ो", ""), ("े", ""), ("ी", ""), ("ि", ""), ("ा", "")]

_GREEK_RULES = [("ματων", "μα"), ("ματα", "μα"), ("ματος", "μα"),
                ("ουδες", "ου"), ("εις", "η"), ("ων", ""), ("ου", ""),
                ("ος", ""), ("ης", ""), ("ας", ""), ("ες", ""),
                ("οι", ""), ("αι", ""), ("α", ""), ("η", ""), ("ο", ""),
                ("ι", ""), ("ε", ""), ("υ", ""), ("ς", "")]

_LATVIAN_RULES = [("iem", ""), ("ajam", ""), ("ajai", ""), ("am", ""),
                  ("ām", ""), ("as", ""), ("ās", ""), ("os", ""),
                  ("us", ""), ("iem", ""), ("īm", ""), ("em", ""),
                  ("a", ""), ("e", ""), ("i", ""), ("s", ""), ("š", ""),
                  ("u", ""), ("o", "")]

_IRISH_RULES = [("acha", "ach"), ("anna", "ann"), ("aigh", ""),
                ("igh", ""), ("ann", ""), ("tha", ""), ("the", ""),
                ("aí", ""), ("í", ""), ("a", ""), ("e", "")]

_ARMENIAN_RULES = [("ություն", ""), ("ներին", ""), ("ների", ""),
                   ("ներ", ""), ("երի", ""), ("եր", ""), ("ում", ""),
                   ("ից", ""), ("ով", ""), ("ը", ""), ("ի", ""),
                   ("ն", "")]

_BASQUE_RULES = [("arekin", ""), ("aren", ""), ("etik", ""), ("ekin", ""),
                 ("aren", ""), ("ean", ""), ("era", ""), ("ari", ""),
                 ("ak", ""), ("ek", ""), ("en", ""), ("an", ""),
                 ("a", ""), ("k", "")]

# Arabic light10-style: strip definite articles and common suffixes
_ARABIC_PREFIXES = ("ال", "وال", "بال", "كال", "فال", "لل", "و")
_ARABIC_SUFFIXES = ("ها", "ان", "ات", "ون", "ين", "يه", "ية", "ه",
                    "ة", "ي")


def _arabic_stem(w: str) -> str:
    for p in sorted(_ARABIC_PREFIXES, key=len, reverse=True):
        if w.startswith(p) and len(w) - len(p) >= 3:
            w = w[len(p):]
            break
    for s in sorted(_ARABIC_SUFFIXES, key=len, reverse=True):
        if w.endswith(s) and len(w) - len(s) >= 3:
            w = w[: -len(s)]
            break
    return w


def _persian_normalize(w: str) -> str:
    # ref: PersianNormalizationFilter — yeh/keheh unification, heh
    # hamza, zero-width non-joiner removal
    return (w.replace("ي", "ی").replace("ك", "ک")
             .replace("ة", "ه").replace("‌", ""))


def _arabic_normalize(w: str) -> str:
    # ref: ArabicNormalizationFilter — hamza/alef forms, teh marbuta,
    # tatweel + diacritics removal
    w = re.sub("[آأإ]", "ا", w)
    w = w.replace("ى", "ي").replace("ـ", "")
    return re.sub("[ً-ْ]", "", w)


_GERMAN_FOLD = _fold({"ä": "a", "ö": "o", "ü": "u", "ß": "ss"})

STEMMERS: dict[str, Callable[[str], str]] = {
    "french": _suffix_stemmer(_FRENCH_RULES, 3),
    "german": _suffix_stemmer(_GERMAN_RULES, 4, prelude=_GERMAN_FOLD,
                              repeat=2),
    "german2": _suffix_stemmer(_GERMAN_RULES, 4, prelude=_GERMAN_FOLD,
                               repeat=2),
    "spanish": _suffix_stemmer(_SPANISH_RULES, 3, repeat=2),
    "italian": _suffix_stemmer(_ITALIAN_RULES, 3),
    "portuguese": _suffix_stemmer(_PORTUGUESE_RULES, 3, repeat=2),
    "brazilian": _suffix_stemmer(_PORTUGUESE_RULES, 3, repeat=2),
    "galician": _suffix_stemmer(_GALICIAN_RULES, 3, repeat=2),
    "catalan": _suffix_stemmer(_CATALAN_RULES, 3, repeat=2),
    "dutch": lambda w, _s=_suffix_stemmer(_DUTCH_RULES, 3): (
        # degemination: katten -> katt -> kat (Snowball dutch step 4)
        (lambda x: x[:-1] if len(x) > 3 and x[-1] == x[-2]
         and x[-1] not in "aeiou" else x)(_s(w))),
    "swedish": _suffix_stemmer(_SWEDISH_RULES, 3),
    "norwegian": _suffix_stemmer(_NORWEGIAN_RULES, 3),
    "danish": _suffix_stemmer(_DANISH_RULES, 3),
    "finnish": _suffix_stemmer(_FINNISH_RULES, 3),
    "russian": _suffix_stemmer(_RUSSIAN_RULES, 3),
    "czech": _suffix_stemmer(_CZECH_RULES, 3),
    "hungarian": _suffix_stemmer(_HUNGARIAN_RULES, 3),
    "romanian": _suffix_stemmer(_ROMANIAN_RULES, 3),
    "bulgarian": _suffix_stemmer(_BULGARIAN_RULES, 3),
    "indonesian": _suffix_stemmer(_INDONESIAN_RULES, 3),
    "turkish": _suffix_stemmer(_TURKISH_RULES, 3),
    "arabic": _arabic_stem,
    "hindi": _suffix_stemmer(_HINDI_RULES, 2),
    "greek": _suffix_stemmer(_GREEK_RULES, 3),
    "latvian": _suffix_stemmer(_LATVIAN_RULES, 3),
    "irish": _suffix_stemmer(_IRISH_RULES, 3),
    "armenian": _suffix_stemmer(_ARMENIAN_RULES, 3),
    "basque": _suffix_stemmer(_BASQUE_RULES, 3),
}


def stemmer_filter(language: str) -> Callable:
    """The `stemmer` token filter (ref: StemmerTokenFilterFactory.java
    dispatching on `language`/`name`)."""
    from ..utils.errors import IllegalArgumentError
    lang = str(language).lower()
    if lang in ("english", "porter", "porter2", "minimal_english"):
        from .analysis import porter_stem_filter
        return porter_stem_filter
    stem = STEMMERS.get(lang)
    if stem is None:
        raise IllegalArgumentError(f"unknown stemmer [{language}]")
    return lambda tokens: [stem(t) for t in tokens]


# ---------------------------------------------------------------------------
# Language-specific filters
# ---------------------------------------------------------------------------

_DEFAULT_ARTICLES = ("l", "m", "t", "qu", "n", "s", "j", "d", "c",
                     "jusqu", "quoiqu", "lorsqu", "puisqu")


def elision_filter(articles=_DEFAULT_ARTICLES) -> Callable:
    """Strip leading elided articles (l'avion -> avion). Ref:
    index/analysis/ElisionTokenFilterFactory.java."""
    arts = tuple(sorted({str(a).lower() for a in articles},
                        key=len, reverse=True))

    def run(tokens):
        out = []
        for t in tokens:
            low = t.lower()
            stripped = False
            for a in arts:
                for apo in ("'", "’"):
                    pre = a + apo
                    if low.startswith(pre) and len(t) > len(pre):
                        t = t[len(pre):]
                        stripped = True
                        break
                if stripped:
                    break  # one article per token, as in Lucene
            out.append(t)
        return out
    return run


_HAN_RE = re.compile(r"[⺀-鿿가-힯]")


def cjk_bigram_filter(tokens):
    """Han/Hangul runs -> overlapping bigrams (ref: Lucene
    CJKBigramFilter via the cjk analyzer). Non-CJK tokens pass through."""
    out = []
    for t in tokens:
        if len(t) >= 2 and all(_HAN_RE.match(c) for c in t):
            out.extend(t[i:i + 2] for i in range(len(t) - 1))
        else:
            out.append(t)
    return out


def _normalize_filter(norm: Callable[[str], str]) -> Callable:
    return lambda tokens: [norm(t) for t in tokens]


# ---------------------------------------------------------------------------
# Language analyzers (ref: the *AnalyzerProvider classes)
# ---------------------------------------------------------------------------


def build_language_analyzers() -> dict:
    from .analysis import (Analyzer, standard_tokenizer, lowercase_filter,
                           stop_filter)
    out: dict = {}
    for lang in SUPPORTED_LANGUAGES:
        if lang == "english":
            continue  # registered by the core module (porter chain)
        filters = []
        if lang in ("french", "italian", "catalan", "irish"):
            filters.append(elision_filter())
        filters.append(lowercase_filter)
        if lang == "arabic":
            filters.append(_normalize_filter(_arabic_normalize))
        if lang in ("persian", "sorani"):
            filters.append(_normalize_filter(_persian_normalize))
        filters.append(stop_filter(STOPWORDS[lang]))
        if lang == "cjk":
            filters.append(cjk_bigram_filter)
        stem = STEMMERS.get(lang)
        if stem is not None:
            s = stem
            filters.append(
                lambda tokens, _s=s: [_s(t) for t in tokens])
        out[lang] = Analyzer(lang, standard_tokenizer, filters)
    return out


def register_all() -> None:
    """Wire languages into the analysis registries (called by
    analysis.py at import)."""
    from . import analysis as a
    for name, an in build_language_analyzers().items():
        # direct dict insert: these are built-ins, not plugin overrides
        a.EXTRA_ANALYZERS.setdefault(name, an)
    a.FILTER_FACTORIES.setdefault(
        "stemmer",
        lambda s: stemmer_filter(s.get_str("language")
                                 or s.get_str("name") or "english"))
    a.FILTER_FACTORIES.setdefault(
        "elision",
        lambda s: elision_filter(s.get_list("articles")
                                 or _DEFAULT_ARTICLES))
    a.TOKEN_FILTERS.setdefault("cjk_bigram", cjk_bigram_filter)
    a.TOKEN_FILTERS.setdefault("arabic_normalization",
                               _normalize_filter(_arabic_normalize))
    a.TOKEN_FILTERS.setdefault("persian_normalization",
                               _normalize_filter(_persian_normalize))
    a.TOKEN_FILTERS.setdefault("german_normalization",
                               _normalize_filter(_GERMAN_FOLD))
    from .hunspell import hunspell_filter
    a.FILTER_FACTORIES.setdefault(
        "hunspell",
        lambda s: hunspell_filter(
            s.get_str("locale") or s.get_str("language") or "",
            dedup=s.get_bool("dedup", True)))
