"""Immutable columnar segments — the TPU-native replacement for Lucene segments.

Reference analog: the per-shard Lucene index managed by
index/engine/InternalEngine.java (IndexWriter segments) plus the fielddata
layer (index/fielddata/ — columnar per-doc values, global ordinals). In
this framework a segment IS columnar from birth:

  * text fields   -> block-CSR postings: fixed 128-lane blocks of
                     (doc_id, bm25_impact) pairs, term -> block range.
                     BM25 impacts are precomputed at index time
                     (BM25S-style "eager scoring" — see PAPERS.md), so
                     query-time work is gather + scatter-add, which maps
                     onto the TPU VPU; there is no per-doc scoring loop.
  * keyword field -> int32 ordinal column + sorted term dictionary
                     (ref: global ordinals, index/fielddata/ordinals/)
  * numeric/date  -> int32/float32 doc-value columns + exists mask
  * _id/_source   -> host-side (fetch phase never touches the device)

A Segment is built once (host, numpy), is immutable afterwards, and can be
uploaded to the device as a DeviceSegment pytree. Deletions are a live
bitmask owned by the engine, not the segment (like Lucene liveDocs).

Shapes are padded to power-of-two buckets so XLA recompilation count is
logarithmic in segment size, and the last dim of posting blocks is 128 to
match the TPU lane width.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field as dc_field
from typing import Iterable

import numpy as np

# guards lazy text-sort column materialization (rare, once per
# segment+field); searches arrive concurrently via ThreadingHTTPServer
_TEXT_SORT_LOCK = threading.Lock()

from .mapping import (
    ParsedDocument, TEXT, KEYWORD, DATE, BOOLEAN, IP,
    LONG, INTEGER, SHORT, BYTE, DOUBLE, FLOAT, DENSE_VECTOR, GEO_POINT,
)

BLOCK = 128  # TPU lane width; one posting block = 128 (doc, impact) lanes
MAX_FWD_SLOTS = 256  # forward-index width limit (beyond: scatter path)

# block-max pruning (the block-max WAND analog for the dense path):
# per-(term, doc-tile) upper-bound impact summaries built at pack time.
# A query's score upper bound over a tile is sum_q w_q * tile_max[q, j];
# tiles whose bound cannot beat the running top-k threshold are skipped
# by the fused score+top-k kernels (ops/scoring.py, ops/pallas_scoring.py).
SCORE_TILE = 1024           # docs per pruning tile (lane-width multiple)
TILE_SUMMARY_BUDGET = 1 << 24  # max T * n_tiles elements (64MB f32)

# Lucene BM25Similarity defaults (ref: index/similarity/BM25SimilarityProvider.java)
BM25_K1 = 1.2
BM25_B = 0.75

# positional pack (third eager column family next to deltas and
# impacts): per-(doc, slot) position lists, delta-encoded int16, width
# pow2-bucketed like the forward slot width. A field whose max
# per-posting tf exceeds POS_CAP (or whose positions overflow int16)
# skips the pack and phrase/span queries take the host path (counted
# under fused_scoring.admission.positional).
POS_CAP = 64                   # max positions kept per (doc, term)
POS_MAX_ENC = 32767            # int16 ceiling for absolute positions
POS_PACK_BUDGET = 1 << 27      # max cap * L * P int16 elements (256MB)


def bm25_norms(doc_len: np.ndarray, avg_len: float,
               k1: float = BM25_K1, b: float = BM25_B
               ) -> tuple[np.ndarray, np.ndarray]:
    """The two per-doc BM25 length-norm columns of the positional pack,
    in the ONE f32 op order every consumer shares:

      lnorm[d] = (1 - b) + b * doc_len[d] / avg_len   (BM25F field norm)
      k1ln[d]  = k1 * lnorm[d]                        (phrase/span k_d)

    Computed in f64 then rounded ONCE to f32 — the device engines, the
    eval_node reference path, and the host phrase/BM25F oracles all
    read these exact values, which is what makes fused positional
    scores byte-identical to the host `search/phrase.py` oracle."""
    ln = (1.0 - b) + b * (doc_len.astype(np.float64) / float(avg_len))
    ln32 = ln.astype(np.float32)
    return ln32, (k1 * ln).astype(np.float32)


def next_pow2(n: int, floor: int = 1) -> int:
    n = max(n, floor)
    return 1 << (n - 1).bit_length()


def bm25_idf(df: np.ndarray | float, doc_count: int) -> np.ndarray | float:
    """idf = ln(1 + (N - df + 0.5) / (df + 0.5)) — Lucene BM25Similarity.idfExplain."""
    return np.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))


def score_tile_size(cap: int) -> int:
    """Pruning-tile width for a capacity: the largest power-of-two
    divisor of cap, capped at SCORE_TILE (pow2 caps get SCORE_TILE, or
    the whole cap when smaller). ALWAYS divides cap exactly, so tiles
    never straddle the array end; build_tile_max rejects degenerate
    widths (< BLOCK) that an odd-factor cap would produce."""
    return math.gcd(cap, SCORE_TILE)


def build_tile_max(fwd_tids: np.ndarray, fwd_imps: np.ndarray,
                   n_terms: int, cap: int,
                   tile: int | None = None) -> np.ndarray | None:
    """[cap, L] forward index -> [T, n_tiles] per-(term, doc-tile) max
    impact, the block-max summary consumed by the fused score+top-k
    kernels. None when there are no terms or the summary would exceed
    TILE_SUMMARY_BUDGET elements (the pruning win never justifies an
    HBM column bigger than the corpus slice it prunes)."""
    if tile is None:
        tile = score_tile_size(cap)
    # degenerate widths (below the lane width, e.g. from an odd-factor
    # cap) would build huge summaries that prune nothing useful
    if cap % tile != 0 or (tile < BLOCK and tile < cap):
        return None
    n_tiles = cap // tile
    if n_terms <= 0 or n_terms * n_tiles > TILE_SUMMARY_BUDGET:
        return None
    out = np.zeros((n_terms, n_tiles), dtype=np.float32)
    # one tile at a time: the transient (mask + fancy-index copies) is
    # a [tile, L] slice, not a second full-size copy of the forward
    # index alongside the one already resident at pack time
    for j in range(n_tiles):
        tids = fwd_tids[j * tile: (j + 1) * tile].ravel()
        imps = fwd_imps[j * tile: (j + 1) * tile].ravel()
        ok = tids >= 0
        np.maximum.at(out[:, j], tids[ok], imps[ok])
    return out


def build_tile_minmax(values: np.ndarray, exists: np.ndarray, cap: int,
                      tile: int | None = None
                      ) -> tuple[np.ndarray, np.ndarray] | None:
    """Per-tile [lo, hi] extrema of a single-valued numeric column over
    the SCORE_TILE grid — the per-clause pack-time summary that lets the
    fused bool engine prune tiles a range filter cannot match in
    (ops/scoring.bundle_tile_bounds). Tiles with no existing value get
    an empty interval (lo > hi: dtype max/min sentinels), so they always
    prune. None when the tile grid would be degenerate for this cap."""
    if tile is None:
        tile = score_tile_size(cap)
    if cap % tile != 0 or (tile < BLOCK and tile < cap):
        return None
    from . import devbuild
    if devbuild.enabled():
        try:
            return devbuild.tile_minmax_device(values, exists, cap, tile)
        except Exception as e:
            devbuild.on_fallback("tile_minmax", e)
    n_tiles = cap // tile
    v = values[:cap].reshape(n_tiles, tile)
    e = exists[:cap].reshape(n_tiles, tile)
    if values.dtype == np.float32:
        lo_pad, hi_pad = np.float32(np.inf), np.float32(-np.inf)
        # NaN values would poison the extrema (every comparison against
        # NaN is False, so the overlap test would prune tiles whose
        # OTHER docs legitimately match). A NaN doc itself can never
        # match a range, so excluding it from the extrema is exact.
        # +-inf stay in: they CAN match unbounded ranges.
        e = e & ~np.isnan(v)
    else:
        lo_pad = np.iinfo(values.dtype).max
        hi_pad = np.iinfo(values.dtype).min
    lo = np.where(e, v, lo_pad).min(axis=1)
    hi = np.where(e, v, hi_pad).max(axis=1)
    return lo, hi


# ---------------------------------------------------------------------------
# Host-side columnar structures
# ---------------------------------------------------------------------------


@dataclass
class PostingsField:
    """Inverted index for one analyzed text field, in block-CSR layout.

    terms[t] is sorted; postings of term t live in blocks
    block_start[t] : block_start[t+1] of (block_docs, block_imps), padded
    with doc_id == capacity (dropped by scatter) and impact 0.
    """

    name: str
    terms: list[str]                       # sorted
    term_index: dict[str, int]
    df: np.ndarray                         # int32 [T] document frequency
    indptr: np.ndarray                     # int64 [T+1] into doc_ids/tfs (host CSR)
    doc_ids: np.ndarray                    # int32 [nnz]
    tfs: np.ndarray                        # float32 [nnz]
    doc_len: np.ndarray                    # float32 [cap] field length per doc
    doc_count: int                         # docs containing this field
    avg_len: float
    # positional sidecar (host-side; phrase/span matching — ref: Lucene
    # postings positions consumed by PhraseQuery/SpanQuery). Positions of
    # posting j live in pos_data[pos_indptr[j] : pos_indptr[j+1]] and are
    # token indices in the (concatenated, position_increment_gap=0 as in
    # ES 2.0 StringFieldMapper) field token stream.
    pos_data: np.ndarray = dc_field(default=None, repr=False)   # int32 [sum tf]
    pos_indptr: np.ndarray = dc_field(default=None, repr=False)  # int64 [nnz+1]
    # device-layout block arrays (term-major: scatter path)
    block_docs: np.ndarray = dc_field(default=None, repr=False)  # int32 [NB,128]
    block_imps: np.ndarray = dc_field(default=None, repr=False)  # float32 [NB,128]
    block_start: np.ndarray = dc_field(default=None, repr=False)  # int32 [T+1]
    # forward index (doc-major: gather path) — score[d] for a few-term
    # query is a compare+FMA over the doc's own (term, impact) slots,
    # which vectorizes on the VPU with NO scatter. tid pad = -1, imp pad 0.
    fwd_tids: np.ndarray = dc_field(default=None, repr=False)    # int32 [cap, L]
    fwd_imps: np.ndarray = dc_field(default=None, repr=False)    # float32 [cap, L]
    # block-max summary for the fused score+top-k path: tile_max[t, j] =
    # max impact of term t among docs in tile j (SCORE_TILE-doc tiles).
    # None when the field has no forward index or exceeds the budget.
    tile_max: np.ndarray = dc_field(default=None, repr=False)    # f32 [T, J]
    # positional pack (third eager column family; device phrase/span/
    # BM25F — ops/scoring positional clause kinds). fwd_pos is forward-
    # aligned with fwd_tids: positions of the term in slot l of doc d
    # live in fwd_pos[d, l*P:(l+1)*P], delta-encoded (first entry
    # absolute, then gaps), pad -1. P = pos_width = next_pow2(max tf),
    # capped at POS_CAP. None when the field has no position sidecar,
    # no forward index, or exceeds a positional cap (host path serves).
    fwd_pos: np.ndarray = dc_field(default=None, repr=False)   # i16 [cap, L*P]
    pos_width: int = 0                                         # P (pow2)
    lnorm: np.ndarray = dc_field(default=None, repr=False)     # f32 [cap]
    k1ln: np.ndarray = dc_field(default=None, repr=False)      # f32 [cap]

    def lookup(self, term: str) -> int:
        return self.term_index.get(term, -1)

    def enc_positions(self, tid: int, stride: int) -> np.ndarray:
        """All (doc, position) pairs of a term encoded as doc*stride + pos,
        sorted ascending — the working set for vectorized phrase
        intersection (search/phrase.py)."""
        if self.pos_data is None or tid < 0:
            return np.empty(0, dtype=np.int64)
        s, e = int(self.indptr[tid]), int(self.indptr[tid + 1])
        if s == e:
            return np.empty(0, dtype=np.int64)
        ps, pe = int(self.pos_indptr[s]), int(self.pos_indptr[e])
        docs = np.repeat(self.doc_ids[s:e].astype(np.int64),
                         np.diff(self.pos_indptr[s:e + 1]).astype(np.int64))
        return docs * stride + self.pos_data[ps:pe]

    def nbytes(self) -> int:
        n = (self.block_docs.nbytes + self.block_imps.nbytes
             + self.block_start.nbytes + self.doc_len.nbytes)
        tm = getattr(self, "tile_max", None)
        if tm is not None:
            n += tm.nbytes
        fp = getattr(self, "fwd_pos", None)
        if fp is not None:
            n += fp.nbytes + self.lnorm.nbytes + self.k1ln.nbytes
        return n


@dataclass
class KeywordColumn:
    """Ordinal doc-value column for one keyword field.

    ords[d] = index into `terms` (sorted), or -1 when the doc has no value.
    Ref: index/fielddata/plain/SortedSetDVOrdinalsIndexFieldData.java +
    global ordinals (ordinals/GlobalOrdinalsBuilder.java) — here ordinals
    are segment-local; the shard maps them to shard-global ords at refresh.
    """

    name: str
    terms: list[str]                       # sorted unique values
    term_index: dict[str, int]
    ords: np.ndarray                       # int32 [cap], -1 = missing;
                                           # multi-valued docs: MIN ord
                                           # (MultiValueMode.MIN sort key)
    df: np.ndarray                         # int32 [card] docs per term
    # multi-valued sidecar: [cap, M] sorted unique ords per doc, pad -1
    # (ref: SortedSetDocValues — ordinal SETS per doc)
    mv_ords: np.ndarray = dc_field(default=None, repr=False)

    @property
    def cardinality(self) -> int:
        return len(self.terms)

    def lookup(self, term: str) -> int:
        return self.term_index.get(term, -1)

    def nbytes(self) -> int:
        n = self.ords.nbytes + self.df.nbytes
        if self.mv_ords is not None:
            n += self.mv_ords.nbytes
        return n


@dataclass
class NumericColumn:
    """Numeric/date/boolean/ip doc-value column.

    Device dtype is int32 when every value fits (exact range filters and
    exact sums for the common case — http_logs status/size, seconds-
    resolution dates); float32 otherwise. Exact int64/float64 originals
    stay host-side in `raw` for fetch/stats exactness.
    Dates are stored as epoch SECONDS in the int32 device column (covers
    1902..2038 exactly; millis precision kept in `raw`).
    """

    name: str
    kind: str                              # mapping type (long/double/date/...)
    values: np.ndarray                     # int32 or float32 [cap] device column
    exists: np.ndarray                     # bool [cap]
    raw: np.ndarray                        # int64 or float64 [cap] host-exact
    bias: int = 0                          # device value = raw - bias (ip: 2^31)
    # multi-valued sidecar (ref: SortedNumericDocValues): values beyond
    # the first live in [cap, M] arrays; mv_exists masks the pad
    mv_values: np.ndarray = dc_field(default=None, repr=False)
    mv_raw: np.ndarray = dc_field(default=None, repr=False)
    mv_exists: np.ndarray = dc_field(default=None, repr=False)

    def nbytes(self) -> int:
        n = self.values.nbytes + self.exists.nbytes
        if self.mv_values is not None:
            n += self.mv_values.nbytes + self.mv_exists.nbytes
        return n


@dataclass
class VectorColumn:
    """Dense embedding column: [capacity, dims] float32.

    The kNN read path is a single [B,dims]x[dims,cap] matmul on the MXU —
    exact search; at TPU batch throughput exact beats ANN-graph recall
    tradeoffs for shard-sized corpora (the ES analog is
    dense_vector/HNSW; ref BASELINE.json config[4]).
    """

    name: str
    values: np.ndarray                     # float32 [cap, dims]
    exists: np.ndarray                     # bool [cap]
    norms: np.ndarray                      # float32 [cap] L2 norms (0 if absent)

    @property
    def dims(self) -> int:
        return self.values.shape[1]

    def nbytes(self) -> int:
        return self.values.nbytes + self.exists.nbytes + self.norms.nbytes


@dataclass
class GeoColumn:
    """geo_point doc-value column: lat/lon float32 pairs.

    Ref: index/fielddata/plain/GeoPointDVIndexFieldData — ES stores
    encoded lat/lon doc values; here they are two flat device columns so
    haversine/bbox/polygon tests are one fused VPU pass (ops/geo.py).
    """

    name: str
    lat: np.ndarray                        # float32 [cap]
    lon: np.ndarray                        # float32 [cap]
    exists: np.ndarray                     # bool [cap]

    def nbytes(self) -> int:
        return self.lat.nbytes + self.lon.nbytes + self.exists.nbytes


@dataclass
class CompletionColumn:
    """Suggest dictionary for one completion field: per-row entry lists.

    Host-resident (suggest never needs the device — same as the
    reference, where Completion090PostingsFormat builds an FST per
    segment). entries[i] = (row, {input, output, weight, payload,
    context}).
    """

    name: str
    entries: list[tuple[int, dict]]

    def nbytes(self) -> int:
        return sum(len(i.encode()) + 16
                   for _, e in self.entries for i in e.get("input", []))


@dataclass
class Segment:
    """One immutable columnar segment."""

    seg_id: str
    num_docs: int
    capacity: int                          # next_pow2(num_docs)
    ids: list[str]
    id_map: dict[str, int]
    sources: list[bytes]
    versions: np.ndarray                   # int64 [num_docs]
    text: dict[str, PostingsField]
    keywords: dict[str, KeywordColumn]
    numerics: dict[str, NumericColumn]
    vectors: dict[str, VectorColumn] = dc_field(default_factory=dict)
    # IVF coarse indexes per dense_vector field (index/ann.AnnIndex),
    # built lazily at first eligible search (the ensure_* convention —
    # index/ann.ensure_ann) or restored by the store round-trip; delta
    # segments always serve the exact scan and never carry one
    ann: dict[str, object] = dc_field(default_factory=dict)
    geos: dict[str, GeoColumn] = dc_field(default_factory=dict)
    completions: dict[str, CompletionColumn] = dc_field(default_factory=dict)
    # block join: parent_of[d] = row of d's parent for nested sub-docs,
    # -1 for primary docs (ref: Lucene block join / ObjectMapper nested)
    parent_of: np.ndarray = dc_field(default=None, repr=False)  # int32 [cap]
    # streaming write path (index/engine.py delta mode): a DELTA segment
    # is the small append-only pack rebuilt at every refresh on top of
    # an immutable base generation. `delta_parent` is the base
    # generation key it rides on; `delta_epoch` counts rebuilds since
    # the last compaction. Base segments leave both at their defaults.
    delta_parent: str | None = None
    delta_epoch: int = 0
    # True for concat_segments products: their eager impacts were
    # PRESERVED from the source segments' field stats and cannot be
    # recomputed from this segment's own doc_count/avg_len — the store
    # must persist them (builder/merge-built segments recompute exactly)
    impacts_preserved: bool = False

    @property
    def has_nested(self) -> bool:
        return self.parent_of is not None and bool((self.parent_of >= 0).any())

    def primary_mask(self) -> np.ndarray:
        if self.parent_of is None:
            m = np.zeros(self.capacity, dtype=bool)
            m[: self.num_docs] = True
            return m
        return self.parent_of == -1

    def drop_device(self) -> None:
        """Drop every piece of HBM-resident device state derived from
        this segment — uploaded columns, the cached live-mask upload,
        layout-permuted live views, any PAGED tile buffers the tiered
        pager holds (index/tiering.py; their fielddata breaker holds
        release here, idempotently — the per-segment weakref backstop
        finding them already gone is a no-op, never a double-release)
        — AND the resident executables pinned on them
        (search/resident.py): a pinned program holds references into
        the dropped column tree, so leaving it cached would defeat the
        cache clear (and serve arrays the caller just asked to free).
        The sticky page/don't-page decision also resets: a re-upload
        re-decides against the CURRENT budget."""
        # IVF probe arrays (index/ann.ensure_ann_device) release their
        # fielddata hold deterministically here; the weakref backstop
        # finding them already released is a no-op (idempotent holds)
        for entry in getattr(self, "_ann_device", {}).values():
            hold = entry.get("_breaker_hold")
            if hold is not None:
                hold.release()
        for attr in ("_device", "_live_dev", "_live_view_cache",
                     "_tile_store", "_tiering_paged", "_ann_device"):
            if hasattr(self, attr):
                delattr(self, attr)
        from .tiering import drop_segment_tiles
        drop_segment_tiles(self.seg_id)
        from ..search.resident import evict_segment
        evict_segment(self.seg_id)

    def nbytes(self) -> int:
        n = 0
        for f in self.text.values():
            n += f.nbytes()
        for f in self.keywords.values():
            n += f.nbytes()
        for f in self.numerics.values():
            n += f.nbytes()
        for f in self.vectors.values():
            n += f.nbytes()
        # NOTE: lazily-built IVF indexes (self.ann) are excluded — their
        # device upload is breaker-accounted separately at ensure time
        # (search/executor.ensure_ann_device), after this estimate was
        # already held
        for f in self.geos.values():
            n += f.nbytes()
        return n

    def fingerprint(self) -> str:
        """Content fingerprint for restart-stable caches (the fused
        autotuner persists backend choices under it). Derived from the
        pack's shape-and-statistics signature — cheap, deterministic,
        and different whenever a refresh/merge rebuilds the segment with
        different contents — NOT from seg_id, which is minted fresh
        every process start."""
        cached = getattr(self, "_fingerprint", None)
        if cached is not None:
            return cached
        import hashlib
        h = hashlib.blake2b(digest_size=12)
        h.update(f"{self.capacity}|{self.num_docs}".encode())
        for f in sorted(self.text):
            pf = self.text[f]
            h.update(f"|t:{f}:{len(pf.terms)}:{int(pf.df.sum())}:"
                     f"{float(pf.doc_len.sum()):.3f}".encode())
        for f in sorted(self.keywords):
            kc = self.keywords[f]
            h.update(f"|k:{f}:{kc.cardinality}:{int(kc.df.sum())}".encode())
        for f in sorted(self.numerics):
            nc = self.numerics[f]
            # value-sensitive, not just count-sensitive: a refresh that
            # rewrites values but not doc counts must still re-key
            vsum = float(np.where(nc.exists,
                                  np.nan_to_num(
                                      nc.values.astype(np.float64)),
                                  0.0).sum())
            h.update(f"|n:{f}:{nc.kind}:{int(nc.exists.sum())}:"
                     f"{vsum:.6g}".encode())
        fp = h.hexdigest()
        self._fingerprint = fp  # type: ignore[attr-defined]
        return fp

    def cache_key(self) -> str:
        """Key for fingerprint-keyed caches (autotune choices, resident
        executables). Base segments key on content (`fingerprint()`),
        so a compaction re-keys. DELTA segments key on the base
        generation plus the pow2 delta-extent bucket INSTEAD of
        content: a refresh rebuilds the delta with new docs but the
        same key until its capacity bucket grows, so every cache keyed
        here survives the epoch bump untouched — refresh is an epoch
        bump, not an eviction."""
        if self.delta_parent is None:
            return self.fingerprint()
        return f"delta({self.delta_parent}):c{next_pow2(self.capacity, floor=BLOCK)}"

    def ensure_text_sort_column(self, field: str) -> bool:
        """Materialize a sortable ordinal view of an analyzed text field:
        per-doc MIN term ordinal over the postings (ref: ES 2.0 allowed
        sorting on analyzed strings via string fielddata; Lucene
        SortedSetDVs MultiValueMode.MIN). Built lazily on first sort,
        registered as a keyword column so the device sort path applies
        unchanged. Returns True only when a NEW column was materialized
        (callers must then invalidate any global-ordinal caches)."""
        with _TEXT_SORT_LOCK:
            if field in self.keywords:
                return False
            pf = self.text.get(field)
            if pf is None:
                return False
            sentinel = np.iinfo(np.int64).max
            ords64 = np.full(self.capacity, sentinel, dtype=np.int64)
            tids = np.repeat(np.arange(len(pf.terms), dtype=np.int64),
                             np.diff(pf.indptr))
            np.minimum.at(ords64, pf.doc_ids, tids)
            ords = np.where(ords64 == sentinel, -1,
                            ords64).astype(np.int32)
            col = KeywordColumn(
                name=field, terms=list(pf.terms),
                term_index=dict(pf.term_index),
                ords=ords, df=pf.df.astype(np.int32))
            # copy-on-write: concurrent searches/stats iterate these
            # dicts (ThreadingHTTPServer), so swap whole objects rather
            # than mutating in place; in-flight readers keep a
            # consistent snapshot either way
            self.keywords = {**self.keywords, field: col}
            dev = getattr(self, "_device", None)
            if dev is not None:
                import jax.numpy as jnp
                self._device = {**dev, "kw": {**dev["kw"],
                                              field: jnp.asarray(ords)}}
            return True

    def field_kind(self, name: str) -> str | None:
        if name in self.text:
            return "text"
        if name in self.keywords:
            return "keyword"
        if name in self.numerics:
            return "numeric"
        if name in self.vectors:
            return "vector"
        if name in self.geos:
            return "geo"
        return None


# ---------------------------------------------------------------------------
# Builder
# ---------------------------------------------------------------------------


class SegmentBuilder:
    """Accumulates parsed documents, emits an immutable Segment.

    Ref analog: the indexing buffer + DocumentsWriter flush in Lucene
    (engine refresh path, index/engine/InternalEngine.java:549-555).

    `similarity` maps a text field name to the Similarity whose impacts
    get baked into that field's posting blocks (ref:
    index/similarity/SimilarityService.java resolved per FieldMapper);
    None = BM25 for every field.
    """

    _counter = 0

    def __init__(self, similarity=None):
        self.docs: list[ParsedDocument] = []
        self.versions: list[int] = []
        self.parent_of: list[int] = []
        self.similarity = similarity  # Callable[[str], Similarity] | None

    def add(self, doc: ParsedDocument, version: int = 1) -> None:
        """Nested sub-documents are laid out as hidden rows BEFORE their
        parent (Lucene block-join order) with a parent pointer."""
        from .mapping import ParsedField, KEYWORD
        n_children = len(doc.nested)
        parent_row = len(self.docs) + n_children
        for i, entry in enumerate(doc.nested):
            path, fields = entry[0], list(entry[1])
            src = entry[2] if len(entry) > 2 else b""
            if not any(f.name == "_nested_path" for f in fields):
                fields.append(ParsedField(name="_nested_path", type=KEYWORD,
                                          value=path))
            self.docs.append(ParsedDocument(
                doc_id=f"{doc.doc_id}\x00{path}\x00{i}", source=src,
                fields=fields))
            self.versions.append(version)
            self.parent_of.append(parent_row)
        self.docs.append(doc)
        self.versions.append(version)
        self.parent_of.append(-1)

    def __len__(self) -> int:
        return len(self.docs)

    @property
    def num_docs(self) -> int:
        return len(self.docs)

    def build(self, seg_id: str | None = None) -> Segment:
        if seg_id is None:
            SegmentBuilder._counter += 1
            seg_id = f"seg_{SegmentBuilder._counter}"
        n = len(self.docs)
        cap = next_pow2(n, floor=BLOCK)

        ids: list[str] = []
        id_map: dict[str, int] = {}
        sources: list[bytes] = []
        # field name -> accumulated data
        text_postings: dict[str, dict[str, list[tuple[int, int]]]] = {}
        text_doclen: dict[str, np.ndarray] = {}
        kw_values: dict[str, dict[int, str]] = {}
        num_values: dict[str, tuple[str, dict[int, float | int]]] = {}
        vec_values: dict[str, dict[int, list[float]]] = {}
        geo_values: dict[str, dict[int, tuple[float, float]]] = {}
        comp_values: dict[str, list[tuple[int, dict]]] = {}

        for d, doc in enumerate(self.docs):
            ids.append(doc.doc_id)
            id_map[doc.doc_id] = d
            sources.append(doc.source)
            # accumulate per-field; multiple ParsedFields with same name =
            # array values (text concatenates tokens BEFORE tf counting so a
            # doc contributes exactly one postings entry per term; keyword/
            # numeric keep first — multi-valued columns land round 2)
            doc_tokens: dict[str, list[str]] = {}
            for pf in doc.fields:
                if pf.type == TEXT:
                    doc_tokens.setdefault(pf.name, []).extend(pf.tokens or [])
                elif pf.type == KEYWORD:
                    col = kw_values.setdefault(pf.name, {})
                    col.setdefault(d, []).append(str(pf.value))
                elif pf.type == DENSE_VECTOR:
                    vcol = vec_values.setdefault(pf.name, {})
                    if d not in vcol:
                        vcol[d] = pf.value  # type: ignore[assignment]
                elif pf.type == GEO_POINT:
                    gcol = geo_values.setdefault(pf.name, {})
                    if d not in gcol:
                        gcol[d] = pf.value  # (lat, lon)
                elif pf.type == "completion":
                    comp_values.setdefault(pf.name, []).append((d, pf.value))
                else:
                    kind, col = num_values.setdefault(pf.name, (pf.type, {}))
                    col.setdefault(d, []).append(pf.value)
            for fname, toks in doc_tokens.items():
                postings = text_postings.setdefault(fname, {})
                if fname not in text_doclen:
                    text_doclen[fname] = np.zeros(cap, dtype=np.float32)
                text_doclen[fname][d] += float(len(toks))
                pos_local: dict[str, list[int]] = {}
                for i, tok in enumerate(toks):
                    pos_local.setdefault(tok, []).append(i)
                for term, positions in pos_local.items():
                    postings.setdefault(term, []).append((d, positions))

        text = {
            name: self._build_postings(name, postings, text_doclen[name], n,
                                       cap, self._sim_for(name))
            for name, postings in text_postings.items()
        }
        keywords = {
            name: self._build_keyword(name, col, cap)
            for name, col in kw_values.items()
        }
        numerics = {
            name: self._build_numeric(name, kind, col, cap)
            for name, (kind, col) in num_values.items()
        }
        vectors = {
            name: self._build_vector(name, col, cap)
            for name, col in vec_values.items()
        }
        geos = {
            name: self._build_geo(name, col, cap)
            for name, col in geo_values.items()
        }
        completions = {
            name: CompletionColumn(name=name, entries=entries)
            for name, entries in comp_values.items()
        }

        parent_of = None
        if any(p >= 0 for p in self.parent_of):
            parent_of = np.full(cap, -1, dtype=np.int32)
            parent_of[:n] = self.parent_of
        return Segment(
            seg_id=seg_id, num_docs=n, capacity=cap,
            ids=ids, id_map=id_map, sources=sources,
            versions=np.asarray(self.versions, dtype=np.int64),
            text=text, keywords=keywords, numerics=numerics, vectors=vectors,
            geos=geos, completions=completions, parent_of=parent_of,
        )

    def _sim_for(self, field: str):
        if self.similarity is None:
            return None
        return self.similarity(field)

    @staticmethod
    def _build_geo(name: str, col: dict[int, tuple[float, float]], cap: int
                   ) -> GeoColumn:
        lat = np.zeros(cap, dtype=np.float32)
        lon = np.zeros(cap, dtype=np.float32)
        exists = np.zeros(cap, dtype=bool)
        for d, (la, lo) in col.items():
            lat[d] = la
            lon[d] = lo
            exists[d] = True
        return GeoColumn(name=name, lat=lat, lon=lon, exists=exists)

    @staticmethod
    def _build_vector(name: str, col: dict[int, list[float]], cap: int
                      ) -> VectorColumn:
        dims = len(next(iter(col.values())))
        values = np.zeros((cap, dims), dtype=np.float32)
        exists = np.zeros(cap, dtype=bool)
        for d, vec in col.items():
            values[d, : len(vec)] = np.asarray(vec, dtype=np.float32)
            exists[d] = True
        norms = np.linalg.norm(values, axis=1).astype(np.float32)
        return VectorColumn(name=name, values=values, exists=exists,
                            norms=norms)

    # -- per-field builders ------------------------------------------------

    @staticmethod
    def _build_postings(name: str, postings: dict[str, list[tuple[int, list[int]]]],
                        doc_len: np.ndarray, n_docs: int, cap: int,
                        sim=None) -> PostingsField:
        terms = sorted(postings)
        term_index = {t: i for i, t in enumerate(terms)}
        df = np.array([len(postings[t]) for t in terms], dtype=np.int32)
        indptr = np.zeros(len(terms) + 1, dtype=np.int64)
        np.cumsum(df, out=indptr[1:])
        nnz = int(indptr[-1])
        doc_ids = np.empty(nnz, dtype=np.int32)
        tfs = np.empty(nnz, dtype=np.float32)
        pos_chunks: list[list[int]] = []
        for i, t in enumerate(terms):
            plist = postings[t]  # already in doc order (docs added in order)
            s = indptr[i]
            for j, (d, positions) in enumerate(plist):
                doc_ids[s + j] = d
                tfs[s + j] = len(positions)
                pos_chunks.append(positions)
        pos_indptr = np.zeros(nnz + 1, dtype=np.int64)
        np.cumsum([len(c) for c in pos_chunks], out=pos_indptr[1:])
        pos_data = (np.concatenate([np.asarray(c, dtype=np.int32)
                                    for c in pos_chunks])
                    if pos_chunks else np.empty(0, dtype=np.int32))

        doc_count = int(np.count_nonzero(doc_len[:n_docs])) or n_docs
        total_len = float(doc_len.sum())
        avg_len = (total_len / doc_count) if doc_count else 1.0

        pf = PostingsField(
            name=name, terms=terms, term_index=term_index, df=df,
            indptr=indptr, doc_ids=doc_ids, tfs=tfs,
            doc_len=doc_len, doc_count=doc_count, avg_len=max(avg_len, 1e-9),
            pos_data=pos_data, pos_indptr=pos_indptr,
        )
        SegmentBuilder._layout_blocks(pf, cap, sim)
        return pf

    @staticmethod
    def _layout_blocks(pf: PostingsField, cap: int, sim=None) -> None:
        """Pack host CSR postings into 128-lane blocks with eager impacts.

        The impact formula comes from the field's Similarity (BM25 by
        default; index/similarity.py) — the only place a similarity
        choice touches the engine; every query path downstream consumes
        impacts uniformly."""
        _pack_layout(pf, cap, _flat_impacts(pf, sim))

    @staticmethod
    def _build_keyword(name: str, col: dict[int, list[str]], cap: int
                       ) -> KeywordColumn:
        terms = sorted({v for vs in col.values() for v in vs})
        term_index = {t: i for i, t in enumerate(terms)}
        per_doc = {d: sorted({term_index[v] for v in vs})
                   for d, vs in col.items()}
        ords = np.full(cap, -1, dtype=np.int32)
        for d, os_ in per_doc.items():
            ords[d] = os_[0]           # MIN ord (MultiValueMode.MIN)
        df = np.zeros(len(terms), dtype=np.int32)
        for os_ in per_doc.values():
            df[os_] += 1               # doc freq counts docs, not values
        mv = None
        max_len = max((len(o) for o in per_doc.values()), default=1)
        if max_len > 1:
            M = next_pow2(max_len, floor=2)
            mv = np.full((cap, M), -1, dtype=np.int32)
            for d, os_ in per_doc.items():
                mv[d, : len(os_)] = os_
        return KeywordColumn(name=name, terms=terms, term_index=term_index,
                             ords=ords, df=df, mv_ords=mv)

    @staticmethod
    def _build_numeric(name: str, kind: str, col: dict[int, list],
                       cap: int) -> NumericColumn:
        exists = np.zeros(cap, dtype=bool)
        is_int = kind in (LONG, INTEGER, SHORT, BYTE, DATE, BOOLEAN, IP)
        dt = np.int64 if is_int else np.float64
        raw = np.zeros(cap, dtype=dt)

        def norm(v):
            if kind == BOOLEAN:
                return 1 if v else 0
            return v

        for d, vs in col.items():
            exists[d] = True
            # MIN value, matching the keyword column's MIN-ord sort key
            # (MultiValueMode.MIN, the ES asc-sort default)
            raw[d] = min(norm(v) for v in vs)
        bias = 1 << 31 if kind == IP else 0
        vals = _device_vals(raw, kind, bias, is_int)
        mv_raw = mv_vals = mv_exists = None
        max_len = max((len(v) for v in col.values()), default=1)
        if max_len > 1:
            M = next_pow2(max_len, floor=2)
            mv_raw = np.zeros((cap, M), dtype=dt)
            mv_exists = np.zeros((cap, M), dtype=bool)
            for d, vs in col.items():
                for j, v in enumerate(vs[:M]):
                    mv_raw[d, j] = norm(v)
                    mv_exists[d, j] = True
            mv_vals = _device_vals(mv_raw, kind, bias, is_int)
        return NumericColumn(name=name, kind=kind, values=vals, exists=exists,
                             raw=raw, bias=bias, mv_values=mv_vals,
                             mv_raw=mv_raw, mv_exists=mv_exists)


def _flat_impacts(pf: PostingsField, sim=None) -> np.ndarray:
    """Per-posting eager impacts in CSR order ([nnz] f32), computed from
    the field's Similarity + field stats. Split out of the layout pass
    so an impact-PRESERVING repack (concat_segments, the streaming
    compaction) can feed recovered impacts through the same packer."""
    if sim is None:
        from .similarity import DEFAULT_SIMILARITY
        sim = DEFAULT_SIMILARITY
    from .similarity import FieldStats
    T = len(pf.terms)
    total_len = float(pf.doc_len.sum())
    ttf_all = np.zeros(T, dtype=np.float64)
    np.add.at(ttf_all,
              np.repeat(np.arange(T), np.diff(pf.indptr)),
              pf.tfs.astype(np.float64))
    out = np.zeros(len(pf.doc_ids), dtype=np.float32)
    for t in range(T):
        s, e = int(pf.indptr[t]), int(pf.indptr[t + 1])
        if s == e:
            continue
        docs = pf.doc_ids[s:e]
        tf = pf.tfs[s:e].astype(np.float64)
        st = FieldStats(df=float(pf.df[t]), ttf=float(ttf_all[t]),
                        doc_count=float(pf.doc_count),
                        avg_len=float(pf.avg_len), total_len=total_len)
        out[s:e] = sim.impacts(tf, pf.doc_len[docs].astype(np.float64), st)
    return out


def extract_flat_impacts(pf: PostingsField) -> np.ndarray:
    """Recover the [nnz] CSR-order impacts from the packed block arrays
    — the inverse of _pack_layout's block fill, exact by construction
    (blocks are contiguous BLOCK-lane slices of each term's posting
    run). The streaming compaction reads impacts back through this so a
    compacted base scores byte-identically to the packs it folded."""
    from . import devbuild
    if devbuild.enabled():
        try:
            # vectorized exact gather (no float math) — the compaction
            # feed of the device-parallel build path
            return devbuild.extract_flat_impacts_fast(pf)
        except Exception as e:
            devbuild.on_fallback("extract_impacts", e)
    nnz = len(pf.doc_ids)
    out = np.empty(nnz, dtype=np.float32)
    T = len(pf.terms)
    for t in range(T):
        s, e = int(pf.indptr[t]), int(pf.indptr[t + 1])
        b0 = int(pf.block_start[t])
        for off in range(0, e - s, BLOCK):
            blk = b0 + off // BLOCK
            ln = min(BLOCK, e - s - off)
            out[s + off: s + off + ln] = pf.block_imps[blk, :ln]
    return out


def _pack_layout(pf: PostingsField, cap: int, imps: np.ndarray) -> None:
    """Device layouts (128-lane blocks, forward index, block-max tile
    summary) from CSR postings + precomputed per-posting impacts.

    This is the ONE seam every pack build flows through — builder
    refresh, merge_segments (repack's build-aside) and concat_segments
    (compaction) all land here — so the device-parallel builder
    (index/devbuild.py) hooks in here: when enabled, the layout pass
    runs as exact device scatters (byte-identical output), and ANY
    device error falls back to the host loops below."""
    from . import devbuild
    if devbuild.enabled():
        try:
            devbuild.pack_layout_device(pf, cap, imps)
            return
        except Exception as e:
            devbuild.on_fallback("pack_layout", e)
    _pack_layout_host(pf, cap, imps)


def _pack_layout_host(pf: PostingsField, cap: int,
                      imps: np.ndarray) -> None:
    """Host reference implementation of the layout pass (per-term
    Python loops) — the fallback, and the identity oracle the device
    path is tested against."""
    T = len(pf.terms)
    n_blocks_per_term = (np.diff(pf.indptr) + BLOCK - 1) // BLOCK
    block_start = np.zeros(T + 1, dtype=np.int32)
    np.cumsum(n_blocks_per_term, out=block_start[1:])
    nb = int(block_start[-1])
    nb_pad = next_pow2(nb, floor=1)
    block_docs = np.full((nb_pad, BLOCK), cap, dtype=np.int32)  # cap = dropped
    block_imps = np.zeros((nb_pad, BLOCK), dtype=np.float32)
    for t in range(T):
        s, e = int(pf.indptr[t]), int(pf.indptr[t + 1])
        docs = pf.doc_ids[s:e]
        imp = imps[s:e]
        b0 = int(block_start[t])
        for off in range(0, e - s, BLOCK):
            blk = b0 + off // BLOCK
            ln = min(BLOCK, e - s - off)
            block_docs[blk, :ln] = docs[off:off + ln]
            block_imps[blk, :ln] = imp[off:off + ln]
    pf.block_docs = block_docs
    pf.block_imps = block_imps
    pf.block_start = block_start

    # forward (doc-major) layout from the same impacts. One doc with
    # thousands of unique terms would inflate the dense [cap, L]
    # arrays for the whole segment, so past MAX_FWD_SLOTS the field
    # skips the forward index and queries take the scatter path.
    lengths = np.zeros(cap, dtype=np.int64)
    np.add.at(lengths, pf.doc_ids, 1)
    L = next_pow2(int(lengths.max(initial=1)), floor=8)
    if L > MAX_FWD_SLOTS:
        pf.fwd_tids = None
        pf.fwd_imps = None
        return
    fwd_tids = np.full((cap, L), -1, dtype=np.int32)
    fwd_imps = np.zeros((cap, L), dtype=np.float32)
    slot = np.zeros(cap, dtype=np.int64)
    for t in range(T):
        s, e = int(pf.indptr[t]), int(pf.indptr[t + 1])
        docs = pf.doc_ids[s:e]
        b0 = int(block_start[t])
        for off in range(0, e - s, BLOCK):
            blk = b0 + off // BLOCK
            ln = min(BLOCK, e - s - off)
            d_slice = docs[off:off + ln]
            j = slot[d_slice]
            fwd_tids[d_slice, j] = t
            fwd_imps[d_slice, j] = block_imps[blk, :ln]
            slot[d_slice] = j + 1
    pf.fwd_tids = fwd_tids
    pf.fwd_imps = fwd_imps
    pf.tile_max = build_tile_max(fwd_tids, fwd_imps, T, cap)
    pack_positions(pf, cap)


def forward_slot_ranks(doc_ids: np.ndarray) -> np.ndarray:
    """Per-posting forward-index slot, CSR order — the rank of each
    posting among its doc's postings in term-major order, exactly the
    slot counter _pack_layout_host's forward fill assigns (and the
    device builder's ops/build.forward_slots). Lets the positional
    pack land each posting's positions in the slot its (tid, impact)
    pair occupies."""
    nnz = len(doc_ids)
    order = np.argsort(doc_ids, kind="stable")
    sorted_docs = doc_ids[order]
    first = np.searchsorted(sorted_docs, sorted_docs, side="left")
    out = np.empty(nnz, dtype=np.int64)
    out[order] = np.arange(nnz, dtype=np.int64) - first
    return out


def position_deltas(pf: PostingsField) -> np.ndarray:
    """[sum tf] int16 delta stream of the position sidecar: per posting
    the first entry is the absolute token position, the rest are gaps
    (strictly positive — one token per position). Exact int math, so
    host and device packs are byte-identical by construction."""
    pd = pf.pos_data.astype(np.int64)
    d = pd.copy()
    d[1:] -= pd[:-1]
    counts = np.diff(pf.pos_indptr)
    starts = pf.pos_indptr[:-1][counts > 0]
    d[starts] = pd[starts]
    return d.astype(np.int16)


def pos_pack_width(pf: PostingsField, cap: int, L: int) -> int | None:
    """P (pow2 positions-per-slot bucket) for a field's positional
    pack, or None with the field staying host-served: no sidecar, tf
    over POS_CAP, positions past the int16 ceiling, or a pack bigger
    than POS_PACK_BUDGET elements. The pow2 bucket is the
    pad_delta_shapes convention: P only changes at pow2 boundaries, so
    delta growth within a bucket never re-shapes the pack."""
    if pf.pos_data is None or pf.pos_indptr is None:
        return None
    max_tf = int(np.diff(pf.pos_indptr).max(initial=0))
    if max_tf <= 0 or max_tf > POS_CAP:
        return None
    if pf.pos_data.size and int(pf.pos_data.max(initial=0)) > POS_MAX_ENC:
        return None
    P = next_pow2(max_tf, floor=2)
    if cap * L * P > POS_PACK_BUDGET:
        return None
    return P


def pack_positions(pf: PostingsField, cap: int) -> None:
    """Build the eager positional column family (fwd_pos + the BM25
    length-norm columns) from the position sidecar, forward-aligned
    with fwd_tids. Shared by the host layout pass and the device
    builder's fallback; ops/build.scatter_positions is the device
    scatter twin (identical int output)."""
    pf.fwd_pos = None
    pf.pos_width = 0
    pf.lnorm = None
    pf.k1ln = None
    if pf.fwd_tids is None:
        return
    L = pf.fwd_tids.shape[1]
    P = pos_pack_width(pf, cap, L)
    if P is None:
        return
    deltas = position_deltas(pf)
    doc_pp, flat_pp = _position_targets(pf, P)
    fwd_pos = np.full((cap, L * P), -1, dtype=np.int16)
    fwd_pos[doc_pp, flat_pp] = deltas
    pf.fwd_pos = fwd_pos
    pf.pos_width = P
    pf.lnorm, pf.k1ln = bm25_norms(pf.doc_len, pf.avg_len)


def _position_targets(pf: PostingsField, P: int
                      ) -> tuple[np.ndarray, np.ndarray]:
    """Per-POSITION (doc row, slot*P + k) scatter targets — host int
    vector math shared by pack_positions and the device builder."""
    counts = np.diff(pf.pos_indptr).astype(np.int64)
    slots = forward_slot_ranks(pf.doc_ids)
    doc_pp = np.repeat(pf.doc_ids.astype(np.int64), counts)
    slot_pp = np.repeat(slots, counts)
    k_pp = (np.arange(int(counts.sum()), dtype=np.int64)
            - np.repeat(pf.pos_indptr[:-1].astype(np.int64), counts))
    return doc_pp, slot_pp * P + k_pp


def pad_delta_shapes(seg: Segment) -> Segment:
    """Bucket every TERM-COUNT-derived device array of a delta segment
    to the next power of two, so the shape signature of the pack — and
    with it every jit program, pinned resident executable, and autotune
    shape bucket — stays constant while the delta grows within a
    bucket. Capacity, forward width L, and block counts are already
    pow2; term count T was the one content-proportional shape left.
    Padded tile_max rows carry zero impact (an absent term bounds to 0
    and can never un-prune a tile — the PackedShards convention);
    padded block_start entries repeat the final block (zero postings).
    Mutates and returns `seg`."""
    for pf in seg.text.values():
        T = len(pf.terms)
        t_pad = next_pow2(max(T, 1), floor=8)
        if pf.tile_max is not None and pf.tile_max.shape[0] < t_pad:
            pad = np.zeros((t_pad - pf.tile_max.shape[0],
                            pf.tile_max.shape[1]), np.float32)
            pf.tile_max = np.concatenate([pf.tile_max, pad], axis=0)
        if pf.block_start is not None and len(pf.block_start) < t_pad + 1:
            pf.block_start = np.concatenate(
                [pf.block_start,
                 np.full(t_pad + 1 - len(pf.block_start),
                         pf.block_start[-1], dtype=pf.block_start.dtype)])
    return seg


def concat_segments(segments: Iterable[Segment], seg_id: str | None = None,
                    live_masks: dict[str, np.ndarray] | None = None
                    ) -> Segment:
    """Impact-PRESERVING columnar concatenation — the streaming write
    path's compaction (fold delta segments into a new base while the
    old generation keeps serving).

    Unlike merge_segments (which re-derives tokens and recomputes
    impacts under the merged field stats), this repack keeps every
    surviving posting's eager impact EXACTLY as the source pack scored
    it: term dictionaries union, doc rows renumber (dead rows drop),
    and the device layouts rebuild from the preserved impacts — so a
    search against the compacted base is byte-identical to the same
    search against the base+delta pair it folded, which is the
    correctness contract the background compaction swap relies on. It
    is also the throughput story (arxiv 1910.11028, BM25S eager
    scoring): compaction cost is a columnar copy, not a re-tokenize +
    re-score of the corpus."""
    from .mapping import ParsedField  # noqa: F401 (parity with merge_segments)
    segs = [s for s in segments if s.num_docs > 0]
    if seg_id is None:
        SegmentBuilder._counter += 1
        seg_id = f"seg_{SegmentBuilder._counter}"

    # -- row survival + renumbering ---------------------------------------
    keeps: list[np.ndarray] = []          # bool [num_docs] per seg
    row_maps: list[np.ndarray] = []       # old row -> new row (-1 dead)
    n = 0
    for s in segs:
        live = None if live_masks is None else live_masks.get(s.seg_id)
        keep = (np.ones(s.num_docs, dtype=bool) if live is None
                else np.array(live[: s.num_docs], dtype=bool, copy=True))
        if s.parent_of is not None:
            ch = s.parent_of[: s.num_docs] >= 0
            keep[ch] &= keep[s.parent_of[: s.num_docs][ch]]
        rm = np.full(s.num_docs, -1, dtype=np.int64)
        rm[keep] = n + np.arange(int(keep.sum()))
        keeps.append(keep)
        row_maps.append(rm)
        n += int(keep.sum())
    cap = next_pow2(n, floor=BLOCK)

    ids: list[str] = []
    sources: list[bytes] = []
    versions = np.ones(n, dtype=np.int64)
    parent_new = np.full(cap, -1, dtype=np.int32)
    any_nested = False
    for s, keep, rm in zip(segs, keeps, row_maps):
        for d in np.nonzero(keep)[0]:
            d = int(d)
            ids.append(s.ids[d])
            sources.append(s.sources[d])
            versions[rm[d]] = int(s.versions[d])
            if s.parent_of is not None and s.parent_of[d] >= 0:
                parent_new[rm[d]] = rm[int(s.parent_of[d])]
                any_nested = True

    # -- text fields: CSR merge with preserved impacts --------------------
    text: dict[str, PostingsField] = {}
    text_names = sorted({f for s in segs for f in s.text})
    for name in text_names:
        all_terms = sorted({t for s in segs for t in
                            (s.text[name].terms if name in s.text else ())})
        t_index = {t: i for i, t in enumerate(all_terms)}
        tid_parts, doc_parts, tf_parts, imp_parts = [], [], [], []
        pos_parts, plen_parts = [], []
        doc_len = np.zeros(cap, dtype=np.float32)
        # one legacy source without the positional sidecar poisons the
        # merged field's: an EMPTY pos array would make phrase queries
        # silently match nothing, where pos_data=None correctly
        # degrades them (QueryBinder's conjunctive approximation)
        have_positions = all(s.text[name].pos_data is not None
                             for s in segs if name in s.text)
        for s, keep, rm in zip(segs, keeps, row_maps):
            pf = s.text.get(name)
            if pf is None:
                continue
            kept_rows = np.nonzero(keep)[0]
            doc_len[rm[kept_rows]] += pf.doc_len[kept_rows]
            nnz = len(pf.doc_ids)
            if nnz == 0:
                continue
            sel = keep[pf.doc_ids]
            if not sel.any():
                continue
            tids = np.repeat(np.arange(len(pf.terms), dtype=np.int64),
                             np.diff(pf.indptr))
            remap = np.asarray([t_index[t] for t in pf.terms],
                               dtype=np.int64)
            flat = extract_flat_impacts(pf)
            tid_parts.append(remap[tids[sel]])
            doc_parts.append(rm[pf.doc_ids[sel]])
            tf_parts.append(pf.tfs[sel])
            imp_parts.append(flat[sel])
            if pf.pos_data is not None:
                plens = np.diff(pf.pos_indptr)[sel]
                plen_parts.append(plens)
                pos_sel = np.repeat(sel, np.diff(pf.pos_indptr))
                pos_parts.append(pf.pos_data[pos_sel])
            else:
                plen_parts.append(np.zeros(int(sel.sum()), dtype=np.int64))
                pos_parts.append(np.empty(0, dtype=np.int32))
        if tid_parts:
            tid_all = np.concatenate(tid_parts)
            doc_all = np.concatenate(doc_parts)
            tf_all = np.concatenate(tf_parts)
            imp_all = np.concatenate(imp_parts)
            plen_all = np.concatenate(plen_parts)
            pos_all = (np.concatenate(pos_parts) if pos_parts
                       else np.empty(0, dtype=np.int32))
        else:
            tid_all = doc_all = np.empty(0, dtype=np.int64)
            tf_all = imp_all = np.empty(0, dtype=np.float32)
            plen_all = np.empty(0, dtype=np.int64)
            pos_all = np.empty(0, dtype=np.int32)
        # stable (term, new-doc) order: per-seg runs are doc-ascending
        # and row renumbering is order-preserving, so lexsort == the
        # concat order a fresh build over the same rows would produce
        order = np.lexsort((doc_all, tid_all))
        tid_all, doc_all = tid_all[order], doc_all[order]
        tf_all, imp_all = tf_all[order], imp_all[order]
        plen_all = plen_all[order]
        # positions follow their posting through the permutation
        pos_off = np.zeros(len(plen_all) + 1, dtype=np.int64)
        if len(plen_all):
            pre = np.concatenate(plen_parts)  # pre-permutation lengths
            starts = np.zeros(len(pre) + 1, dtype=np.int64)
            np.cumsum(pre, out=starts[1:])
            chunks = [pos_all[starts[j]: starts[j + 1]] for j in order]
            pos_all = (np.concatenate(chunks) if chunks
                       else np.empty(0, dtype=np.int32))
            np.cumsum(plen_all, out=pos_off[1:])
        T = len(all_terms)
        df = np.bincount(tid_all, minlength=T).astype(np.int32)
        indptr = np.zeros(T + 1, dtype=np.int64)
        np.cumsum(df, out=indptr[1:])
        doc_count = int(np.count_nonzero(doc_len[:n])) or n
        total_len = float(doc_len.sum())
        avg_len = (total_len / doc_count) if doc_count else 1.0
        pf_new = PostingsField(
            name=name, terms=all_terms, term_index=t_index, df=df,
            indptr=indptr, doc_ids=doc_all.astype(np.int32),
            tfs=tf_all.astype(np.float32), doc_len=doc_len,
            doc_count=doc_count, avg_len=max(avg_len, 1e-9),
            pos_data=(pos_all.astype(np.int32) if have_positions
                      else None),
            pos_indptr=(pos_off if have_positions else None),
        )
        _pack_layout(pf_new, cap, imp_all.astype(np.float32))
        text[name] = pf_new

    # -- keyword columns ---------------------------------------------------
    keywords: dict[str, KeywordColumn] = {}
    kw_names = sorted({f for s in segs for f in s.keywords
                       if f not in s.text})  # text-sort views rebuild lazily
    for name in kw_names:
        all_terms = sorted({t for s in segs
                            for t in (s.keywords[name].terms
                                      if name in s.keywords else ())})
        t_index = {t: i for i, t in enumerate(all_terms)}
        ords = np.full(cap, -1, dtype=np.int32)
        mv_width = 0
        per_seg_remap = []
        for s in segs:
            kc = s.keywords.get(name)
            per_seg_remap.append(
                None if kc is None else
                np.asarray([t_index[t] for t in kc.terms], dtype=np.int32))
            if kc is not None and kc.mv_ords is not None:
                mv_width = max(mv_width, kc.mv_ords.shape[1])
        mv = (np.full((cap, next_pow2(mv_width, floor=2)), -1,
                      dtype=np.int32) if mv_width else None)
        df = np.zeros(len(all_terms), dtype=np.int32)
        for s, keep, rm, remap in zip(segs, keeps, row_maps,
                                      per_seg_remap):
            kc = s.keywords.get(name)
            if kc is None or remap is None:
                continue
            rows = np.nonzero(keep)[0]
            loc = kc.ords[rows]
            has = loc >= 0
            ords[rm[rows[has]]] = remap[loc[has]]
            if kc.mv_ords is not None and mv is not None:
                lmv = kc.mv_ords[rows]
                hmv = lmv >= 0
                vals = np.where(hmv, remap[np.clip(lmv, 0, None)], -1)
                mv[rm[rows], : lmv.shape[1]] = vals
                for r, row_vals in zip(rm[rows], vals):
                    u = np.unique(row_vals[row_vals >= 0])
                    df[u] += 1
            else:
                if mv is not None:
                    mv[rm[rows[has]], 0] = remap[loc[has]]
                u, c = np.unique(remap[loc[has]], return_counts=True)
                df[u] += c.astype(np.int32)
        keywords[name] = KeywordColumn(
            name=name, terms=all_terms, term_index=t_index, ords=ords,
            df=df, mv_ords=mv)

    # -- numeric / vector / geo / completion columns -----------------------
    numerics: dict[str, NumericColumn] = {}
    num_names = sorted({f for s in segs for f in s.numerics})
    for name in num_names:
        kind = next(s.numerics[name].kind for s in segs
                    if name in s.numerics)
        is_int = all(s.numerics[name].raw.dtype == np.int64
                     for s in segs if name in s.numerics)
        dt = np.int64 if is_int else np.float64
        raw = np.zeros(cap, dtype=dt)
        exists = np.zeros(cap, dtype=bool)
        mv_width = max((s.numerics[name].mv_raw.shape[1]
                        for s in segs if name in s.numerics
                        and s.numerics[name].mv_raw is not None),
                       default=0)
        mv_raw = (np.zeros((cap, mv_width), dtype=dt) if mv_width else None)
        mv_exists = (np.zeros((cap, mv_width), dtype=bool)
                     if mv_width else None)
        bias = 1 << 31 if kind == IP else 0
        for s, keep, rm in zip(segs, keeps, row_maps):
            nc = s.numerics.get(name)
            if nc is None:
                continue
            rows = np.nonzero(keep)[0]
            raw[rm[rows]] = nc.raw[rows].astype(dt)
            exists[rm[rows]] = nc.exists[rows]
            if mv_raw is not None:
                if nc.mv_raw is not None:
                    w = nc.mv_raw.shape[1]
                    mv_raw[rm[rows], :w] = nc.mv_raw[rows].astype(dt)
                    mv_exists[rm[rows], :w] = nc.mv_exists[rows]
                else:
                    has = nc.exists[rows]
                    mv_raw[rm[rows[has]], 0] = nc.raw[rows[has]].astype(dt)
                    mv_exists[rm[rows[has]], 0] = True
        numerics[name] = NumericColumn(
            name=name, kind=kind, values=_device_vals(raw, kind, bias,
                                                      is_int),
            exists=exists, raw=raw, bias=bias,
            mv_values=(None if mv_raw is None
                       else _device_vals(mv_raw, kind, bias, is_int)),
            mv_raw=mv_raw, mv_exists=mv_exists)

    vectors: dict[str, VectorColumn] = {}
    for name in sorted({f for s in segs for f in s.vectors}):
        dims = next(s.vectors[name].dims for s in segs if name in s.vectors)
        vals = np.zeros((cap, dims), dtype=np.float32)
        exists = np.zeros(cap, dtype=bool)
        for s, keep, rm in zip(segs, keeps, row_maps):
            vc = s.vectors.get(name)
            if vc is None:
                continue
            rows = np.nonzero(keep)[0]
            vals[rm[rows]] = vc.values[rows]
            exists[rm[rows]] = vc.exists[rows]
        vectors[name] = VectorColumn(
            name=name, values=vals, exists=exists,
            norms=np.linalg.norm(vals, axis=1).astype(np.float32))

    # -- ANN carry-over: skip the IVF rebuild when the source column is
    # unchanged. When exactly ONE source segment holds a vector field,
    # already has its IVF index, and every one of its rows survives at
    # the SAME ordinal (identity row map — the deletes-only / pure-
    # append compaction shape), the merged column is byte-equal to the
    # source column, so the source index (centroids, members, radii)
    # is still exact and transplants as-is instead of re-clustering.
    ann_carry: dict[str, object] = {}
    for name in vectors:
        srcs = [(s, keep, rm) for s, keep, rm
                in zip(segs, keeps, row_maps) if name in s.vectors]
        if len(srcs) != 1:
            continue
        s0, keep0, rm0 = srcs[0]
        src_ai = s0.ann.get(name)
        if src_ai is None or not bool(keep0.all()):
            continue
        if not np.array_equal(rm0, np.arange(s0.num_docs)):
            continue
        ann_carry[name] = src_ai
        from . import devbuild
        devbuild.count_skipped("ann")

    geos: dict[str, GeoColumn] = {}
    for name in sorted({f for s in segs for f in s.geos}):
        lat = np.zeros(cap, dtype=np.float32)
        lon = np.zeros(cap, dtype=np.float32)
        exists = np.zeros(cap, dtype=bool)
        for s, keep, rm in zip(segs, keeps, row_maps):
            gc = s.geos.get(name)
            if gc is None:
                continue
            rows = np.nonzero(keep)[0]
            lat[rm[rows]] = gc.lat[rows]
            lon[rm[rows]] = gc.lon[rows]
            exists[rm[rows]] = gc.exists[rows]
        geos[name] = GeoColumn(name=name, lat=lat, lon=lon, exists=exists)

    completions: dict[str, CompletionColumn] = {}
    for name in sorted({f for s in segs for f in s.completions}):
        entries: list[tuple[int, dict]] = []
        for s, keep, rm in zip(segs, keeps, row_maps):
            cc = s.completions.get(name)
            if cc is None:
                continue
            for row, entry in cc.entries:
                if row < len(keep) and keep[row]:
                    entries.append((int(rm[row]), entry))
        completions[name] = CompletionColumn(name=name, entries=entries)

    return Segment(
        seg_id=seg_id, num_docs=n, capacity=cap,
        ids=ids, id_map={i: j for j, i in enumerate(ids)},
        sources=sources, versions=versions,
        text=text, keywords=keywords, numerics=numerics, vectors=vectors,
        ann=ann_carry,
        geos=geos, completions=completions,
        parent_of=parent_new if any_nested else None,
        impacts_preserved=True,
    )


def _device_vals(raw: np.ndarray, kind: str, bias: int,
                 is_int: bool) -> np.ndarray:
    """Host-exact raw values -> device column dtype (see NumericColumn)."""
    if kind == DATE:
        return (raw // 1000).astype(np.int32)   # epoch seconds, int32-exact
    if kind == IP:
        # uint32 address space biased into int32 so adjacent IPs stay
        # exact (float32's 24-bit mantissa would smear /24 ranges)
        return (raw - bias).astype(np.int32)
    if is_int:
        lo, hi = raw.min(initial=0), raw.max(initial=0)
        if np.iinfo(np.int32).min <= lo and hi <= np.iinfo(np.int32).max:
            return raw.astype(np.int32)
        return raw.astype(np.float32)  # precision caveat: > 2^24 longs
    return raw.astype(np.float32)


class _ConstList:
    """O(1)-memory stand-in for per-doc host lists (sources of a
    columnar bulk load are synthesized, not stored)."""

    __slots__ = ("_value", "_n")

    def __init__(self, value, n: int):
        self._value = value
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._value] * len(range(*i.indices(self._n)))
        return self._value


class _RangeIds:
    """Virtual id list "0".."n-1" — 20M python strings would cost GBs."""

    __slots__ = ("_n",)

    def __init__(self, n: int):
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [str(j) for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        return str(i)

    def __iter__(self):
        return (str(i) for i in range(self._n))


class _RangeIdMap:
    """Virtual {str(i): i} map matching _RangeIds."""

    __slots__ = ("_n",)

    def __init__(self, n: int):
        self._n = n

    def get(self, key, default=None):
        try:
            i = int(key)
        except (TypeError, ValueError):
            return default
        if 0 <= i < self._n and str(i) == key:
            return i
        return default

    def __getitem__(self, key):
        v = self.get(key)
        if v is None:
            raise KeyError(key)
        return v

    def __contains__(self, key) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return self._n


def build_columnar(seg_id: str, n: int, *,
                   keywords: dict[str, np.ndarray] | None = None,
                   numerics: dict[str, tuple[str, np.ndarray]] | None = None,
                   ids: list[str] | None = None,
                   sources: list[bytes] | None = None,
                   pad_multiple: int = 512) -> Segment:
    """Bulk columnar ingestion: build a Segment directly from numpy
    arrays, vectorized — the path for loading tens of millions of rows
    of analytics data in seconds instead of the doc-by-doc parse
    (which costs minutes at that scale).

    keywords: field -> array of values (any dtype; uniqued into the
    sorted term dictionary). numerics: field -> (mapping_kind, values)
    with values in the field's HOST unit (dates: epoch millis).
    Produces the exact structure SegmentBuilder.build would for the same
    single-valued data (verified by tests/test_columnar_build.py).

    Capacity pads to `pad_multiple` (not pow2): one big segment compiles
    once, and a 20M-row corpus must not pay pow2's up-to-2x padding in
    every per-query column scan.

    Ref analog: bulk indexing (action/bulk/TransportBulkAction) feeding
    DocumentsWriter — here the flush IS the load.
    """
    cap = max(-(-n // pad_multiple) * pad_multiple, BLOCK)
    kw_cols = {}
    for name, vals in (keywords or {}).items():
        if isinstance(vals, tuple):
            # pre-encoded (terms, ordinals): terms MUST already be in
            # sorted order — uniquing 20M strings is the slow part the
            # caller is skipping
            terms, inv = list(vals[0]), np.asarray(vals[1])
            if any(terms[i] >= terms[i + 1]
                   for i in range(len(terms) - 1)):
                raise ValueError(
                    f"pre-encoded terms for [{name}] must be strictly "
                    "sorted (ordinal order IS term sort order)")
            if inv.size and (inv.min() < 0 or inv.max() >= len(terms)):
                raise ValueError(
                    f"pre-encoded ordinals for [{name}] out of range")
        else:
            vals = np.asarray(vals)
            terms_arr, inv = np.unique(vals, return_inverse=True)
            terms = [str(t) for t in terms_arr]
        ords = np.full(cap, -1, dtype=np.int32)
        ords[:n] = inv.astype(np.int32)
        df = np.bincount(inv, minlength=len(terms)).astype(np.int32)
        kw_cols[name] = KeywordColumn(
            name=name, terms=terms,
            term_index={t: i for i, t in enumerate(terms)},
            ords=ords, df=df)
    num_cols = {}
    for name, (kind, vals) in (numerics or {}).items():
        is_int = kind in (LONG, INTEGER, SHORT, BYTE, DATE, BOOLEAN, IP)
        raw = np.zeros(cap, dtype=np.int64 if is_int else np.float64)
        raw[:n] = vals
        exists = np.zeros(cap, dtype=bool)
        exists[:n] = True
        bias = 1 << 31 if kind == IP else 0
        num_cols[name] = NumericColumn(
            name=name, kind=kind, values=_device_vals(raw, kind, bias,
                                                      is_int),
            exists=exists, raw=raw, bias=bias)
    return Segment(
        seg_id=seg_id, num_docs=n, capacity=cap,
        ids=ids if ids is not None else _RangeIds(n),
        id_map=({i: j for j, i in enumerate(ids)} if ids is not None
                else _RangeIdMap(n)),
        sources=sources if sources is not None else _ConstList(b"{}", n),
        versions=np.ones(n, dtype=np.int64),
        text={}, keywords=kw_cols, numerics=num_cols,
    )


def merge_segments(segments: Iterable[Segment], seg_id: str | None = None,
                   live_masks: dict[str, np.ndarray] | None = None,
                   similarity=None) -> "Segment":
    """Merge segments into one, dropping deleted docs.

    Ref analog: Lucene segment merging driven by TieredMergePolicy
    (index/merge/policy/TieredMergePolicyProvider.java). Columnar merge =
    re-parse-free rebuild from host CSR data.
    """
    from .mapping import ParsedField  # local import to avoid cycle at module load

    builder = SegmentBuilder(similarity=similarity)
    for seg in segments:
        live = None if live_masks is None else live_masks.get(seg.seg_id)
        # invert CSR once per text field: doc -> ordered token list, using
        # the positional sidecar so phrase/span queries survive merges
        doc_terms: dict[str, list[list[str]]] = {}
        for name, pf in seg.text.items():
            per_doc: list[list[str]] = [
                [None] * int(pf.doc_len[d]) for d in range(seg.num_docs)]
            for t_idx, term in enumerate(pf.terms):
                s, e = int(pf.indptr[t_idx]), int(pf.indptr[t_idx + 1])
                for j in range(s, e):
                    d = int(pf.doc_ids[j])
                    if pf.pos_data is not None:
                        ps, pe = int(pf.pos_indptr[j]), int(pf.pos_indptr[j + 1])
                        for p in pf.pos_data[ps:pe]:
                            per_doc[d][int(p)] = term
                    else:  # legacy segment without positions: order unknown
                        slots = per_doc[d]
                        tf = int(pf.tfs[j])
                        placed = 0
                        for i, v in enumerate(slots):
                            if v is None and placed < tf:
                                slots[i] = term
                                placed += 1
            doc_terms[name] = per_doc
        comp_by_row: dict[int, list[tuple[str, dict]]] = {}
        for name, cc in seg.completions.items():
            for row, entry in cc.entries:
                comp_by_row.setdefault(row, []).append((name, entry))

        def row_fields(d: int) -> list[ParsedField]:
            fields: list[ParsedField] = []
            for name in seg.text:
                toks = [t for t in doc_terms[name][d] if t is not None]
                if toks:
                    fields.append(ParsedField(name=name, type=TEXT, tokens=toks))
            for name, entry in comp_by_row.get(d, ()):
                fields.append(ParsedField(name=name, type="completion",
                                          value=entry))
            for name, kc in seg.keywords.items():
                if name in seg.text:
                    continue  # derived text-sort view; rebuilt lazily
                if kc.mv_ords is not None:
                    for o in kc.mv_ords[d]:
                        if o >= 0:
                            fields.append(ParsedField(
                                name=name, type=KEYWORD,
                                value=kc.terms[int(o)]))
                elif kc.ords[d] >= 0:
                    fields.append(ParsedField(name=name, type=KEYWORD,
                                              value=kc.terms[kc.ords[d]]))
            for name, nc in seg.numerics.items():
                if not nc.exists[d]:
                    continue
                if nc.mv_raw is not None:
                    vals = nc.mv_raw[d][nc.mv_exists[d]]
                else:
                    vals = [nc.raw[d]]
                for v in vals:
                    value = int(v) if nc.raw.dtype == np.int64 else float(v)
                    if nc.kind == BOOLEAN:
                        value = bool(v)
                    fields.append(ParsedField(name=name, type=nc.kind,
                                              value=value))
            for name, vc in seg.vectors.items():
                if vc.exists[d]:
                    fields.append(ParsedField(
                        name=name, type=DENSE_VECTOR,
                        value=[float(x) for x in vc.values[d]]))
            for name, gc in seg.geos.items():
                if gc.exists[d]:
                    fields.append(ParsedField(
                        name=name, type=GEO_POINT,
                        value=(float(gc.lat[d]), float(gc.lon[d]))))
            return fields

        # nested child rows re-attach to their parent (block order is
        # rebuilt by SegmentBuilder.add)
        children_of: dict[int, list[int]] = {}
        if seg.parent_of is not None:
            for d in range(seg.num_docs):
                p = int(seg.parent_of[d])
                if p >= 0:
                    children_of.setdefault(p, []).append(d)

        for d in range(seg.num_docs):
            if live is not None and not live[d]:
                continue
            if seg.parent_of is not None and seg.parent_of[d] >= 0:
                continue  # child rows ride with their parent
            doc = ParsedDocument(doc_id=seg.ids[d], source=seg.sources[d],
                                 fields=row_fields(d))
            for c in children_of.get(d, ()):
                cf = row_fields(c)
                path = next((f.value for f in cf
                             if f.name == "_nested_path"), "")
                cf = [f for f in cf if f.name != "_nested_path"]
                doc.nested.append((str(path), cf, seg.sources[c]))
            builder.add(doc, version=int(seg.versions[d]))
    return builder.build(seg_id)
