"""Text analysis: tokenizers, token filters, analyzers.

Reference analog: index/analysis/ (149 files — AnalysisService.java,
AnalysisModule.java, StandardAnalyzerProvider.java, ...). Analysis is a
pure host-side concern in the TPU build — it produces term streams at
index time and query time; only term ids ever reach the device.

Scope: the core analyzers the reference registers by default
(standard/simple/whitespace/keyword/stop/english + custom chains from
settings). The reference's ~30 language analyzers are a registry matter,
not an architecture one; they slot into TOKEN_FILTERS/ANALYZERS as added.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Callable, Iterable

from ..utils.settings import Settings
from ..utils.errors import IllegalArgumentError

# ---------------------------------------------------------------------------
# Tokenizers: text -> list of (term, position)
# ---------------------------------------------------------------------------

_WORD_RE = re.compile(r"[\w][\w'']*", re.UNICODE)
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)


def standard_tokenizer(text: str) -> list[str]:
    """Unicode word-boundary tokenizer (approximates Lucene StandardTokenizer,
    ref: index/analysis/StandardTokenizerFactory.java)."""
    return _WORD_RE.findall(text)


def whitespace_tokenizer(text: str) -> list[str]:
    return text.split()


def letter_tokenizer(text: str) -> list[str]:
    return _LETTER_RE.findall(text)


def keyword_tokenizer(text: str) -> list[str]:
    return [text] if text else []


def ngram_tokenizer(min_gram: int = 1, max_gram: int = 2) -> Callable[[str], list[str]]:
    def tokenize(text: str) -> list[str]:
        out = []
        n = len(text)
        for i in range(n):
            for g in range(min_gram, max_gram + 1):
                if i + g <= n:
                    out.append(text[i:i + g])
        return out
    return tokenize


def pattern_tokenizer(pattern: str = r"\W+") -> Callable[[str], list[str]]:
    rx = re.compile(pattern, re.UNICODE)
    return lambda text: [t for t in rx.split(text) if t]


TOKENIZERS: dict[str, Callable] = {
    "standard": standard_tokenizer,
    "whitespace": whitespace_tokenizer,
    "letter": letter_tokenizer,
    "keyword": keyword_tokenizer,
}

# ---------------------------------------------------------------------------
# Token filters: list[str] -> list[str]
# ---------------------------------------------------------------------------

# Lucene's default English stopword set (StopAnalyzer.ENGLISH_STOP_WORDS_SET)
ENGLISH_STOP_WORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split()
)


def lowercase_filter(tokens: list[str]) -> list[str]:
    return [t.lower() for t in tokens]


def uppercase_filter(tokens: list[str]) -> list[str]:
    return [t.upper() for t in tokens]


def stop_filter(stopwords: Iterable[str] = ENGLISH_STOP_WORDS) -> Callable:
    sw = frozenset(stopwords)
    return lambda tokens: [t for t in tokens if t not in sw]


def asciifolding_filter(tokens: list[str]) -> list[str]:
    """Strip diacritics (ref: ASCIIFoldingTokenFilterFactory.java)."""
    return [
        unicodedata.normalize("NFKD", t).encode("ascii", "ignore").decode("ascii") or t
        for t in tokens
    ]


def unique_filter(tokens: list[str]) -> list[str]:
    seen: set[str] = set()
    out = []
    for t in tokens:
        if t not in seen:
            seen.add(t)
            out.append(t)
    return out


def length_filter(min_len: int = 0, max_len: int = 1 << 30) -> Callable:
    return lambda tokens: [t for t in tokens if min_len <= len(t) <= max_len]


def edge_ngram_filter(min_gram: int = 1, max_gram: int = 8) -> Callable:
    def f(tokens: list[str]) -> list[str]:
        out = []
        for t in tokens:
            for g in range(min_gram, min(max_gram, len(t)) + 1):
                out.append(t[:g])
        return out
    return f


# --- Porter stemmer (classic algorithm; ref: PorterStemTokenFilterFactory) --

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Number of VC sequences."""
    m = 0
    prev_vowel = False
    for i in range(len(stem)):
        if _is_cons(stem, i):
            if prev_vowel:
                m += 1
            prev_vowel = False
        else:
            prev_vowel = True
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return (len(word) >= 2 and word[-1] == word[-2] and _is_cons(word, len(word) - 1))


def _cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    return (_is_cons(word, len(word) - 3) and not _is_cons(word, len(word) - 2)
            and _is_cons(word, len(word) - 1) and word[-1] not in "wxy")


_STEP2 = (("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
          ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"),
          ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
          ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
          ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"))
_STEP3 = (("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
          ("ical", "ic"), ("ful", ""), ("ness", ""))
_STEP4 = tuple(sorted(
    ("al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement", "ment",
     "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize"),
    key=len, reverse=True))


def porter_stem(word: str) -> str:
    if len(word) <= 2:
        return word
    w = word

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]

    # step 1b
    flag = False
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and _has_vowel(w[:-2]):
        w = w[:-2]
        flag = True
    elif w.endswith("ing") and _has_vowel(w[:-3]):
        w = w[:-3]
        flag = True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_cons(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif _measure(w) == 1 and _cvc(w):
            w += "e"

    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"

    for suf, rep in _STEP2:
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    for suf, rep in _STEP3:
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    if w.endswith("ion") and len(w) > 3 and w[-4] in "st" and _measure(w[:-3]) > 1:
        w = w[:-3]
    else:
        for suf in _STEP4:
            if w.endswith(suf):
                stem = w[: -len(suf)]
                if _measure(stem) > 1:
                    w = stem
                break

    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = _measure(stem)
        if m > 1 or (m == 1 and not _cvc(stem)):
            w = stem
    # step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w


def porter_stem_filter(tokens: list[str]) -> list[str]:
    return [porter_stem(t) for t in tokens]


TOKEN_FILTERS: dict[str, Callable] = {
    "lowercase": lowercase_filter,
    "uppercase": uppercase_filter,
    "stop": stop_filter(),
    "asciifolding": asciifolding_filter,
    "porter_stem": porter_stem_filter,
    "stemmer": porter_stem_filter,
    "unique": unique_filter,
}

# parameterized factories for custom components declared under
# analysis.tokenizer.<name>.* / analysis.filter.<name>.* settings
# (ref: AnalysisModule registering *TokenizerFactory / *TokenFilterFactory)
TOKENIZER_FACTORIES: dict[str, Callable] = {
    "ngram": lambda s: ngram_tokenizer(s.get_int("min_gram", 1),
                                       s.get_int("max_gram", 2)),
    "nGram": lambda s: ngram_tokenizer(s.get_int("min_gram", 1),
                                       s.get_int("max_gram", 2)),
    "pattern": lambda s: pattern_tokenizer(s.get_str("pattern", r"\W+")),
    "standard": lambda s: standard_tokenizer,
    "whitespace": lambda s: whitespace_tokenizer,
    "letter": lambda s: letter_tokenizer,
    "keyword": lambda s: keyword_tokenizer,
}
def _resolve_stopwords(spec) -> frozenset:
    """`stopwords` setting -> concrete set: a list of words (each
    possibly a `_lang_` named set), one `_lang_` name, `_none_`, or
    absent -> English (ref: Analysis.parseStopWords resolving
    namedStopWords)."""
    if spec is None or spec in ("", "_english_"):
        return ENGLISH_STOP_WORDS
    if spec == "_none_" or spec == []:
        return frozenset()   # stopwords: [] means explicitly none
    from .lang_analysis import STOPWORDS
    names = spec if isinstance(spec, (list, tuple)) else [spec]
    out: set[str] = set()
    for n in names:
        n = str(n)
        if n.startswith("_") and n.endswith("_"):
            lang = n.strip("_")
            if lang == "none":
                continue
            if lang == "english":
                out |= ENGLISH_STOP_WORDS
                continue
            if lang not in STOPWORDS:
                raise IllegalArgumentError(
                    f"unknown named stopword set [{n}]")
            out |= STOPWORDS[lang]
        else:
            out.add(n)
    return frozenset(out)


FILTER_FACTORIES: dict[str, Callable] = {
    "stop": lambda s: stop_filter(_resolve_stopwords(
        s.get_list("stopwords", None))),
    "length": lambda s: length_filter(s.get_int("min", 0),
                                      s.get_int("max", 1 << 30)),
    "edge_ngram": lambda s: edge_ngram_filter(s.get_int("min_gram", 1),
                                              s.get_int("max_gram", 8)),
    "edgeNGram": lambda s: edge_ngram_filter(s.get_int("min_gram", 1),
                                             s.get_int("max_gram", 8)),
}

# ---------------------------------------------------------------------------
# Analyzers
# ---------------------------------------------------------------------------


class Analyzer:
    """A tokenizer + ordered filter chain."""

    def __init__(self, name: str, tokenizer: Callable, filters: list[Callable]):
        self.name = name
        self.tokenizer = tokenizer
        self.filters = filters

    def analyze(self, text: str) -> list[str]:
        tokens = self.tokenizer(text)
        for f in self.filters:
            tokens = f(tokens)
        return tokens

    def __repr__(self) -> str:
        return f"Analyzer({self.name!r})"


class _NativeBackedAnalyzer(Analyzer):
    """Standard analyzer with the C++ fast path (native/tokenizer.py);
    falls back to the Python chain when the toolchain is missing. Output
    parity is covered by tests/test_native.py."""

    def __init__(self):
        super().__init__("standard", standard_tokenizer, [lowercase_filter])
        self._native = None
        self._native_tried = False

    def _get_native(self):
        if not self._native_tried:
            self._native_tried = True
            try:
                from ..native.tokenizer import NativeStandardAnalyzer
                self._native = NativeStandardAnalyzer()
            except Exception:
                self._native = None
        return self._native

    def analyze(self, text: str) -> list[str]:
        nat = self._get_native()
        if nat is not None:
            return nat.analyze(text)
        return super().analyze(text)

    def analyze_batch(self, texts: list[str]) -> list[list[str]]:
        nat = self._get_native()
        if nat is not None:
            return nat.analyze_batch(texts)
        return [super(_NativeBackedAnalyzer, self).analyze(t) for t in texts]


def _builtin_analyzers() -> dict[str, Analyzer]:
    return {
        "standard": _NativeBackedAnalyzer(),
        "simple": Analyzer("simple", letter_tokenizer, [lowercase_filter]),
        "whitespace": Analyzer("whitespace", whitespace_tokenizer, []),
        "keyword": Analyzer("keyword", keyword_tokenizer, []),
        "stop": Analyzer("stop", letter_tokenizer, [lowercase_filter, stop_filter()]),
        "english": Analyzer(
            "english", standard_tokenizer,
            [lowercase_filter, stop_filter(), porter_stem_filter]),
    }


# plugin-contributed whole analyzers, merged into every per-index
# service (ref: AnalysisModule.addAnalyzer — the extension point
# analysis plugins use; see plugins.py)
EXTRA_ANALYZERS: dict[str, "Analyzer"] = {}


def register_analyzer(name: str, analyzer) -> None:
    """Register a named analyzer globally. Accepts an Analyzer or a
    zero-arg factory returning one."""
    if callable(analyzer) and not isinstance(analyzer, Analyzer):
        analyzer = analyzer()
    if not isinstance(analyzer, Analyzer):
        raise IllegalArgumentError(
            f"plugin analyzer [{name}] must be an Analyzer")
    EXTRA_ANALYZERS[name] = analyzer


class AnalysisService:
    """Per-index registry of analyzers, built from index settings.

    Ref: index/analysis/AnalysisService.java — resolves named analyzers and
    custom chains declared under `analysis.analyzer.<name>.*` settings:

      analysis.analyzer.my_a.type: custom
      analysis.analyzer.my_a.tokenizer: standard
      analysis.analyzer.my_a.filter: ["lowercase", "stop"]
    """

    def __init__(self, settings: Settings = Settings.EMPTY):
        # index settings arrive in canonical "index."-prefixed form from
        # create-index (node.create_index normalization) and in bare
        # "analysis." form from direct construction — honor both
        stripped = settings.by_prefix("index.")
        if len(stripped):
            settings = settings.merged_with(stripped)
        self._analyzers = _builtin_analyzers()
        self._analyzers.update(EXTRA_ANALYZERS)  # plugin contributions
        # custom parameterized tokenizers/filters, then analyzers using them
        self._tokenizers = dict(TOKENIZERS)
        self._filters = dict(TOKEN_FILTERS)
        for name, group in settings.groups("analysis.tokenizer").items():
            typ = group.get_str("type") or ""
            factory = TOKENIZER_FACTORIES.get(typ)
            if factory is not None:
                self._tokenizers[name] = factory(group)
            elif typ in TOKENIZERS:  # parameterless builtin used as a type
                self._tokenizers[name] = TOKENIZERS[typ]
            else:
                raise IllegalArgumentError(f"unknown tokenizer type [{typ}] for [{name}]")
        for name, group in settings.groups("analysis.filter").items():
            typ = group.get_str("type") or ""
            factory = FILTER_FACTORIES.get(typ)
            if factory is not None:
                self._filters[name] = factory(group)
            elif typ in TOKEN_FILTERS:  # parameterless builtin used as a type
                self._filters[name] = TOKEN_FILTERS[typ]
            else:
                raise IllegalArgumentError(
                    f"unknown token filter type [{typ}] for [{name}]")
        for name, group in settings.groups("analysis.analyzer").items():
            self._analyzers[name] = self._build_custom(name, group)

    def _build_custom(self, name: str, s: Settings) -> Analyzer:
        typ = s.get_str("type", "custom")
        if typ != "custom":
            base = self._analyzers.get(typ)
            if base is None:
                raise IllegalArgumentError(f"unknown analyzer type [{typ}] for [{name}]")
            return Analyzer(name, base.tokenizer, list(base.filters))
        tok_name = s.get_str("tokenizer", "standard")
        tokenizer = self._tokenizers.get(tok_name)
        if tokenizer is None:
            raise IllegalArgumentError(f"unknown tokenizer [{tok_name}] for analyzer [{name}]")
        filters = []
        for f_name in s.get_list("filter", []) or []:
            f = self._filters.get(f_name)
            if f is None:
                raise IllegalArgumentError(f"unknown token filter [{f_name}] for analyzer [{name}]")
            filters.append(f)
        return Analyzer(name, tokenizer, filters)

    def analyzer(self, name: str) -> Analyzer:
        a = self._analyzers.get(name)
        if a is None:
            raise IllegalArgumentError(f"unknown analyzer [{name}]")
        return a

    @property
    def default_analyzer(self) -> Analyzer:
        return self._analyzers["standard"]

    def names(self) -> list[str]:
        return sorted(self._analyzers)


# language analyzers + stemmer/elision/normalization filters slot into
# the registries above (ref: the ~30 *AnalyzerProvider registrations in
# AnalysisModule)
from .lang_analysis import register_all as _register_languages  # noqa: E402
_register_languages()
