"""Process-wide durability counters: the audit surface of the crash /
corruption containment path (ISSUE 15).

Reference analog: the reference surfaces its durability events through
shard-level stats and the `corrupted_<uuid>` store markers
(index/store/Store.java corruption handling); here one small counter
block rides ``nodes_stats()["indices"]["durability"]`` so a chaos run
(tests/test_durability.py, the kill -9 soak) can assert exactly which
salvage/containment events fired — and a CLEAN recovery can assert
that none did.

Counters:

  * ``corruptions_detected``  — CorruptIndexError/TranslogCorrupted
    raised by a store/translog read (checksum mismatch, torn commit,
    mid-log crc break)
  * ``commits_fell_back``     — commit generations skipped by the
    newest→oldest salvage walk (torn/corrupt commit point)
  * ``translog_truncated_bytes`` — torn-tail bytes truncated on
    translog open (the tolerated, counted crash residue)
  * ``segments_salvaged``     — segments referenced only by a
    skipped commit, dropped with their docs re-entering via translog
    replay (the lossless half of salvage)
  * ``shards_failed_corrupt`` — shards CONTAINED: a corruption that
    salvage could not prove lossless failed the shard (marker written,
    node stays up)
  * ``peer_recoveries_after_corruption`` — corrupt local copies wiped
    and re-sourced from a surviving peer (cluster/distributed_node.py)

Ownership follows the fault-registry convention (search/dispatch.py
install_process_stats): each Node installs a FRESH stats object at init
and resets on close only while the installed object is still its own.
"""

from __future__ import annotations

import threading

_FIELDS = ("corruptions_detected", "commits_fell_back",
           "translog_truncated_bytes", "segments_salvaged",
           "shards_failed_corrupt", "peer_recoveries_after_corruption")


class DurabilityStats:
    """Thread-safe counter block for the durability path."""

    def __init__(self):
        self._mx = threading.Lock()
        self._counts = {f: 0 for f in _FIELDS}

    def inc(self, field: str, n: int = 1) -> None:
        with self._mx:
            self._counts[field] += n

    def get(self, field: str) -> int:
        with self._mx:
            return self._counts[field]

    def snapshot(self) -> dict:
        with self._mx:
            return dict(self._counts)


_process_stats_mx = threading.Lock()
stats = DurabilityStats()


def install_process_stats() -> DurabilityStats:
    """Node-init hook: install a FRESH counter object so a new node
    never inherits (or double-counts into) a previous node's numbers.
    Returns the installed object; the node passes it back to
    reset_process_stats on close."""
    global stats
    with _process_stats_mx:
        stats = DurabilityStats()
        return stats


def reset_process_stats(if_owner: DurabilityStats | None = None) -> None:
    """Node-close hook, fault-registry convention: reset only while
    the installed object is still the closing node's."""
    global stats
    with _process_stats_mx:
        if if_owner is None or if_owner is stats:
            stats = DurabilityStats()


# -- event helpers (the store/translog/engine call sites) ---------------

def on_corruption_detected(n: int = 1) -> None:
    stats.inc("corruptions_detected", n)


def on_commit_fell_back(n: int = 1) -> None:
    stats.inc("commits_fell_back", n)


def on_translog_truncated(nbytes: int) -> None:
    if nbytes > 0:
        stats.inc("translog_truncated_bytes", nbytes)


def on_segments_salvaged(n: int) -> None:
    if n > 0:
        stats.inc("segments_salvaged", n)


def on_shard_failed_corrupt() -> None:
    stats.inc("shards_failed_corrupt")


def on_peer_recovery_after_corruption() -> None:
    stats.inc("peer_recoveries_after_corruption")


def snapshot() -> dict:
    return stats.snapshot()
