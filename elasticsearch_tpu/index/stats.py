"""Per-index operation counters backing the `_stats` API.

Reference analog: action/admin/indices/stats/CommonStats.java — the
per-shard stats sections (docs, store, indexing, get, search, merges,
refresh, flush, ...) aggregated per index and across indices, with
per-type indexing counters (index/indexing/ShardIndexingService.java)
and per-group search counters (index/search/stats/ShardSearchService
`groupStats`).

TPU-first deviation: counters live at the index-service level, not per
shard — the engine's shards share one write path here, and the `_stats`
`level=shards` view derives per-shard rows from the segment state. All
counters are monotonically increasing ints guarded by the GIL (single
increments), matching the reference's CounterMetric semantics.
"""

from __future__ import annotations

import threading
import time


class _Counter:
    __slots__ = ("total", "time_ms")

    def __init__(self) -> None:
        self.total = 0
        self.time_ms = 0

    def inc(self, took_ms: float = 0.0) -> None:
        self.total += 1
        self.time_ms += int(took_ms)


class IndexOpStats:
    """Operation counters for one index."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # indexing (ref: ShardIndexingService.StatsHolder)
        self.index_total = 0
        self.index_time_ms = 0
        self.delete_total = 0
        self.delete_time_ms = 0
        self.noop_update_total = 0
        self.types: dict[str, _Counter] = {}       # per-type index counters
        # get (ref: index/get/ShardGetService stats)
        self.get_total = 0
        self.get_time_ms = 0
        self.get_exists = 0
        self.get_missing = 0
        # search (ref: index/search/stats/ShardSearchService)
        self.query_total = 0
        self.query_time_ms = 0
        self.fetch_total = 0
        self.fetch_time_ms = 0
        self.groups: dict[str, _Counter] = {}      # per-stats-group counters
        # pack build (refresh rebuilds + compaction folds): wall-time
        # and docs so operators and the ingest bench can see where
        # indexing time goes (today only merge counters existed);
        # build_device_total counts builds routed through the
        # device-parallel builder (index/devbuild.py)
        self.build_total = 0
        self.build_time_ms = 0
        self.build_docs = 0
        self.build_device_total = 0
        # maintenance
        self.refresh_total = 0
        self.refresh_time_ms = 0
        self.flush_total = 0
        self.flush_time_ms = 0
        self.merge_total = 0
        self.merge_time_ms = 0
        self.warmer_total = 0
        self.warmer_time_ms = 0
        # suggest / percolate
        self.suggest_total = 0
        self.suggest_time_ms = 0
        self.percolate_total = 0
        self.percolate_time_ms = 0

    # -- record sites ------------------------------------------------------
    def on_index(self, doc_type: str | None, took_ms: float = 0.0) -> None:
        with self._lock:
            self.index_total += 1
            self.index_time_ms += int(took_ms)
            t = self.types.setdefault(doc_type or "_doc", _Counter())
            t.inc(took_ms)

    def on_delete(self, took_ms: float = 0.0) -> None:
        with self._lock:
            self.delete_total += 1
            self.delete_time_ms += int(took_ms)

    def on_noop_update(self) -> None:
        with self._lock:
            self.noop_update_total += 1

    def on_get(self, found: bool, took_ms: float = 0.0) -> None:
        with self._lock:
            self.get_total += 1
            self.get_time_ms += int(took_ms)
            if found:
                self.get_exists += 1
            else:
                self.get_missing += 1

    def on_search(self, groups: list[str] | None = None,
                  took_ms: float = 0.0) -> None:
        with self._lock:
            self.query_total += 1
            self.query_time_ms += int(took_ms)
            for g in groups or ():
                self.groups.setdefault(str(g), _Counter()).inc(took_ms)

    def on_fetch(self, took_ms: float = 0.0) -> None:
        with self._lock:
            self.fetch_total += 1
            self.fetch_time_ms += int(took_ms)

    def on_build(self, took_ms: float = 0.0, docs: int = 0,
                 device: bool = False) -> None:
        with self._lock:
            self.build_total += 1
            self.build_time_ms += int(took_ms)
            self.build_docs += int(docs)
            if device:
                self.build_device_total += 1

    def on_refresh(self, took_ms: float = 0.0) -> None:
        with self._lock:
            self.refresh_total += 1
            self.refresh_time_ms += int(took_ms)

    def on_flush(self, took_ms: float = 0.0) -> None:
        with self._lock:
            self.flush_total += 1
            self.flush_time_ms += int(took_ms)

    def on_merge(self, took_ms: float = 0.0) -> None:
        with self._lock:
            self.merge_total += 1
            self.merge_time_ms += int(took_ms)

    def on_warmer(self, took_ms: float = 0.0) -> None:
        with self._lock:
            self.warmer_total += 1
            self.warmer_time_ms += int(took_ms)

    def on_suggest(self, took_ms: float = 0.0) -> None:
        with self._lock:
            self.suggest_total += 1
            self.suggest_time_ms += int(took_ms)

    def on_percolate(self, took_ms: float = 0.0) -> None:
        with self._lock:
            self.percolate_total += 1
            self.percolate_time_ms += int(took_ms)


class timed:
    """`with timed() as t: ...; stats.on_x(t.ms)` helper."""

    def __enter__(self) -> "timed":
        self._t0 = time.monotonic()
        self.ms = 0.0
        return self

    def __exit__(self, *exc) -> None:
        self.ms = (time.monotonic() - self._t0) * 1000.0


def merge_type_counters(parts: list[dict[str, _Counter]]) -> dict[str, dict]:
    """Sum per-key counters across indices -> plain dict rows."""
    out: dict[str, dict] = {}
    for part in parts:
        for k, c in part.items():
            row = out.setdefault(k, {"index_total": 0,
                                     "index_time_in_millis": 0,
                                     "index_current": 0})
            row["index_total"] += c.total
            row["index_time_in_millis"] += c.time_ms
    return out


def merge_group_counters(parts: list[dict[str, _Counter]]) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for part in parts:
        for k, c in part.items():
            row = out.setdefault(k, {
                "query_total": 0, "query_time_in_millis": 0,
                "query_current": 0,
                "fetch_total": 0, "fetch_time_in_millis": 0,
                "fetch_current": 0})
            row["query_total"] += c.total
            row["query_time_in_millis"] += c.time_ms
    return out
