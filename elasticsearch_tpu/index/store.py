"""Segment persistence: checksummed on-disk columnar format + commits.

Reference analog: index/store/Store.java (checksummed file metadata,
corruption detection via VerifyingIndexOutput) + the Lucene commit point
+ gateway/MetaDataStateFormat.java:48-52 (checksummed, atomically-renamed
state files).

Layout under <shard_path>/store/:
    seg_<id>.npz        numeric arrays (postings CSR, columns, versions)
    seg_<id>.meta.json  string data (terms, ids) + sha256 of the npz
    commit_<gen>.json   atomic commit point: list of live segments +
                        per-file checksums (torn/partial writes excluded
                        by write-to-temp + os.replace, like the reference)
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from ..utils.errors import ElasticsearchTpuError
from .segment import (Segment, SegmentBuilder, PostingsField,
                      KeywordColumn, NumericColumn, VectorColumn, GeoColumn,
                      CompletionColumn, extract_flat_impacts, _pack_layout)


class CorruptIndexError(ElasticsearchTpuError):
    status = 500


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class Store:
    """One shard's on-disk segment store."""

    def __init__(self, path: str):
        self.dir = os.path.join(path, "store")
        os.makedirs(self.dir, exist_ok=True)

    # -- segment IO --------------------------------------------------------
    def save_segment(self, seg: Segment, live: np.ndarray | None = None) -> None:
        arrays: dict[str, np.ndarray] = {
            "versions": seg.versions,
            "live": (live if live is not None else np.ones(seg.capacity, bool)),
        }
        meta: dict = {"seg_id": seg.seg_id, "num_docs": seg.num_docs,
                      "capacity": seg.capacity, "ids": seg.ids,
                      "text": {}, "keywords": {}, "numerics": {},
                      "vectors": []}
        if seg.delta_parent is not None:
            # streaming delta metadata: a flushed delta must reload AS
            # a delta, or the restarted engine would fold it into the
            # base generation hash (re-keying every delta(...) cache
            # entry) and lose the single-delta invariant
            meta["delta_parent"] = seg.delta_parent
            meta["delta_epoch"] = int(seg.delta_epoch)
        # sources as one concatenated blob + offsets
        blob = b"".join(seg.sources)
        offsets = np.zeros(len(seg.sources) + 1, dtype=np.int64)
        np.cumsum([len(s) for s in seg.sources], out=offsets[1:])
        arrays["src_blob"] = np.frombuffer(blob, dtype=np.uint8)
        arrays["src_offsets"] = offsets
        if seg.parent_of is not None:
            arrays["parent_of"] = seg.parent_of
        for name, pf in seg.text.items():
            key = f"text__{name}"
            arrays[f"{key}__df"] = pf.df
            arrays[f"{key}__indptr"] = pf.indptr
            arrays[f"{key}__doc_ids"] = pf.doc_ids
            arrays[f"{key}__tfs"] = pf.tfs
            arrays[f"{key}__doc_len"] = pf.doc_len
            # eager per-posting impacts, CSR order: a compacted base
            # carries impacts PRESERVED from its source segments'
            # field stats (segment.concat_segments), which a reload
            # recomputing from tfs under the merged field's own
            # doc_count/avg_len could not reproduce — persisting them
            # keeps scores bit-identical across flush + restart.
            # Builder/merge-built segments recompute exactly on load
            # (the pre-impacts fallback path), so they skip the column
            if seg.impacts_preserved:
                arrays[f"{key}__imps"] = extract_flat_impacts(pf)
            if pf.pos_data is not None:
                arrays[f"{key}__pos_data"] = pf.pos_data
                arrays[f"{key}__pos_indptr"] = pf.pos_indptr
            meta["text"][name] = {"terms": pf.terms, "doc_count": pf.doc_count,
                                  "avg_len": pf.avg_len}
        for name, kc in seg.keywords.items():
            if name in seg.text:
                continue  # derived text-sort view; rebuilt lazily on sort
            key = f"kw__{name}"
            arrays[f"{key}__ords"] = kc.ords
            arrays[f"{key}__df"] = kc.df
            if kc.mv_ords is not None:
                arrays[f"{key}__mv_ords"] = kc.mv_ords
            meta["keywords"][name] = {"terms": kc.terms}
        for name, nc in seg.numerics.items():
            key = f"num__{name}"
            arrays[f"{key}__raw"] = nc.raw
            arrays[f"{key}__exists"] = nc.exists
            if nc.mv_raw is not None:
                arrays[f"{key}__mv_raw"] = nc.mv_raw
                arrays[f"{key}__mv_exists"] = nc.mv_exists
            meta["numerics"][name] = {"kind": nc.kind, "bias": nc.bias}
        for name, vc in seg.vectors.items():
            key = f"vec__{name}"
            arrays[f"{key}__values"] = vc.values
            arrays[f"{key}__exists"] = vc.exists
            meta["vectors"].append(name)
        # IVF coarse indexes (index/ann.py): persisting the k-means
        # product makes the build a PACK artifact — a restart serves
        # the same clusters without re-clustering (and without the
        # pack-shape churn a reseeded k-means could introduce)
        meta["ann"] = {}
        for name, ai in seg.ann.items():
            key = f"ann__{name}"
            for aname, arr in ai.arrays().items():
                arrays[f"{key}__{aname}"] = arr
            meta["ann"][name] = {"similarity": ai.similarity}
        meta["geos"] = []
        for name, gc in seg.geos.items():
            key = f"geo__{name}"
            arrays[f"{key}__lat"] = gc.lat
            arrays[f"{key}__lon"] = gc.lon
            arrays[f"{key}__exists"] = gc.exists
            meta["geos"].append(name)
        # completion dictionaries are pure JSON (host-side suggest data)
        meta["completions"] = {name: cc.entries
                               for name, cc in seg.completions.items()}

        npz_path = os.path.join(self.dir, f"seg_{seg.seg_id}.npz")
        tmp = npz_path + ".tmp.npz"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, npz_path)
        meta["sha256"] = _sha256(npz_path)
        _atomic_write(os.path.join(self.dir, f"seg_{seg.seg_id}.meta.json"),
                      json.dumps(meta).encode())

    def load_segment(self, seg_id: str, verify: bool = True
                     ) -> tuple[Segment, np.ndarray]:
        meta_path = os.path.join(self.dir, f"seg_{seg_id}.meta.json")
        npz_path = os.path.join(self.dir, f"seg_{seg_id}.npz")
        with open(meta_path) as f:
            meta = json.load(f)
        if verify and _sha256(npz_path) != meta["sha256"]:
            raise CorruptIndexError(f"checksum mismatch for segment [{seg_id}]")
        z = np.load(npz_path)
        blob = z["src_blob"].tobytes()
        offsets = z["src_offsets"]
        sources = [blob[offsets[i]: offsets[i + 1]] for i in range(len(offsets) - 1)]
        cap = int(meta["capacity"])
        # presence of a persisted __imps column marks a segment whose
        # impacts can't be recomputed from its own stats (a compacted
        # base); the flag round-trips so a later re-save keeps them
        impacts_preserved = False
        text = {}
        for name, m in meta["text"].items():
            key = f"text__{name}"
            pf = PostingsField(
                name=name, terms=m["terms"],
                term_index={t: i for i, t in enumerate(m["terms"])},
                df=z[f"{key}__df"], indptr=z[f"{key}__indptr"],
                doc_ids=z[f"{key}__doc_ids"], tfs=z[f"{key}__tfs"],
                doc_len=z[f"{key}__doc_len"], doc_count=int(m["doc_count"]),
                avg_len=float(m["avg_len"]),
                pos_data=(z[f"{key}__pos_data"]
                          if f"{key}__pos_data" in z.files else None),
                pos_indptr=(z[f"{key}__pos_indptr"]
                            if f"{key}__pos_indptr" in z.files else None),
            )
            if f"{key}__imps" in z.files:
                _pack_layout(pf, cap, z[f"{key}__imps"])
                impacts_preserved = True
            else:
                # pre-impacts file format: recompute under the field's
                # own stats (exact for builder-built segments)
                SegmentBuilder._layout_blocks(pf, cap)
            text[name] = pf
        keywords = {}
        for name, m in meta["keywords"].items():
            key = f"kw__{name}"
            keywords[name] = KeywordColumn(
                name=name, terms=m["terms"],
                term_index={t: i for i, t in enumerate(m["terms"])},
                ords=z[f"{key}__ords"], df=z[f"{key}__df"],
                mv_ords=(z[f"{key}__mv_ords"]
                         if f"{key}__mv_ords" in z.files else None))
        numerics = {}
        for name, m in meta["numerics"].items():
            key = f"num__{name}"
            raw = z[f"{key}__raw"]
            exists = z[f"{key}__exists"]
            nc = NumericColumn(name=name, kind=m["kind"], values=None,  # type: ignore
                               exists=exists, raw=raw, bias=int(m.get("bias", 0)))
            nc.values = _device_column(nc)
            if f"{key}__mv_raw" in z.files:
                from .segment import _device_vals
                nc.mv_raw = z[f"{key}__mv_raw"]
                nc.mv_exists = z[f"{key}__mv_exists"]
                is_int = nc.mv_raw.dtype == np.int64
                nc.mv_values = _device_vals(nc.mv_raw, nc.kind, nc.bias,
                                            is_int)
            numerics[name] = nc
        vectors = {}
        for name in meta.get("vectors", []):
            key = f"vec__{name}"
            values = z[f"{key}__values"]
            vectors[name] = VectorColumn(
                name=name, values=values, exists=z[f"{key}__exists"],
                norms=np.linalg.norm(values, axis=1).astype(np.float32))
        ann = {}
        for name, m in meta.get("ann", {}).items():
            key = f"ann__{name}"
            if name not in vectors or f"{key}__centroids" not in z.files:
                continue
            from .ann import AnnIndex
            ann[name] = AnnIndex.from_arrays(
                m["similarity"],
                {a: z[f"{key}__{a}"]
                 for a in ("centroids", "radii", "members", "counts")})
        geos = {}
        for name in meta.get("geos", []):
            key = f"geo__{name}"
            geos[name] = GeoColumn(
                name=name, lat=z[f"{key}__lat"], lon=z[f"{key}__lon"],
                exists=z[f"{key}__exists"])
        seg = Segment(
            seg_id=meta["seg_id"], num_docs=int(meta["num_docs"]), capacity=cap,
            ids=meta["ids"], id_map={t: i for i, t in enumerate(meta["ids"])},
            sources=sources, versions=z["versions"],
            text=text, keywords=keywords, numerics=numerics, vectors=vectors,
            ann=ann, geos=geos,
            completions={
                name: CompletionColumn(
                    name=name, entries=[(int(r), e) for r, e in entries])
                for name, entries in meta.get("completions", {}).items()},
            parent_of=(z["parent_of"] if "parent_of" in z.files else None),
            delta_parent=meta.get("delta_parent"),
            delta_epoch=int(meta.get("delta_epoch", 0)),
            impacts_preserved=impacts_preserved,
        )
        if seg.delta_parent is not None:
            from .segment import pad_delta_shapes
            pad_delta_shapes(seg)   # restore the epoch-stable shapes
        return seg, z["live"]

    def delete_segment(self, seg_id: str) -> None:
        for suffix in (".npz", ".meta.json"):
            try:
                os.remove(os.path.join(self.dir, f"seg_{seg_id}{suffix}"))
            except OSError:
                pass

    # -- commit points -----------------------------------------------------
    def write_commit(self, generation: int, seg_ids: list[str],
                     extra: dict | None = None) -> None:
        commit = {"generation": generation, "segments": seg_ids,
                  **(extra or {})}
        _atomic_write(os.path.join(self.dir, f"commit_{generation}.json"),
                      json.dumps(commit).encode())
        # drop older commit files after the new one is durable
        for name in os.listdir(self.dir):
            if name.startswith("commit_") and name != f"commit_{generation}.json":
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass

    def read_last_commit(self) -> dict | None:
        commits = []
        for name in os.listdir(self.dir):
            if name.startswith("commit_") and name.endswith(".json"):
                try:
                    commits.append(int(name[len("commit_"):-len(".json")]))
                except ValueError:
                    pass
        if not commits:
            return None
        with open(os.path.join(self.dir, f"commit_{max(commits)}.json")) as f:
            return json.load(f)

    def cleanup_uncommitted(self, live_seg_ids: set[str]) -> None:
        for name in os.listdir(self.dir):
            if name.startswith("seg_") and name.endswith(".meta.json"):
                sid = name[len("seg_"):-len(".meta.json")]
                if sid not in live_seg_ids:
                    self.delete_segment(sid)


def _device_column(nc: NumericColumn) -> np.ndarray:
    """Recompute the device dtype view from exact raw values (single
    source of truth: segment._device_vals)."""
    from .segment import _device_vals
    return _device_vals(nc.raw, nc.kind, nc.bias,
                        nc.raw.dtype == np.int64)
