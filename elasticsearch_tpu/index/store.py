"""Segment persistence: checksummed on-disk columnar format + commits.

Reference analog: index/store/Store.java (checksummed file metadata,
corruption detection via VerifyingIndexOutput) + the Lucene commit point
+ gateway/MetaDataStateFormat.java:48-52 (checksummed, atomically-renamed
state files).

Layout under <shard_path>/store/:
    seg_<id>@<gen>.npz  numeric arrays (postings CSR, columns, versions)
    seg_<id>@<gen>.meta.json
                        string data (terms, ids) + sha256 of the npz.
                        Segment files are WRITE-ONCE (the Lucene rule):
                        each flush that must re-save a segment (its
                        live mask changed) writes a NEW @<commit-gen>
                        pair and the commit references exact stems — a
                        crash mid-save can never tear a pair a commit
                        relies on, because committed files are never
                        rewritten in place. Unsuffixed seg_<id>.* names
                        are the legacy (and direct-Store-API) form
    commit_<gen>.json   atomic commit point: list of live segments, a
                        payload self-checksum (a flipped bit is detected,
                        not parsed), and the translog generation that was
                        ACTIVE at commit time — the recovery coverage
                        witness (torn/partial writes excluded by
                        write-to-temp + os.replace, like the reference).
                        The PREVIOUS generation's file is retained until
                        the next commit so a torn newest commit has a
                        fallback (read_last_commit walks newest→oldest)
    corrupted_<uuid>    corruption marker (the ES Store convention): a
                        detected-corrupt shard writes one and FAILS —
                        recovery refuses to serve the copy until the
                        marker is cleared (peer re-source / manual)

Every write/read boundary is hooked into utils/faults.py
(`crash_point` / `disk_corrupt` / `io_error`), so the crash-recovery
matrix drives this file's failure handling deterministically.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from ..utils import faults
from ..utils.errors import ElasticsearchTpuError
from . import durability
from .segment import (Segment, SegmentBuilder, PostingsField,
                      KeywordColumn, NumericColumn, VectorColumn, GeoColumn,
                      CompletionColumn, extract_flat_impacts, _pack_layout)


class CorruptIndexError(ElasticsearchTpuError):
    status = 500


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _commit_checksum(commit: dict) -> str:
    """Self-checksum over the canonical commit payload (everything but
    the checksum field itself) — MetaDataStateFormat's checksummed
    state-file convention: a corrupted commit point is DETECTED, never
    half-parsed."""
    body = {k: v for k, v in commit.items() if k != "checksum"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True,
                   separators=(",", ":")).encode()).hexdigest()


class Store:
    """One shard's on-disk segment store. `index`/`shard` scope the
    fault-injection selectors (and marker reasons) to this shard."""

    CORRUPTED_PREFIX = "corrupted_"

    def __init__(self, path: str, index: str | None = None,
                 shard: int | None = None):
        self.dir = os.path.join(path, "store")
        self.index = index
        self.shard = shard
        os.makedirs(self.dir, exist_ok=True)

    def _write_hook(self, phase: str, partial=None) -> None:
        faults.on_storage_write("store", phase, index=self.index,
                                shard=self.shard, partial=partial)

    def _read_hook(self, phase: str, path: str) -> None:
        faults.on_storage_read("store", phase, path, index=self.index,
                               shard=self.shard)

    # -- segment IO --------------------------------------------------------
    def _stem_paths(self, stem: str) -> tuple[str, str]:
        return (os.path.join(self.dir, f"{stem}.npz"),
                os.path.join(self.dir, f"{stem}.meta.json"))

    def seg_stems_on_disk(self) -> set[str]:
        """Every segment-file stem present (seg_<id> / seg_<id>@<gen>),
        from either half of the pair — crash residue may have only one."""
        out = set()
        for name in os.listdir(self.dir):
            if not name.startswith("seg_"):
                continue
            if name.endswith(".meta.json"):
                out.add(name[: -len(".meta.json")])
            elif name.endswith(".npz") and not name.endswith(".tmp.npz"):
                out.add(name[: -len(".npz")])
        return out

    def save_segment(self, seg: Segment, live: np.ndarray | None = None,
                     suffix: int | None = None) -> str:
        arrays: dict[str, np.ndarray] = {
            "versions": seg.versions,
            "live": (live if live is not None else np.ones(seg.capacity, bool)),
        }
        meta: dict = {"seg_id": seg.seg_id, "num_docs": seg.num_docs,
                      "capacity": seg.capacity, "ids": seg.ids,
                      "text": {}, "keywords": {}, "numerics": {},
                      "vectors": []}
        if seg.delta_parent is not None:
            # streaming delta metadata: a flushed delta must reload AS
            # a delta, or the restarted engine would fold it into the
            # base generation hash (re-keying every delta(...) cache
            # entry) and lose the single-delta invariant
            meta["delta_parent"] = seg.delta_parent
            meta["delta_epoch"] = int(seg.delta_epoch)
        # sources as one concatenated blob + offsets
        blob = b"".join(seg.sources)
        offsets = np.zeros(len(seg.sources) + 1, dtype=np.int64)
        np.cumsum([len(s) for s in seg.sources], out=offsets[1:])
        arrays["src_blob"] = np.frombuffer(blob, dtype=np.uint8)
        arrays["src_offsets"] = offsets
        if seg.parent_of is not None:
            arrays["parent_of"] = seg.parent_of
        for name, pf in seg.text.items():
            key = f"text__{name}"
            arrays[f"{key}__df"] = pf.df
            arrays[f"{key}__indptr"] = pf.indptr
            arrays[f"{key}__doc_ids"] = pf.doc_ids
            arrays[f"{key}__tfs"] = pf.tfs
            arrays[f"{key}__doc_len"] = pf.doc_len
            # eager per-posting impacts, CSR order: a compacted base
            # carries impacts PRESERVED from its source segments'
            # field stats (segment.concat_segments), which a reload
            # recomputing from tfs under the merged field's own
            # doc_count/avg_len could not reproduce — persisting them
            # keeps scores bit-identical across flush + restart.
            # Builder/merge-built segments recompute exactly on load
            # (the pre-impacts fallback path), so they skip the column
            if seg.impacts_preserved:
                arrays[f"{key}__imps"] = extract_flat_impacts(pf)
            if pf.pos_data is not None:
                arrays[f"{key}__pos_data"] = pf.pos_data
                arrays[f"{key}__pos_indptr"] = pf.pos_indptr
            meta["text"][name] = {"terms": pf.terms, "doc_count": pf.doc_count,
                                  "avg_len": pf.avg_len}
        for name, kc in seg.keywords.items():
            if name in seg.text:
                continue  # derived text-sort view; rebuilt lazily on sort
            key = f"kw__{name}"
            arrays[f"{key}__ords"] = kc.ords
            arrays[f"{key}__df"] = kc.df
            if kc.mv_ords is not None:
                arrays[f"{key}__mv_ords"] = kc.mv_ords
            meta["keywords"][name] = {"terms": kc.terms}
        for name, nc in seg.numerics.items():
            key = f"num__{name}"
            arrays[f"{key}__raw"] = nc.raw
            arrays[f"{key}__exists"] = nc.exists
            if nc.mv_raw is not None:
                arrays[f"{key}__mv_raw"] = nc.mv_raw
                arrays[f"{key}__mv_exists"] = nc.mv_exists
            meta["numerics"][name] = {"kind": nc.kind, "bias": nc.bias}
        for name, vc in seg.vectors.items():
            key = f"vec__{name}"
            arrays[f"{key}__values"] = vc.values
            arrays[f"{key}__exists"] = vc.exists
            meta["vectors"].append(name)
        # IVF coarse indexes (index/ann.py): persisting the k-means
        # product makes the build a PACK artifact — a restart serves
        # the same clusters without re-clustering (and without the
        # pack-shape churn a reseeded k-means could introduce)
        meta["ann"] = {}
        for name, ai in seg.ann.items():
            key = f"ann__{name}"
            for aname, arr in ai.arrays().items():
                arrays[f"{key}__{aname}"] = arr
            meta["ann"][name] = {"similarity": ai.similarity}
        meta["geos"] = []
        for name, gc in seg.geos.items():
            key = f"geo__{name}"
            arrays[f"{key}__lat"] = gc.lat
            arrays[f"{key}__lon"] = gc.lon
            arrays[f"{key}__exists"] = gc.exists
            meta["geos"].append(name)
        # completion dictionaries are pure JSON (host-side suggest data)
        meta["completions"] = {name: cc.entries
                               for name, cc in seg.completions.items()}

        stem = (f"seg_{seg.seg_id}" if suffix is None
                else f"seg_{seg.seg_id}@{suffix}")
        npz_path, meta_path = self._stem_paths(stem)
        tmp = npz_path + ".tmp.npz"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        # crash BEFORE the replace: the tmp file is garbage, no real
        # file exists under this stem yet — exactly what a crash
        # mid-save leaves (committed stems are never rewritten)
        self._write_hook("seg_npz")
        os.replace(tmp, npz_path)
        # crash HERE: npz present, meta absent — a half-pair under a
        # stem NO commit references yet; recovery ignores it and the
        # next cleanup reclaims it
        self._write_hook("seg_meta")
        meta["sha256"] = _sha256(npz_path)
        _atomic_write(meta_path, json.dumps(meta).encode())
        return stem

    def load_segment(self, seg_id: str, verify: bool = True,
                     stem: str | None = None
                     ) -> tuple[Segment, np.ndarray]:
        """Load one segment, converting EVERY read failure — missing
        file, torn json, zip/zlib damage, checksum mismatch — into
        CorruptIndexError: the recovery path (engine._recover) makes
        containment decisions on exactly one exception type, and a
        flipped bit must never surface as a raw KeyError/BadZipFile
        stack out of node startup. `stem` names the exact write-once
        file pair a commit references (legacy unsuffixed by default)."""
        try:
            return self._load_segment_inner(seg_id, verify, stem)
        except CorruptIndexError:
            durability.on_corruption_detected()
            raise
        except OSError as e:
            import errno
            if e.errno == errno.EIO:
                raise   # an injected/real device error, not corruption
            durability.on_corruption_detected()
            raise CorruptIndexError(
                f"segment [{seg_id}] unreadable: {e}") from e
        except Exception as e:  # noqa: BLE001 — any decode damage
            durability.on_corruption_detected()
            raise CorruptIndexError(
                f"segment [{seg_id}] corrupt: {type(e).__name__}: {e}"
            ) from e

    def _load_segment_inner(self, seg_id: str, verify: bool = True,
                            stem: str | None = None
                            ) -> tuple[Segment, np.ndarray]:
        npz_path, meta_path = self._stem_paths(stem or f"seg_{seg_id}")
        self._read_hook("load_meta", meta_path)
        with open(meta_path) as f:
            meta = json.load(f)
        self._read_hook("load_npz", npz_path)
        if verify and _sha256(npz_path) != meta["sha256"]:
            raise CorruptIndexError(f"checksum mismatch for segment [{seg_id}]")
        z = np.load(npz_path)
        blob = z["src_blob"].tobytes()
        offsets = z["src_offsets"]
        sources = [blob[offsets[i]: offsets[i + 1]] for i in range(len(offsets) - 1)]
        cap = int(meta["capacity"])
        # presence of a persisted __imps column marks a segment whose
        # impacts can't be recomputed from its own stats (a compacted
        # base); the flag round-trips so a later re-save keeps them
        impacts_preserved = False
        text = {}
        for name, m in meta["text"].items():
            key = f"text__{name}"
            pf = PostingsField(
                name=name, terms=m["terms"],
                term_index={t: i for i, t in enumerate(m["terms"])},
                df=z[f"{key}__df"], indptr=z[f"{key}__indptr"],
                doc_ids=z[f"{key}__doc_ids"], tfs=z[f"{key}__tfs"],
                doc_len=z[f"{key}__doc_len"], doc_count=int(m["doc_count"]),
                avg_len=float(m["avg_len"]),
                pos_data=(z[f"{key}__pos_data"]
                          if f"{key}__pos_data" in z.files else None),
                pos_indptr=(z[f"{key}__pos_indptr"]
                            if f"{key}__pos_indptr" in z.files else None),
            )
            if f"{key}__imps" in z.files:
                _pack_layout(pf, cap, z[f"{key}__imps"])
                impacts_preserved = True
            else:
                # pre-impacts file format: recompute under the field's
                # own stats (exact for builder-built segments)
                SegmentBuilder._layout_blocks(pf, cap)
            text[name] = pf
        keywords = {}
        for name, m in meta["keywords"].items():
            key = f"kw__{name}"
            keywords[name] = KeywordColumn(
                name=name, terms=m["terms"],
                term_index={t: i for i, t in enumerate(m["terms"])},
                ords=z[f"{key}__ords"], df=z[f"{key}__df"],
                mv_ords=(z[f"{key}__mv_ords"]
                         if f"{key}__mv_ords" in z.files else None))
        numerics = {}
        for name, m in meta["numerics"].items():
            key = f"num__{name}"
            raw = z[f"{key}__raw"]
            exists = z[f"{key}__exists"]
            nc = NumericColumn(name=name, kind=m["kind"], values=None,  # type: ignore
                               exists=exists, raw=raw, bias=int(m.get("bias", 0)))
            nc.values = _device_column(nc)
            if f"{key}__mv_raw" in z.files:
                from .segment import _device_vals
                nc.mv_raw = z[f"{key}__mv_raw"]
                nc.mv_exists = z[f"{key}__mv_exists"]
                is_int = nc.mv_raw.dtype == np.int64
                nc.mv_values = _device_vals(nc.mv_raw, nc.kind, nc.bias,
                                            is_int)
            numerics[name] = nc
        vectors = {}
        for name in meta.get("vectors", []):
            key = f"vec__{name}"
            values = z[f"{key}__values"]
            vectors[name] = VectorColumn(
                name=name, values=values, exists=z[f"{key}__exists"],
                norms=np.linalg.norm(values, axis=1).astype(np.float32))
        ann = {}
        for name, m in meta.get("ann", {}).items():
            key = f"ann__{name}"
            if name not in vectors or f"{key}__centroids" not in z.files:
                continue
            from .ann import AnnIndex
            ann[name] = AnnIndex.from_arrays(
                m["similarity"],
                {a: z[f"{key}__{a}"]
                 for a in ("centroids", "radii", "members", "counts")})
        geos = {}
        for name in meta.get("geos", []):
            key = f"geo__{name}"
            geos[name] = GeoColumn(
                name=name, lat=z[f"{key}__lat"], lon=z[f"{key}__lon"],
                exists=z[f"{key}__exists"])
        seg = Segment(
            seg_id=meta["seg_id"], num_docs=int(meta["num_docs"]), capacity=cap,
            ids=meta["ids"], id_map={t: i for i, t in enumerate(meta["ids"])},
            sources=sources, versions=z["versions"],
            text=text, keywords=keywords, numerics=numerics, vectors=vectors,
            ann=ann, geos=geos,
            completions={
                name: CompletionColumn(
                    name=name, entries=[(int(r), e) for r, e in entries])
                for name, entries in meta.get("completions", {}).items()},
            parent_of=(z["parent_of"] if "parent_of" in z.files else None),
            delta_parent=meta.get("delta_parent"),
            delta_epoch=int(meta.get("delta_epoch", 0)),
            impacts_preserved=impacts_preserved,
        )
        if seg.delta_parent is not None:
            from .segment import pad_delta_shapes
            pad_delta_shapes(seg)   # restore the epoch-stable shapes
        return seg, z["live"]

    def delete_segment(self, seg_id: str) -> None:
        """Remove every file pair of this segment id — the legacy
        unsuffixed pair and all write-once @<gen> pairs."""
        stems = {s for s in self.seg_stems_on_disk()
                 if s == f"seg_{seg_id}"
                 or s.startswith(f"seg_{seg_id}@")}
        for stem in stems:
            for path in self._stem_paths(stem):
                try:
                    os.remove(path)
                except OSError:
                    pass

    # -- commit points -----------------------------------------------------
    def write_commit(self, generation: int, seg_ids: list[str],
                     extra: dict | None = None) -> None:
        commit = {"generation": generation, "segments": seg_ids,
                  **(extra or {})}
        commit["checksum"] = _commit_checksum(commit)
        # crash BEFORE the atomic replace: no new commit exists —
        # recovery serves the previous generation + translog replay
        # (flush orders commit STRICTLY before translog rotation, so
        # the replay always covers the gap)
        self._write_hook("commit")
        _atomic_write(os.path.join(self.dir, f"commit_{generation}.json"),
                      json.dumps(commit).encode())
        # drop older commit files after the new one is durable — but
        # RETAIN the immediately-previous generation: it is the salvage
        # walk's fallback when the newest commit point turns out torn
        # or bit-flipped on the next open
        gens = [g for g in self.commit_generations() if g != generation]
        self._write_hook("cleanup")
        for g in gens[1:]:   # gens is newest-first; keep gens[0]
            try:
                os.remove(os.path.join(self.dir, f"commit_{g}.json"))
            except OSError:
                pass

    def commit_generations(self) -> list[int]:
        """On-disk commit generations, NEWEST first — the salvage
        walk's candidate order."""
        commits = []
        for name in os.listdir(self.dir):
            if name.startswith("commit_") and name.endswith(".json"):
                try:
                    commits.append(int(name[len("commit_"):-len(".json")]))
                except ValueError:
                    pass
        return sorted(commits, reverse=True)

    def read_commit(self, generation: int) -> dict:
        """Read ONE commit point; torn/bit-flipped files raise
        CorruptIndexError (payload self-checksum; pre-checksum legacy
        files are accepted on parse alone)."""
        path = os.path.join(self.dir, f"commit_{generation}.json")
        self._read_hook("read_commit", path)
        try:
            with open(path) as f:
                commit = json.load(f)
        except OSError as e:
            import errno
            if e.errno == errno.EIO:
                raise
            durability.on_corruption_detected()
            raise CorruptIndexError(
                f"commit [{generation}] unreadable: {e}") from e
        except ValueError as e:   # torn/garbage json
            durability.on_corruption_detected()
            raise CorruptIndexError(
                f"commit [{generation}] torn: {e}") from e
        if "checksum" in commit \
                and commit["checksum"] != _commit_checksum(commit):
            durability.on_corruption_detected()
            raise CorruptIndexError(
                f"commit [{generation}] checksum mismatch")
        return commit

    def read_last_commit(self) -> dict | None:
        """Newest USABLE commit point: walks generations newest→oldest
        skipping torn/corrupt commit files (each skip counted under
        `commits_fell_back`). Whether a FALLBACK commit is actually
        safe to serve (translog coverage) is the engine's call —
        engine._recover re-walks with the coverage check; this
        convenience form is for callers that only need the newest
        parseable point (verify, tooling)."""
        for gen in self.commit_generations():
            try:
                return self.read_commit(gen)
            except CorruptIndexError:
                durability.on_commit_fell_back()
        return None

    def _commit_stems_raw(self, generation: int) -> set[str] | None:
        """Stems one on-disk commit references — RAW read (no fault
        hooks, no corruption counting: this is retention bookkeeping,
        not the serving path). None when the file is unreadable."""
        path = os.path.join(self.dir, f"commit_{generation}.json")
        try:
            with open(path) as f:
                commit = json.load(f)
        except Exception:  # noqa: BLE001 — unreadable = holds nothing
            return None
        files = commit.get("files") or {}
        return {files.get(sid, f"seg_{sid}")
                for sid in commit.get("segments", ())}

    def referenced_stems(self) -> set[str]:
        """Union of segment stems referenced by EVERY readable commit
        still on disk — the retention set: the previous commit
        generation is kept as the salvage walk's fallback, so its
        segment files must survive cleanup too (a fallback commit
        whose segments were reclaimed would be useless)."""
        out: set[str] = set()
        for gen in self.commit_generations():
            stems = self._commit_stems_raw(gen)
            if stems is not None:
                out |= stems
        return out

    def cleanup_uncommitted(self, live_stems: set[str]) -> None:
        """Reclaim every segment file pair that NO commit still on
        disk references (retired generations, crash residue) plus
        stale .tmp files. `live_stems` are the stems the just-written
        commit lists; stems the RETAINED previous commit references
        are kept as well — they are the fallback's data."""
        # crash HERE: the commit is durable but garbage segments (and
        # stale .tmp files) survive — recovery ignores them and the
        # next commit's cleanup reclaims them; nothing is lost
        self._write_hook("cleanup")
        keep = set(live_stems) | self.referenced_stems()
        for stem in self.seg_stems_on_disk() - keep:
            for path in self._stem_paths(stem):
                try:
                    os.remove(path)
                except OSError:
                    pass
        for name in os.listdir(self.dir):
            if name.endswith((".tmp", ".tmp.npz")):
                # crash residue from a torn save (write-to-temp)
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass

    # -- corruption markers (ref: Store.java markStoreCorrupted writing
    # corrupted_<uuid> files; a marked store refuses to open) --------------
    def corruption_markers(self) -> list[str]:
        return sorted(n for n in os.listdir(self.dir)
                      if n.startswith(self.CORRUPTED_PREFIX))

    def corruption_marker(self) -> str | None:
        """Reason recorded by the first marker, or None when clean."""
        for name in self.corruption_markers():
            try:
                with open(os.path.join(self.dir, name)) as f:
                    return json.load(f).get("reason", "corrupted")
            except Exception:  # noqa: BLE001 — a torn marker still marks
                return "corrupted (unreadable marker)"
        return None

    def write_corruption_marker(self, reason: str) -> str:
        """Persist the containment decision (idempotent: an existing
        marker stands — the FIRST detected corruption is the reason a
        later open reports)."""
        existing = self.corruption_markers()
        if existing:
            return existing[0]
        import uuid
        name = f"{self.CORRUPTED_PREFIX}{uuid.uuid4().hex}"
        _atomic_write(os.path.join(self.dir, name),
                      json.dumps({"reason": reason}).encode())
        return name

    def clear_corruption_markers(self) -> None:
        for name in self.corruption_markers():
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass

    # -- integrity audit (the index.shard.check_on_startup analog) ---------
    def verify_integrity(self) -> dict:
        """Full store audit WITHOUT loading segments into memory:
        corruption markers, newest-commit readability, and every
        committed segment's meta-parse + sha256. Pure reads — no fault
        hooks fire (an audit is not the production read path) and
        nothing is mutated. Returns {"clean", "segments_checked",
        "failures": [{"file", "reason"}]}."""
        failures: list[dict] = []
        marker = self.corruption_marker()
        if marker is not None:
            failures.append({"file": self.corruption_markers()[0],
                             "reason": f"corruption marker: {marker}"})
        gens = self.commit_generations()
        commit = None
        for gen in gens:
            path = os.path.join(self.dir, f"commit_{gen}.json")
            try:
                with open(path) as f:
                    c = json.load(f)
                if "checksum" in c and c["checksum"] != _commit_checksum(c):
                    raise ValueError("checksum mismatch")
                commit = c
                break
            except Exception as e:  # noqa: BLE001 — audit, not serve
                failures.append({"file": f"commit_{gen}.json",
                                 "reason": str(e)})
        if commit is None and gens:
            failures.append({"file": "commit",
                             "reason": "no readable commit point"})
        checked = 0
        files = (commit or {}).get("files") or {}
        for sid in (commit or {}).get("segments", ()):
            checked += 1
            stem = files.get(sid, f"seg_{sid}")
            npz_path, meta_path = self._stem_paths(stem)
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
                if _sha256(npz_path) != meta["sha256"]:
                    raise ValueError("sha256 mismatch")
            except Exception as e:  # noqa: BLE001 — audit, not serve
                failures.append({"file": stem, "reason": str(e)})
        return {"clean": not failures, "segments_checked": checked,
                "failures": failures}


def _device_column(nc: NumericColumn) -> np.ndarray:
    """Recompute the device dtype view from exact raw values (single
    source of truth: segment._device_vals)."""
    from .segment import _device_vals
    return _device_vals(nc.raw, nc.kind, nc.bias,
                        nc.raw.dtype == np.int64)
