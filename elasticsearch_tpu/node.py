"""Node: the composition root tying indices, search fan-out, and APIs.

Reference analog: node/Node.java (builds the module graph :166-200,
starts services :230-273) — but composition is plain Python. One Node
owns an IndicesService-equivalent registry and exposes the operations the
action layer (action/) implements in the reference: index/bulk/get/
delete/search/count/admin. The distributed fan-out across shards of one
process mirrors TransportSearchAction's QUERY_THEN_FETCH flow with the
SearchPhaseController merge (host path); multi-chip execution of the
same search is parallel/distributed.py.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .utils.settings import Settings, parse_time_value as _parse_time_value
from .utils.errors import (IndexNotFoundError, IndexAlreadyExistsError,
                           ElasticsearchTpuError, IllegalArgumentError,
                           SearchTimeoutError, ShardFailedError)
from .utils.metrics import MetricsRegistry
from .index.index_service import IndexService
from .search.controller import (merge_shard_results, shards_header,
                                shard_failure)
from .search.aggregations import parse_aggs
from .search.suggest import parse_suggest, merge_suggests
from .search.shard_searcher import ShardReader


def parse_time_value(v, default_ms: int = 60_000) -> int:
    """'5m' / '30s' -> millis; wraps the shared helper with the API error
    type (ref: common/unit/TimeValue)."""
    try:
        return _parse_time_value(v, default_ms)
    except ValueError as e:
        raise IllegalArgumentError(str(e))


class Node:
    def __init__(self, settings: Settings | dict | None = None):
        self.settings = (settings if isinstance(settings, Settings)
                         else Settings(settings or {}))
        # seed the process-wide HBM breakers with this node's limits
        # (first constructor wins; see utils/breaker.breaker_service)
        from .utils.breaker import breaker_service
        breaker_service(self.settings)
        self.name = self.settings.get_str("node.name", "node-0")
        self.cluster_name = self.settings.get_str("cluster.name",
                                                  "elasticsearch-tpu")
        self.data_path = self.settings.get_str("path.data")
        self._node_lock_fh = None
        if self.data_path:
            os.makedirs(self.data_path, exist_ok=True)
            # exclusive node lock: two nodes must never share a data
            # dir (ref: env/NodeEnvironment.java acquiring node.lock
            # per node path)
            import fcntl
            lock_path = os.path.join(self.data_path, "node.lock")
            fh = open(lock_path, "a+")
            try:
                fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                fh.close()
                raise IllegalArgumentError(
                    f"failed to obtain node lock on [{self.data_path}]: "
                    f"is another node using the same data path?")
            self._node_lock_fh = fh
            # fused-scoring autotuner choices persist under the data
            # path, keyed by pack fingerprint (so a refreshed pack
            # re-tunes instead of serving a stale choice). The store is
            # process-global: first node wins, and only the owner tears
            # it down on close
            from .search.executor import configure_autotune_persistence
            store = os.path.join(self.data_path, "fused_autotune.json")
            # atomic claim: only the node that actually configured the
            # process-global store owns (and later tears down) it
            self._autotune_store = store if configure_autotune_persistence(
                store, only_if_unset=True) else None
        self.indices: dict[str, IndexService] = {}
        self.metrics = MetricsRegistry()
        self._started_at = time.time()
        # scroll contexts: id -> {"readers", "body", "pos", "expires_at"}
        # (ref: SearchService.activeContexts :138 + keepalive reaper :168)
        self._scrolls: dict[str, dict] = {}
        from .snapshots import SnapshotsService
        self.snapshots = SnapshotsService(self)
        # alias -> {index names}; ref: cluster/metadata/AliasMetaData +
        # MetaDataIndexAliasesService
        self._aliases: dict[str, set[str]] = {}
        # (alias, index) -> {filter?, index_routing?, search_routing?}
        self._alias_meta: dict[tuple[str, str], dict] = {}
        # index templates; ref: cluster/metadata/MetaDataIndexTemplateService
        self._templates: dict[str, dict] = {}
        self._closed: set[str] = set()
        # named host-side pools (ref: threadpool/ThreadPool.java; the
        # device collapses the reference's search/bulk pool pressure)
        from .utils.threadpool import ThreadPoolService
        self.thread_pool = ThreadPoolService()
        # traffic control plane (search/traffic.py): per-tenant
        # token-bucket/concurrency admission BEFORE any breaker hold,
        # priority lanes for the scheduler's weighted drain, the
        # adaptive coalescing window, and the query-cache hit-rate
        # surface. Quotas come from `search.traffic.tenant.<id>.*`,
        # dynamically updatable via _cluster/settings.
        from .search.traffic import controller_from_settings
        self.traffic = controller_from_settings(self.settings)
        # search dispatch scheduler: cross-request coalescing + pipelined
        # fan-out (search/dispatch.py). ES_TPU_COALESCE_WINDOW_MS
        # overrides the setting at drain time; with neither set the
        # traffic controller's adaptive window drives coalescing.
        from .search.dispatch import DispatchScheduler
        from .search import dispatch as _dispatch_mod
        self._dispatch = DispatchScheduler(
            window_ms=float(self.settings.get_str(
                "search.dispatch.coalesce_window_ms", "0") or 0),
            traffic=self.traffic)
        # process-wide failover/eviction/membership counters: install
        # FRESH objects so this node never double-counts into (or
        # inherits) another in-process node's numbers; close() resets
        # them only while they are still this node's — the
        # fault-registry ownership convention
        self._process_stats = _dispatch_mod.install_process_stats()
        # durability counters (index/durability.py), same ownership
        # convention — installed BEFORE _load_existing_indices so
        # recovery-time salvage/containment events land in THIS node's
        # block
        from .index import durability as _durability_mod
        self._durability_stats = _durability_mod.install_process_stats()
        # elastic degraded mesh (parallel/repack.py): eviction
        # threshold + re-expansion probe cadence. Module-global
        # defaults like the resident cache; imported only when set so
        # mesh-less nodes never pay the import.
        ev_threshold = self.settings.get_int(
            "mesh.eviction.failure_threshold")
        ev_probe = self.settings.get_str("mesh.eviction.probe_interval")
        self._eviction_cfg = None
        if ev_threshold is not None or ev_probe is not None:
            from .parallel import repack as _repack
            _repack.configure(
                failure_threshold=ev_threshold,
                probe_interval_ms=(
                    float(parse_time_value(ev_probe, 5000))
                    if ev_probe is not None else None))
            self._eviction_cfg = _repack.config_snapshot()
        # resident query loop (search/resident.py, ES_TPU_RESIDENT_LOOP
        # opt-in): cap on pinned AOT executables. Process-global like
        # the executor itself; the last configured node wins.
        from .search import resident as _resident
        max_entries = self.settings.get_int("search.resident.max_entries")
        if max_entries is not None:
            _resident.configure(max_entries=max_entries)
        # tiered tile residency (index/tiering.py, ES_TPU_TIERED_PACK /
        # index.tiering.enabled opt-in): HBM as a cache over host-RAM
        # forward-index tiles. Process-global config like the resident
        # cache; close() resets only while this node configured it.
        self._tiering_cfg = None
        t_enabled = self.settings.get_bool("index.tiering.enabled", None)
        t_budget = self.settings.get_bytes("index.tiering.budget_bytes",
                                           None)
        t_chunk = self.settings.get_int("index.tiering.chunk_tiles")
        if t_enabled is not None or t_budget is not None \
                or t_chunk is not None:
            from .index import tiering as _tiering
            self._tiering_cfg = _tiering.configure(
                enabled=t_enabled, budget_bytes=t_budget,
                chunk_tiles=t_chunk)
        # IVF vector search (index/ann.py): exact-scan -> coarse-
        # quantized crossover + declared recall / nprobe. Process-
        # global config like tiering; close() resets only while this
        # node configured it.
        self._ann_cfg = None
        a_min = self.settings.get_int("index.ann.min_docs")
        a_nprobe = self.settings.get_int("index.ann.nprobe")
        a_recall = self.settings.get_float("index.ann.recall")
        if a_min is not None or a_nprobe is not None \
                or a_recall is not None:
            from .index import ann as _ann
            self._ann_cfg = _ann.configure(
                min_docs=a_min, nprobe=a_nprobe, recall=a_recall)
        # runtime hot-path hygiene guard (utils/trace_guard.py,
        # ES_TPU_TRACE_GUARD opt-in): disallow implicit device<->host
        # transfers + count compiles; bench runs then report
        # transfer_guard_trips/recompiles in nodes_stats()["dispatch"].
        # Process-wide and idempotent, like the breaker service.
        from .utils import trace_guard as _trace_guard
        if _trace_guard.env_requested():
            _trace_guard.arm()
        # runtime race sanitizer (utils/race_guard.py,
        # ES_TPU_RACE_GUARD opt-in): declared-shared structures assert
        # their lock is held on every mutation; trips surface as
        # nodes_stats()["dispatch"]["race_guard_trips"] while armed
        from .utils import race_guard as _race_guard
        if _race_guard.env_requested():
            _race_guard.arm()
        # deterministic fault injection (utils/faults.py): the setting
        # installs the process-wide registry; close() clears it again
        # ONLY while the installed registry is still this node's (test
        # nodes must not leak faults, but must not clobber a registry
        # someone configured after them either)
        self._fault_registry = None
        fault_spec = self.settings.get_str("search.fault_injection")
        if fault_spec is not None:
            from .utils import faults
            self._fault_registry = faults.configure(fault_spec)
        # plugins (ref: PluginsService loaded before any index exists so
        # analysis/query contributions are visible to every mapping)
        from .plugins import PluginsService
        self.plugins = PluginsService(self.settings)
        self.plugins.apply_analysis_hooks()
        self.plugins.apply_query_hooks()
        # resource watcher + file scripts (ref: ResourceWatcherService
        # watching config/scripts for ScriptService file reload)
        from .utils.watcher import ResourceWatcherService
        self.resource_watcher = ResourceWatcherService(self.settings)
        self._watch_file_scripts()
        # hunspell dictionaries under <path.conf|path.data>/hunspell/
        # <locale>/*.aff|*.dic (ref: indices/analysis/HunspellService)
        from .index.hunspell import HunspellService
        for base in (self.settings.get_str("path.conf"), self.data_path):
            if base:
                HunspellService.instance().add_root(
                    os.path.join(base, "hunspell"))
        if self.data_path:
            self._load_existing_indices()
            self._load_stored_scripts()
            if self._autotune_store is not None:
                # sweep persisted autotuner entries whose pack no
                # longer exists on disk (a long-lived node's refresh/
                # merge/compaction history otherwise accumulates dead
                # fingerprints in fused_autotune.json forever); runs
                # AFTER recovery so the live key set is complete
                from .search.executor import sweep_autotune_store
                # engine segments are the complete live set FOR THIS
                # NODE: the store is only ever written by the timed
                # single-chip tuner (resolve_fused_backend persists
                # solely on the run_backend path; the mesh passes
                # run_backend=None and can only LOOK UP entries, under
                # per-shard keys that equal these when content matches)
                # — so no mesh-only key can exist to be swept. Caveat:
                # the store is process-global (first node wins), so a
                # SECOND in-process node's choices persist into this
                # file under packs this sweep can't see; they are swept
                # at the owner's next startup and that node re-tunes
                # once per pack — accepted, matching the breaker
                # first-wins convention (one node per process in prod)
                live = set()
                for svc in self.indices.values():
                    for eng in svc.shards.values():
                        for seg in eng.segments:
                            live.add(seg.fingerprint())
                            live.add(seg.cache_key())
                sweep_autotune_store(live)
        # TTL sweep (ref: IndicesTTLService, indices.ttl.interval 60s)
        import threading as _threading
        self._ttl_stop = _threading.Event()
        ttl_interval = parse_time_value(
            self.settings.get_str("indices.ttl.interval", "60s"), 60_000)

        def _ttl_loop():
            while not self._ttl_stop.wait(ttl_interval / 1000.0):
                try:
                    self.purge_expired()
                except Exception:
                    pass  # the sweep must never kill the node

        self._ttl_thread = _threading.Thread(
            target=_ttl_loop, name="ttl-purger", daemon=True)
        self._ttl_thread.start()
        self.plugins.apply_node_hooks(self)

    def _watch_file_scripts(self) -> None:
        """File scripts: `<path.scripts>` (default <path.data>/scripts)
        loaded by name-minus-extension and hot-reloaded through the
        resource watcher (ref: ScriptService.java ScriptChangesListener
        on config/scripts)."""
        path = self.settings.get_str("path.scripts") or (
            os.path.join(self.data_path, "scripts")
            if self.data_path else None)
        if not path:
            return
        # register even when the dir does not exist yet: FileWatcher
        # tolerates a missing path, so a later-created dir starts
        # loading at the next poll instead of requiring a restart
        from .script import ScriptService
        from .utils.watcher import FileChangesListener, FileWatcher, HIGH

        svc = ScriptService.instance()

        # only extensions a script engine owns load (ref: ScriptService
        # registers per-engine extensions; editor backups etc. are
        # ignored rather than shadowing the real script)
        _EXTS = (".expression", ".painless", ".mustache", ".txt")

        class _Listener(FileChangesListener):
            def on_file_created(self, p):
                self._load(p)

            def on_file_changed(self, p):
                self._load(p)

            @staticmethod
            def on_file_deleted(p):
                # scripts key on the file STEM; another script extension
                # with the same stem may still provide the script —
                # reload from a survivor instead of dropping blindly
                if not p.endswith(_EXTS):
                    return
                name = os.path.splitext(os.path.basename(p))[0]
                d = os.path.dirname(p)
                try:
                    survivor = next(
                        (os.path.join(d, f) for f in sorted(os.listdir(d))
                         if os.path.splitext(f)[0] == name
                         and f.endswith(_EXTS)
                         and os.path.isfile(os.path.join(d, f))), None)
                except OSError:
                    survivor = None
                if survivor is not None:
                    _Listener._load(survivor)
                else:
                    svc.file_scripts.pop(name, None)

            @staticmethod
            def _load(p):
                if not p.endswith(_EXTS):
                    return
                name = os.path.splitext(os.path.basename(p))[0]
                try:
                    with open(p) as f:
                        svc.file_scripts[name] = f.read().strip()
                except OSError:
                    pass

        w = FileWatcher(path)
        w.add_listener(_Listener())
        self.resource_watcher.add(w, HIGH)
        self._script_watcher = w

    # -- stored scripts (ref: ScriptService indexed scripts in .scripts;
    # persisted here like gateway metadata) ----------------------------
    def _scripts_file(self) -> str:
        return os.path.join(self.data_path, "scripts.json")

    def _load_stored_scripts(self) -> None:
        from .script import ScriptService
        path = self._scripts_file()
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            svc = ScriptService.instance()
            if "sources" in data and isinstance(data["sources"], dict):
                svc.stored.update(data["sources"])
                svc.meta.update(data.get("meta", {}))
            else:  # pre-versioning flat format
                for sid, src in data.items():
                    svc.stored[sid] = src

    def put_stored_script(self, script_id: str, source: str) -> None:
        from .script import ScriptService
        ScriptService.instance().put_stored(script_id, source)
        self._persist_stored_scripts()

    def delete_stored_script(self, script_id: str) -> bool:
        from .script import ScriptService
        found = ScriptService.instance().delete_stored(script_id)
        self._persist_stored_scripts()
        return found

    def put_stored_script_versioned(self, script_id: str, source: str,
                                    lang: str, version: int | None = None,
                                    version_type: str = "internal"
                                    ) -> tuple[int, bool]:
        from .script import ScriptService
        v, created = ScriptService.instance().put_versioned(
            script_id, source, lang, version=version,
            version_type=version_type)
        self._persist_stored_scripts()
        return v, created

    def delete_stored_script_versioned(self, script_id: str,
                                       version: int | None = None,
                                       version_type: str = "internal"
                                       ) -> int | None:
        from .script import ScriptService
        v = ScriptService.instance().delete_versioned(
            script_id, version=version, version_type=version_type)
        self._persist_stored_scripts()
        return v

    def _persist_stored_scripts(self) -> None:
        if not self.data_path:
            return
        from .script import ScriptService
        svc = ScriptService.instance()
        tmp = self._scripts_file() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"sources": svc.stored, "meta": svc.meta}, f)
        os.replace(tmp, self._scripts_file())

    # -- index admin (ref: MetaDataCreateIndexService etc.) ----------------
    def create_index(self, name: str, settings: dict | None = None,
                     mappings: dict | None = None,
                     aliases: dict | None = None,
                     warmers: dict | None = None) -> dict:
        if name in self.indices:
            raise IndexAlreadyExistsError(name)
        if not name or name != name.lower() or name.startswith(("_", "-", "+")):
            raise IllegalArgumentError(f"invalid index name [{name}]")
        # apply matching index templates, lowest order first so higher
        # orders override (ref: MetaDataCreateIndexService template merge)
        import fnmatch
        matching = sorted(
            (t for t in self._templates.values()
             if any(fnmatch.fnmatch(name, p) for p in t["patterns"])),
            key=lambda t: t.get("order", 0))
        merged_settings: dict = {}
        merged_mappings: dict = {}
        def register_alias(alias: str, spec) -> None:
            self._aliases.setdefault(alias, set()).add(name)
            meta: dict = {}
            spec = spec if isinstance(spec, dict) else {}
            if spec.get("filter") is not None:
                meta["filter"] = spec["filter"]
            routing = spec.get("routing")
            ir = spec.get("index_routing", routing)
            sr = spec.get("search_routing", routing)
            if ir is not None:
                meta["index_routing"] = str(ir)
            if sr is not None:
                meta["search_routing"] = str(sr)
            self._alias_meta[(alias, name)] = meta

        for t in matching:
            merged_settings.update(t.get("settings") or {})
            _deep_merge(merged_mappings, t.get("mappings") or {})
            for alias, aspec in (t.get("aliases") or {}).items():
                register_alias(alias, aspec)
        merged_settings.update(settings or {})
        if merged_mappings:
            m2 = dict(mappings or {})
            _deep_merge(merged_mappings, m2)
            mappings = merged_mappings
        settings = merged_settings
        for alias, aspec in (aliases or {}).items():
            register_alias(alias, aspec)
        # bare index-level keys ("number_of_shards") normalize to the
        # canonical "index."-prefixed form (ref: IndexMetaData.Builder
        # settings handling) so IndexService sees them uniformly
        flat = Settings(settings or {}).as_dict()
        settings = {k if k.startswith("index.") else f"index.{k}": v
                    for k, v in flat.items()}
        idx_settings = self.settings.merged_with(settings)
        mapping = None
        type_mappings = None
        if mappings:
            # accept both {"properties": ...} and {"<type>": {"properties"...}}
            if "properties" in mappings or not mappings:
                mapping = mappings
            else:
                type_mappings = mappings
        svc = IndexService(name, idx_settings, mapping,
                           data_path=self.data_path,
                           type_mappings=type_mappings)
        svc.mapping_types = set(type_mappings or ())
        if warmers:
            # create-body warmers: {name: {source: <search body>, types}}
            # (ref: search/warmer/IndexWarmersMetaData.java fromXContent)
            svc.warmers = {
                wn: (w.get("source") or {"query": {"match_all": {}}})
                if isinstance(w, dict) else {"query": {"match_all": {}}}
                for wn, w in warmers.items()}
        self.indices[name] = svc
        if self.data_path:
            self._persist_index_meta(svc, settings or {})
        return {"acknowledged": True, "index": name}

    def delete_index(self, name: str) -> dict:
        svc = self._index(name)
        svc.close()
        del self.indices[name]
        self._closed.discard(name)
        if self.data_path:
            import shutil
            shutil.rmtree(os.path.join(self.data_path, name), ignore_errors=True)
        return {"acknowledged": True}

    def _index(self, name: str) -> IndexService:
        svc = self.indices.get(name)
        if svc is None and name in self._aliases:
            targets = self._aliases[name]
            if len(targets) == 1:
                return self.indices[next(iter(targets))]
            raise IllegalArgumentError(
                f"Alias [{name}] has more than one indices associated with "
                f"it, can't execute a single index op")
        if svc is None:
            raise IndexNotFoundError(name)
        return svc

    def _resolve(self, names: str | None,
                 expand_wildcards: str = "open",
                 ignore_unavailable: bool = False,
                 metadata_op: bool = False) -> list[IndexService]:
        """Index name resolution incl. _all, comma lists, wildcards, and
        aliases (ref: cluster/metadata/IndexNameExpressionResolver).
        `expand_wildcards` (open|closed|none|all, comma-combinable)
        controls which states wildcard/_all expressions expand to.
        `metadata_op` lets concretely-named CLOSED indices resolve —
        mapping/alias/settings updates are cluster-metadata operations
        that apply to closed indices in the reference."""
        states = {s.strip() for s in str(expand_wildcards).split(",")}
        if "all" in states:
            states |= {"open", "closed"}

        def state_ok(name: str) -> bool:
            closed = name in self._closed
            return ("closed" if closed else "open") in states

        if names in (None, "_all", "*", ""):
            return [s for n, s in self.indices.items() if state_ok(n)]
        out = []
        seen: set[str] = set()

        def add(svc: IndexService, concrete: bool = False):
            if concrete:
                if not metadata_op and svc.name in self._closed:
                    if ignore_unavailable:
                        return  # closed counts as unavailable
                    # a data operation naming a closed index directly is
                    # forbidden (ref: IndexClosedException, 403)
                    from .utils.errors import IndexClosedError
                    raise IndexClosedError(svc.name)
                ok = True
            else:
                ok = state_ok(svc.name)
            if svc.name not in seen and ok:
                seen.add(svc.name)
                out.append(svc)
        for n in str(names).split(","):
            n = n.strip()
            if n in self._aliases:
                for target in sorted(self._aliases[n]):
                    if target in self.indices:
                        add(self.indices[target])
            elif "*" in n:
                import fnmatch
                matched = False
                for k in sorted(self.indices):
                    if fnmatch.fnmatch(k, n):
                        add(self.indices[k])
                        matched = True
                for alias, targets in sorted(self._aliases.items()):
                    if fnmatch.fnmatch(alias, n):
                        for target in sorted(targets):
                            if target in self.indices:
                                add(self.indices[target])
                        matched = True
                _ = matched
            else:
                try:
                    add(self._index(n), concrete=True)
                except IndexNotFoundError:
                    if not ignore_unavailable:
                        raise
        return out

    def _ensure_index(self, name: str) -> IndexService:
        """Auto-create on first write (ref: TransportBulkAction auto-create).
        Aliases resolve before auto-creation (writes through a
        single-index alias land in its backing index)."""
        if name in self._aliases:
            return self._index(name)
        if name not in self.indices:
            if not self.settings.get_bool("action.auto_create_index", True):
                raise IndexNotFoundError(name)
            self.create_index(name)
        return self.indices[name]

    # -- document APIs -----------------------------------------------------
    def index_doc(self, index: str, doc_id: str | None, body,
                  version: int | None = None, routing: str | None = None,
                  refresh: bool = False, ttl: str | None = None,
                  doc_type: str | None = None,
                  version_type: str = "internal",
                  parent: str | None = None,
                  timestamp: str | None = None) -> dict:
        svc = self._ensure_index(index)
        if doc_id is None:
            import uuid
            doc_id = uuid.uuid4().hex[:20]
        self._check_routing_required(svc, doc_id, routing, parent)
        if ttl is None:
            # mapping-level default TTL (ref: TTLFieldMapper default)
            dflt = getattr(svc.mappers.mapper, "ttl_default_ms", None)
            if dflt:
                ttl = int(dflt)
        # index timestamp: explicit millis/date param or write time
        # (ref: index/mapper/internal/TimestampFieldMapper.java)
        if timestamp is not None:
            from .index.mapping import parse_date_millis
            try:
                ts = int(timestamp)
            except (TypeError, ValueError):
                ts = parse_date_millis(timestamp)
        else:
            ts = int(time.time() * 1000)
        if ttl is not None:
            # _ttl metadata (ref: index/mapper/internal/TTLFieldMapper +
            # indices/ttl/IndicesTTLService): expiry stored as a normal
            # date column, purged by the TTL sweep. Expiry anchors on
            # the doc timestamp; an already-passed expiry rejects the
            # write (ref: AlreadyExpiredException)
            body = dict(body if isinstance(body, dict)
                        else json.loads(body))
            expiry = int(ts + parse_time_value(ttl, 0))
            if expiry <= int(time.time() * 1000):
                raise IllegalArgumentError(
                    f"AlreadyExpiredException: already expired "
                    f"[{index}]/[{doc_id}]")
            body["_ttl_expiry"] = expiry
        _t0 = time.monotonic()
        r = svc.index_doc(doc_id, body, version, routing, doc_type=doc_type,
                          version_type=version_type, parent=parent,
                          timestamp_ms=ts)
        self._indexing_slowlog(svc, doc_id, body,
                               (time.monotonic() - _t0) * 1000.0)
        if refresh:
            # per-shard refresh: a doc-level refresh only publishes the
            # WRITTEN shard (ref: TransportIndexAction refresh flag is a
            # shard-level operation; delete/50_refresh.yaml encodes it).
            # Parent folds into routing exactly as the write path did.
            svc.shard_for(doc_id,
                          routing if routing is not None else parent
                          ).refresh()
        self.metrics.counter("indexing.index_total").inc()
        return r

    @staticmethod
    def _slowlog(logger_name: str, settings, threshold_prefix: str,
                 took_ms: float, fmt: str, *args) -> None:
        """Shared slowlog core: resolve the warn/info/debug/trace
        thresholds under `threshold_prefix` and emit at the first level
        the duration crosses (ref: both ShardSlowLogSearchService and
        ShardSlowLogIndexingService share this shape)."""
        import logging
        logger = logging.getLogger(logger_name)
        for level, log_fn in (("warn", logger.warning),
                              ("info", logger.info),
                              ("debug", logger.debug),
                              ("trace", logger.debug)):
            thr = settings.get_str(f"{threshold_prefix}.{level}")
            if thr is None:
                continue
            try:
                thr_ms = parse_time_value(thr, default_ms=1 << 60)
            except ElasticsearchTpuError:
                continue  # a bad threshold must never fail the op
            if took_ms >= thr_ms:
                log_fn(fmt, *args)
                return

    @classmethod
    def _indexing_slowlog(cls, svc, doc_id: str, body,
                          took_ms: float) -> None:
        """Per-index indexing slowlog (ref: index/indexing/slowlog/
        ShardSlowLogIndexingService.java; source truncated per
        index.indexing.slowlog.source). Serializing the source is paid
        only when a threshold is configured at all — the common
        (unconfigured) write path must not tax every document."""
        prefix = "index.indexing.slowlog.threshold.index"
        if not any(svc.settings.get_str(f"{prefix}.{lvl}") is not None
                   for lvl in ("warn", "info", "debug", "trace")):
            return
        limit = svc.settings.get_int("index.indexing.slowlog.source", 1000)
        src = json.dumps(body, default=str)[:limit] \
            if not isinstance(body, (bytes, str)) else str(body)[:limit]
        cls._slowlog("index.indexing.slowlog.index", svc.settings,
                     prefix, took_ms,
                     "[%s] took[%dms], id[%s], source[%s]", svc.name,
                     int(took_ms), doc_id, src)

    @staticmethod
    def _check_routing_required(svc, doc_id: str, routing, parent) -> None:
        """Parent-mapped (or routing-required) types reject doc ops
        without routing/parent (ref: RoutingMissingException usage in
        TransportIndexAction/TransportGetAction)."""
        if routing is None and parent is None and (
                svc.mappers.parent_type is not None
                or svc.mappers.routing_required):
            from .utils.errors import RoutingMissingError
            raise RoutingMissingError(svc.name, doc_id)

    def get_doc(self, index: str, doc_id: str, routing: str | None = None,
                doc_type: str | None = None, realtime: bool = True,
                parent: str | None = None) -> dict:
        svc = self._index(index)
        self._check_routing_required(svc, doc_id, routing, parent)
        r = svc.get_doc(doc_id,
                        routing if routing is not None else parent,
                        doc_type=doc_type, realtime=realtime)
        src = r.get("_source")
        # _ttl_expiry is metadata, never surfaced; the substring probe
        # gates the parse so untouched docs skip json entirely, then the
        # top-level key alone is stripped, type preserved
        if isinstance(src, (bytes, str)) and b'"_ttl_expiry"' in (
                src if isinstance(src, bytes) else src.encode()):
            obj = json.loads(src)
            if isinstance(obj, dict) and "_ttl_expiry" in obj:
                obj.pop("_ttl_expiry", None)
                clean = json.dumps(obj, separators=(",", ":"))
                r["_source"] = clean if isinstance(src, str) else clean.encode()
        elif isinstance(src, dict) and "_ttl_expiry" in src:
            r["_source"] = {k: v for k, v in src.items()
                            if k != "_ttl_expiry"}
        return r

    def delete_doc(self, index: str, doc_id: str, version: int | None = None,
                   routing: str | None = None, refresh: bool = False,
                   doc_type: str | None = None,
                   version_type: str = "internal",
                   parent: str | None = None) -> dict:
        svc = self._index(index)
        self._check_routing_required(svc, doc_id, routing, parent)
        r = svc.delete_doc(doc_id, version,
                           routing if routing is not None else parent,
                           doc_type=doc_type, version_type=version_type)
        if refresh:
            svc.shard_for(doc_id,
                          routing if routing is not None else parent
                          ).refresh()
        return r

    def update_doc(self, index: str, doc_id: str, body: dict,
                   refresh: bool = False,
                   doc_type: str | None = None,
                   routing: str | None = None,
                   parent: str | None = None,
                   version: int | None = None,
                   fields: list[str] | None = None,
                   ttl: str | None = None,
                   timestamp: str | None = None) -> dict:
        """Partial update: doc merge, script update (ctx._source
        mutation), upsert. Ref: action/update/TransportUpdateAction.java
        + UpdateHelper.java — get, apply doc/script, re-index with the
        read version (optimistic concurrency)."""
        # update auto-creates a missing index when the request can upsert
        # (ref: TransportUpdateAction.doExecute auto-create round trip)
        if index not in self.indices and index not in self._aliases and (
                body.get("upsert") is not None
                or body.get("doc_as_upsert")
                or body.get("scripted_upsert")):
            svc = self._ensure_index(index)
        else:
            svc = self._index(index)
        self._check_routing_required(svc, doc_id, routing, parent)
        routing = routing if routing is not None else parent
        script_spec = body.get("script")
        if isinstance(script_spec, str) and (
                body.get("params") is not None
                or body.get("lang") is not None):
            # 1.x UpdateRequest shape: script/params/lang are request
            # TOP-LEVEL keys (ref: UpdateRequest.source parsing)
            script_spec = {"inline": script_spec,
                           "params": body.get("params") or {},
                           "lang": body.get("lang", "groovy")}
        if script_spec is not None and body.get("doc") is not None:
            # ref: UpdateRequest.validate — "can't provide both script and doc"
            raise IllegalArgumentError(
                "can't provide both script and doc")

        def _with_get(r: dict, new_src: dict) -> dict:
            # ?fields= echoes the post-update doc in a `get` section
            # (ref: UpdateHelper.extractGetResult)
            if fields:
                g: dict = {"found": True}
                if "_source" in fields:
                    g["_source"] = new_src
                flds = {}
                for f in fields:
                    if f == "_parent":
                        if doc_id in svc.doc_parent:
                            flds[f] = svc.doc_parent[doc_id]
                    elif f == "_routing":
                        if doc_id in svc.doc_routing:
                            flds[f] = svc.doc_routing[doc_id]
                    elif f == "_timestamp":
                        if doc_id in svc.doc_ts:
                            flds[f] = svc.doc_ts[doc_id]
                    elif f == "_ttl":
                        exp = new_src.get("_ttl_expiry")
                        if exp:
                            flds[f] = int(exp - time.time() * 1000)
                    elif f != "_source" and f in new_src:
                        v = new_src[f]
                        flds[f] = v if isinstance(v, list) else [v]
                if flds:
                    g["fields"] = flds
                r["get"] = g
            return r

        try:
            current = svc.get_doc(doc_id, routing, doc_type=doc_type)
        except ElasticsearchTpuError:
            if version is not None:
                # versioned update on a missing doc is always a conflict
                # (ref: UpdateRequest version + missing doc)
                from .utils.errors import VersionConflictError
                raise VersionConflictError(index, doc_id, -1, version)
            upsert = body.get("upsert")
            if upsert is None and script_spec is not None and \
                    body.get("scripted_upsert"):
                upsert = {}
            elif upsert is None and body.get("doc_as_upsert"):
                upsert = body.get("doc")
            if upsert is None:
                raise
            if script_spec is not None and body.get("scripted_upsert"):
                upsert = self._run_update_script(script_spec, dict(upsert),
                                                 is_upsert=True)
                if upsert is None:  # ctx.op == none/delete on upsert
                    return {"_index": index, "_id": doc_id,
                            "result": "noop"}
            r = self.index_doc(index, doc_id, upsert, routing=routing,
                               doc_type=doc_type, refresh=refresh,
                               ttl=ttl, timestamp=timestamp,
                               parent=parent)
            return _with_get(r, dict(upsert))
        if version is not None and current["_version"] != version:
            from .utils.errors import VersionConflictError
            raise VersionConflictError(index, doc_id,
                                       current["_version"], version)
        src = json.loads(current["_source"])
        if script_spec is not None:
            new_src = self._run_update_script(script_spec, src)
            if new_src is None:  # ctx.op = "none"
                return {"_index": index, "_id": doc_id,
                        "_version": current["_version"], "result": "noop"}
            if new_src == "__delete__":
                r = svc.delete_doc(doc_id, current["_version"], routing)
                if refresh:
                    svc.shard_for(doc_id, routing).refresh()
                return r
            src = new_src
        else:
            doc_part = body.get("doc")
            if doc_part is None:
                raise IllegalArgumentError(
                    "update requires [doc] or [script]")
            # ref: UpdateRequest.detectNoop — defaults FALSE in 2.0
            # (opt-in; flipped to true only in later ES)
            if body.get("detect_noop", False):
                merged = json.loads(json.dumps(src))
                _deep_merge(merged, doc_part)
                if merged == src:
                    svc.op_stats.on_noop_update()
                    return {"_index": index, "_id": doc_id,
                            "_version": current["_version"],
                            "result": "noop"}
                src = merged
            else:
                _deep_merge(src, doc_part)
        r = self.index_doc(index, doc_id, src,
                           version=current["_version"],
                           routing=routing, doc_type=doc_type,
                           ttl=ttl, timestamp=timestamp, parent=parent,
                           refresh=refresh)
        return _with_get(r, src)

    @staticmethod
    def _run_update_script(script_spec, src: dict, is_upsert: bool = False):
        """Run an update script against ctx._source; returns the new
        source, "__delete__", or None for a noop. Ref: UpdateHelper
        ctx.op handling (index/delete/none)."""
        from .script import parse_script_spec, compile_script
        source, params = parse_script_spec(script_spec)
        cs = compile_script(source)
        ctx = {"_source": src, "op": "index",
               "_now": int(time.time() * 1000)}
        cs.run(params=params, bindings={"ctx": ctx})
        op = ctx.get("op", "index")
        if op in ("none", "noop"):
            return None
        if op == "delete":
            return None if is_upsert else "__delete__"
        return ctx["_source"]

    def bulk(self, operations: list[tuple[str, dict]], refresh: bool = False) -> dict:
        """operations: [(action, payload)] where action in index/create/
        delete/update; payload carries _index/_id/doc. Ref:
        TransportBulkAction.executeBulk grouping by shard."""
        started = time.monotonic()
        items = []
        errors = False
        touched: set[str] = set()
        for action, payload in operations:
            try:
                idx = payload["_index"]
                typ = payload.get("_type")
                if action in ("index", "create"):
                    r = self.index_doc(idx, payload.get("_id"), payload["doc"],
                                       routing=payload.get("_routing"),
                                       doc_type=typ)
                    touched.add(idx)
                    items.append({action: {**r, "status": 201 if r.get("created")
                                           else 200}})
                elif action == "delete":
                    r = self.delete_doc(idx, payload["_id"], doc_type=typ,
                                        routing=payload.get("_routing"))
                    touched.add(idx)
                    items.append({"delete": {**r, "status": 200 if r.get("found")
                                             else 404}})
                elif action == "update":
                    r = self.update_doc(idx, payload["_id"], payload["doc"],
                                        doc_type=typ,
                                        routing=payload.get("_routing"))
                    touched.add(idx)
                    items.append({"update": {**r, "status": 200}})
                else:
                    raise IllegalArgumentError(f"unknown bulk action [{action}]")
            except ElasticsearchTpuError as e:
                errors = True
                items.append({action: {"error": e.to_dict(), "status": e.status}})
        if refresh:
            for idx in touched:
                self.indices[idx].refresh()
        return {"took": int((time.monotonic() - started) * 1000),
                "errors": errors, "items": items}

    # -- search (ref: TransportSearchAction QUERY_THEN_FETCH) --------------
    def search(self, index: str | None, body: dict | None = None,
               scroll: str | None = None,
               search_type: str | None = None,
               tenant: str | None = None) -> dict:
        """Admission control FIRST (search/traffic.py): the tenant's
        token bucket / concurrency quota sheds over-quota load with a
        structured 429 (TrafficRejectedError carries retry_after)
        BEFORE the request takes a thread-pool slot or any breaker
        hold — a shed request costs the node nothing but the
        bookkeeping. Then executes on the bounded `search` pool:
        saturation with a full queue answers 429
        EsRejectedExecutionError instead of growing unbounded host
        threads (ref: ThreadPool.java:112-127 SEARCH pool +
        EsRejectedExecutionException). Pool threads re-entering search
        (template/inner flows) run inline to stay deadlock-free and
        are NOT re-admitted — the outer request already paid."""
        if threading.current_thread().name.startswith("pool-search"):
            return self._search_inner(index, body, scroll, search_type)
        ticket = self.traffic.admit(tenant, "search")
        try:
            pool = self.thread_pool.executor("search")
            return pool.submit(self._search_inner, index, body, scroll,
                               search_type, ticket.lane).result()
        finally:
            ticket.release()

    def _search_inner(self, index: str | None, body: dict | None = None,
                      scroll: str | None = None,
                      search_type: str | None = None,
                      lane: str = "interactive") -> dict:
        batch = self._dispatch.batch(lane=lane)
        st = self._search_submit(index, body, scroll, search_type, batch)
        batch.dispatch()
        return self._search_finish(st)

    def _search_submit(self, index: str | None, body: dict | None,
                       scroll: str | None, search_type: str | None,
                       batch) -> dict:
        """Resolve + bind + enqueue the fan-out of one search onto a
        dispatch batch (search/dispatch.py) WITHOUT collecting — so
        msearch / concurrent callers can coalesce identical plans and
        pipeline the rest before any device round trip completes."""
        body = body or {}
        services = self._resolve(index)
        shard_readers: list[tuple[str, ShardReader]] = []
        # shard-level containment (ISSUE 15): a FAILED (corrupt-
        # contained) shard becomes a structured `_shards.failures`
        # entry and the search reduces over the survivors — the node
        # stays up, the response says exactly which shard is dark
        prefailed: list[tuple[str, int, Exception]] = []
        for svc in services:
            for sid, eng in svc.shards.items():
                try:
                    shard_readers.append((svc.name,
                                          eng.acquire_searcher()))
                except ShardFailedError as e:
                    prefailed.append((svc.name, sid, e))
        if search_type in ("dfs_query_then_fetch", "dfs_query_and_fetch"):
            # DFS pre-phase: aggregate term statistics across shards so
            # every shard scores with GLOBAL idf (ref: search/dfs/
            # DfsPhase.java + SearchPhaseController.aggregateDfs :88)
            stats = self._aggregate_dfs(shard_readers, services, body)
            if stats:
                body = dict(body)
                body["_dfs_stats"] = stats
        scan_mode = search_type == "scan"
        if scan_mode:
            # scan: cursor-order export, no scoring (ref: search/scan/
            # ScanContext.java:47 + QueryPhase.java:115) — wrap as a
            # constant-score filter; the first response carries only the
            # cursor + total
            body = dict(body)
            body["query"] = {"constant_score": {
                "filter": body.get("query") or {"match_all": {}}}}
        started = time.monotonic()
        # per-request search deadline (ref: the body/URL `timeout` param
        # enforced per shard in QueryPhase): body timeout wins, else the
        # node-level search.default_search_timeout setting; -1 disables
        timeout = body.get("timeout")
        if timeout is None:
            timeout = self.settings.get_str("search.default_search_timeout")
        deadline = None
        if timeout not in (None, "", -1, "-1"):
            deadline = started + parse_time_value(timeout, 0) / 1000.0
        exec_st = self._submit_on_readers(shard_readers, body, batch,
                                          deadline=deadline)
        if prefailed:
            exec_st["prefailed"] = prefailed
        return {"services": services, "shard_readers": shard_readers,
                "body": body, "scan_mode": scan_mode, "scroll": scroll,
                "started": started, "exec": exec_st}

    def _search_finish(self, st: dict) -> dict:
        services = st["services"]
        shard_readers = st["shard_readers"]
        body = st["body"]
        scan_mode = st["scan_mode"]
        scroll = st["scroll"]
        result = self._finish_on_readers(st["exec"])
        took_ms = (time.monotonic() - st["started"]) * 1000.0
        self._search_slowlog(services, body, took_ms)
        # query counter + per-group search stats (ref: body `stats`
        # groups → ShardSearchStats.groupStats); fetch rides the same
        # program here (query_then_fetch fused), suggest when requested
        for svc in services:
            svc.op_stats.on_search(body.get("stats"), took_ms)
            svc.op_stats.on_fetch(0.0)
            if body.get("suggest"):
                svc.op_stats.on_suggest(took_ms)
        # surface stored per-doc mapping types on hits (no-op when the
        # index only ever saw untyped writes)
        if any(svc.doc_types for svc in services):
            by_name = {svc.name: svc for svc in services}
            for hit in result.get("hits", {}).get("hits", []):
                svc = by_name.get(hit.get("_index"))
                if svc is not None and svc.doc_types:
                    hit["_type"] = svc.doc_type_of(hit["_id"])
        if scroll is not None:
            import uuid
            scroll_id = uuid.uuid4().hex
            self._reap_scrolls()
            self._scrolls[scroll_id] = {
                "readers": shard_readers, "body": dict(body),
                # scan: the first response returns no hits, the cursor
                # starts at 0; regular scroll continues after page 1
                "pos": 0 if scan_mode else
                       int(body.get("from", 0)) + int(body.get("size", 10)),
                "keepalive_ms": parse_time_value(scroll, 60_000),
                "expires_at": time.time()
                + parse_time_value(scroll, 60_000) / 1000.0,
            }
            result["_scroll_id"] = scroll_id
            if scan_mode:
                result["hits"]["hits"] = []
        return result

    def _aggregate_dfs(self, shard_readers, services, body: dict) -> dict:
        """Collect (field, term) pairs from the query and sum df/doc_count
        across every shard — the aggregateDfs merge."""
        from .search.query_dsl import QueryParser
        from .search.highlight import collect_terms
        if not services or body.get("query") is None:
            return {}
        try:
            ast = QueryParser(services[0].mappers).parse(body["query"])
        except ElasticsearchTpuError:
            return {}
        pairs = [(f, t) for f, terms in collect_terms(ast).items()
                 for t in terms]
        stats: dict[str, list] = {}
        for _, reader in shard_readers:
            for key, (df, n) in reader.term_stats(pairs).items():
                cur = stats.setdefault(key, [0, 0])
                cur[0] += df
                cur[1] += n
        return {k: v for k, v in stats.items() if v[1] > 0}

    def _search_slowlog(self, services, body: dict, took_ms: float) -> None:
        """Per-index search slowlog (ref: index/search/slowlog/
        ShardSlowLogSearchService.java)."""
        for svc in services:
            self._slowlog("index.search.slowlog.query", svc.settings,
                          "index.search.slowlog.threshold.query", took_ms,
                          "[%s] took[%dms], search[%s]", svc.name,
                          int(took_ms), json.dumps(body)[:1000])

    def scroll(self, scroll_id: str, scroll: str | None = None,
               tenant: str | None = None) -> dict:
        """Next page over the stored point-in-time readers (ref:
        TransportSearchScrollAction + SearchService keepalive). Scroll
        pages ride the `scroll` lane (tenant lane override wins) and
        pay admission like any other search — a runaway exporter is
        shed with 429s before it holds anything."""
        ticket = self.traffic.admit(tenant, "scroll")
        try:
            self._reap_scrolls()
            ctx = self._scrolls.get(scroll_id)
            if ctx is None:
                err = ElasticsearchTpuError(
                    f"No search context found for id [{scroll_id}]")
                err.status = 404
                raise err
            body = dict(ctx["body"])
            size = int(body.get("size", 10))
            body["from"] = ctx["pos"]
            ctx["pos"] += size
            if scroll is not None:
                ctx["keepalive_ms"] = parse_time_value(scroll, 60_000)
            ctx["expires_at"] = time.time() + ctx["keepalive_ms"] / 1000.0
            result = self._execute_on_readers(ctx["readers"], body,
                                              lane=ticket.lane)
            result["_scroll_id"] = scroll_id
            return result
        finally:
            ticket.release()

    def clear_scroll(self, scroll_ids: list[str] | None = None) -> dict:
        if scroll_ids is None or scroll_ids == ["_all"]:
            n = len(self._scrolls)
            self._scrolls.clear()
        else:
            n = 0
            for sid in scroll_ids:
                if self._scrolls.pop(sid, None) is not None:
                    n += 1
            if n == 0:
                # ref: RestClearScrollAction — nothing freed is a 404
                return {"succeeded": True, "num_freed": 0, "_missing": True}
        return {"succeeded": True, "num_freed": n}

    def _reap_scrolls(self) -> None:
        now = time.time()
        for sid in [s for s, c in self._scrolls.items()
                    if c["expires_at"] < now]:
            del self._scrolls[sid]

    def _execute_on_readers(self, shard_readers: list[tuple[str, ShardReader]],
                            body: dict, lane: str = "interactive") -> dict:
        batch = self._dispatch.batch(lane=lane)
        st = self._submit_on_readers(shard_readers, body, batch)
        batch.dispatch()
        return self._finish_on_readers(st)

    def _submit_on_readers(self, shard_readers: list[tuple[str, ShardReader]],
                           body: dict, batch,
                           deadline: float | None = None) -> dict:
        """Enqueue the per-shard fan-out of one request onto a dispatch
        batch. Identical plans from other requests on the same batch
        coalesce into ONE batched device program; the rest dispatch
        back-to-back so tunnel round trips overlap (the scheduler in
        search/dispatch.py owns both behaviors)."""
        ap = body.get("allow_partial_search_results")
        if ap is None:
            ap = self.settings.get_bool(
                "search.default_allow_partial_results", True)
        st: dict = {"shard_readers": shard_readers, "body": body,
                    "allow_partial": bool(ap)}
        if not shard_readers:
            st["empty"] = True
            return st
        frm = int(body.get("from", 0))
        size = int(body.get("size", 10))
        # each shard computes the full from+size window (ref: sortDocs)
        shard_body = dict(body)
        shard_body["from"] = 0
        shard_body["size"] = frm + size
        # coordinator-level controls: stripped so plan signatures and
        # request-cache keys stay identical with and without them
        shard_body.pop("timeout", None)
        shard_body.pop("allow_partial_search_results", None)
        from .index.cache import cacheable, canonical_key
        cache_key = None
        cache_by_index: dict[str, bool] = {}
        entries: list[tuple] = []
        for name, reader in shard_readers:
            svc = self.indices.get(name)
            use_cache = cache_by_index.get(name)
            if use_cache is None:
                use_cache = svc is not None and cacheable(
                    shard_body, svc.settings.get_bool(
                        "index.cache.query.enable", False),
                    include_hits=svc.settings.get_bool(
                        "index.cache.query.include_hits", False))
                cache_by_index[name] = use_cache
            r = None
            if use_cache:
                if cache_key is None:
                    cache_key = canonical_key(shard_body)
                # generation-exact key (reader.generation_key inside
                # the cache): a hit is a pure host-side copy — zero
                # device dispatches/transfers/compiles — and is
                # invalidated exactly by compaction / delta-epoch
                # re-keys, never by a reader republish alone
                r = svc.request_cache.get(reader, cache_key)
                self.traffic.note_cache(hit=r is not None)
            if r is None:
                job = batch.submit(reader, shard_body, with_partials=True,
                                   deadline=deadline)
                entries.append(("job", svc if use_cache else None,
                                reader, cache_key, job))
            else:
                entries.append(("hit", None, None, None, r))
        st["entries"] = entries
        return st

    def _finish_on_readers(self, st: dict) -> dict:
        body = st["body"]
        prefailed = st.get("prefailed") or []
        if st.get("empty") and not prefailed:
            # zero shards: empty result (ref: empty SearchResponse)
            return merge_shard_results([], [], [], 0,
                                       int(body.get("size", 10)))
        shard_readers = st["shard_readers"]
        agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        suggest_specs = parse_suggest(body.get("suggest"))
        frm = int(body.get("from", 0))
        size = int(body.get("size", 10))
        allow_partial = st.get("allow_partial", True)
        responses = []
        partials = []
        suggest_parts = []
        failures = []
        hard_errors = []
        timed_out = False
        # contained (corrupt-failed) shards never produced a reader:
        # they enter the reduce as structured failures up front, and
        # fail-fast requests re-raise exactly like an in-flight shard
        # error would
        for name, sid, exc in prefailed:
            if not allow_partial:
                raise exc
            hard_errors.append(exc)
            failures.append(shard_failure(sid, name, exc,
                                          node=self.name))
        for kind, svc, reader, cache_key, payload in st.get("entries", ()):
            if kind == "job":
                # per-shard failure isolation (ref: onShardFailure in
                # TransportSearchTypeAction): a failing shard becomes a
                # structured `_shards.failures` entry and the reduce
                # runs over the survivors — unless the request (or
                # search.default_allow_partial_results) asked for
                # fail-fast, which restores the old re-raise
                try:
                    r = payload.result()
                except Exception as e:  # noqa: BLE001 — any shard error
                    if isinstance(e, SearchTimeoutError):
                        timed_out = True
                    else:
                        hard_errors.append(e)
                    if not allow_partial:
                        raise
                    failures.append(shard_failure(
                        reader.shard_id, reader.index_name, e,
                        node=self.name))
                    continue
                if svc is not None:
                    svc.request_cache.put(reader, cache_key, r)
            else:
                r = payload
            partials.append(r.pop("_agg_partials", {}))
            if "suggest" in r:
                suggest_parts.append(r.pop("suggest"))
            responses.append(r)
        if not responses and hard_errors:
            # ALL shards failed hard (ref: SearchPhaseExecutionException
            # "all shards failed"): a partial response needs at least one
            # survivor; a query that is broken everywhere — parse error,
            # every copy dead — stays an error. All-shards-TIMED-OUT is
            # different: the reference answers that with an (empty)
            # `timed_out: true` response, so pure-timeout exits fall
            # through to the partial reduce below.
            raise hard_errors[0]
        sort = body.get("sort")
        score_sort = sort in (None, [], "_score") or (
            isinstance(sort, list) and sort and sort[0] == "_score")
        descending = True
        multi_orders = None
        if isinstance(sort, list) and len(sort) > 1:
            multi_orders = []
            for e in sort:
                if isinstance(e, str):
                    multi_orders.append(False)
                else:
                    spec = next(iter(e.values()))
                    order = (spec.get("order", "asc")
                             if isinstance(spec, dict) else str(spec))
                    multi_orders.append(str(order).lower() == "desc")
            score_sort = False
        elif not score_sort:
            entry = sort[0] if isinstance(sort, list) else sort
            if isinstance(entry, dict):
                spec = next(iter(entry.values()))
                order = (spec.get("order", "asc") if isinstance(spec, dict)
                         else str(spec))
                descending = order.lower() == "desc"
            else:
                descending = False
        self.metrics.counter("search.query_total").inc()
        if timed_out:
            self.metrics.counter("search.timed_out_total").inc()
        if failures:
            self.metrics.counter("search.shard_failures_total").inc(
                len(failures))
        out = merge_shard_results(responses, agg_specs, partials,
                                  frm=frm, size=size, descending=descending,
                                  score_sort=score_sort,
                                  multi_orders=multi_orders,
                                  total_shards=(len(st.get("entries", ()))
                                                + len(prefailed)),
                                  failures=failures, timed_out=timed_out)
        if suggest_specs:
            out["suggest"] = merge_suggests(suggest_parts, suggest_specs)
        self._apply_sig_subs(out, agg_specs, body, shard_readers)
        return out

    def _apply_sig_subs(self, out: dict, agg_specs, body: dict,
                        shard_readers) -> None:
        """significant_terms nested under a terms agg, fanned over the
        SAME shard set and JLH-scored at the coordinator (see
        aggregations.apply_sig_subs). The enclosing-query foreground
        scope is honored via a capped (10k) matching-id set."""
        if not any(getattr(spec, "sig_subs", None) for spec in agg_specs):
            return
        from .search.aggregations import apply_sig_subs

        def search_ids(query: dict) -> set:
            r = self._execute_on_readers(
                shard_readers, {"query": query, "size": 10_000,
                                "_source": False})
            return {h["_id"] for h in r["hits"]["hits"]}

        apply_sig_subs(agg_specs, out.get("aggregations", {}),
                       [reader for _, reader in shard_readers],
                       raw_query=body.get("query"),
                       search_ids=search_ids)
    def msearch(self, requests: list[tuple],
                tenant: str | None = None) -> dict:
        """Multi-search through the dispatch scheduler: every item's
        fan-out is SUBMITTED before anything is collected, so items
        whose plans finalize identically coalesce into one batched
        device dispatch and the rest pipeline their tunnel round trips
        (vs the serial self.search loop this replaces). Items are
        (index, body) or (index, body, search_type) tuples.

        Admission is PER ITEM (search/traffic.py): the tenant's token
        bucket grants the longest admissible prefix, the rejected tail
        answers structured per-item 429s with `retry_after` — an
        over-quota bulk tenant degrades to partial progress, it is
        never errored wholesale, and no shed item ever touches a
        thread-pool slot or breaker hold.

        Per-request failure isolation: one bad search (e.g. missing
        index) yields an error entry, not a failed batch; every item
        carries its own `took` and `status` (ref:
        TransportMultiSearchAction item responses)."""
        if threading.current_thread().name.startswith("pool-search"):
            return self._msearch_inner(requests)
        from .utils.errors import TrafficRejectedError
        items = self.traffic.admit_items(tenant, "msearch",
                                         len(requests))
        try:
            admitted = requests[:items.granted]
            responses: list[dict] = []
            if admitted:
                pool = self.thread_pool.executor("search")
                try:
                    responses = pool.submit(
                        self._msearch_inner, admitted,
                        items.lane).result()["responses"]
                except ElasticsearchTpuError as e:
                    if e.status != 429:
                        raise
                    # pool saturation: keep the old serial loop's
                    # per-item isolation — every admitted item answers
                    # 429, the batch shape holds
                    responses = [
                        {"error": _legacy_error_string(e),
                         "status": e.status}
                        for _ in admitted]
            if items.granted < len(requests):
                shed = TrafficRejectedError(
                    items.tenant, "rate limit exceeded",
                    retry_after_s=items.retry_after_s)
                responses.extend(
                    {"error": _legacy_error_string(shed),
                     "status": shed.status,
                     "retry_after": shed.info["retry_after"]}
                    for _ in range(len(requests) - items.granted))
            return {"responses": responses}
        finally:
            items.release()

    def _msearch_inner(self, requests: list[tuple],
                       lane: str = "msearch") -> dict:
        batch = self._dispatch.batch(lane=lane)
        prepared: list[tuple] = []
        for item in requests:
            i, b = item[0], item[1]
            search_type = item[2] if len(item) > 2 else None
            t0 = time.monotonic()
            try:
                st = self._search_submit(i, b, None, search_type, batch)
                prepared.append((t0, None, st))
            except ElasticsearchTpuError as e:
                prepared.append((t0, e, None))
        batch.dispatch()
        out = []
        for t0, err, st in prepared:
            if err is None:
                try:
                    r = self._search_finish(st)
                    r["took"] = int((time.monotonic() - t0) * 1000)
                    r["status"] = 200
                    out.append(r)
                    continue
                except ElasticsearchTpuError as e:
                    err = e
            out.append({"error": _legacy_error_string(err),
                        "status": err.status})
        return {"responses": out}

    def count(self, index: str | None, body: dict | None = None) -> dict:
        r = self.search(index, {"query": (body or {}).get("query"), "size": 0})
        return {"count": r["hits"]["total"], "_shards": r["_shards"]}

    # -- admin -------------------------------------------------------------
    def _broadcast_per_index(self, svcs, op) -> dict:
        """Run a per-index maintenance op with real shard accounting:
        an index whose op raises contributes structured failures for its
        shards instead of fabricating `failed: 0` (the same
        shards_header the search reduce uses)."""
        total = successful = 0
        failures: list[dict] = []
        for svc in svcs:
            n = len(svc.shards)
            total += n
            try:
                op(svc)
                successful += n
            except Exception as e:  # noqa: BLE001 — per-index isolation
                failures.extend(
                    shard_failure(sid, svc.name, e, node=self.name)
                    for sid in svc.shards)
        return {"_shards": shards_header(total, successful, failures)}

    def refresh(self, index: str | None = None) -> dict:
        svcs = self._resolve(index)

        def op(svc):
            svc.refresh()
            if getattr(svc, "warmers", None):
                self._run_warmers(svc)

        return self._broadcast_per_index(svcs, op)

    def flush(self, index: str | None = None) -> dict:
        return self._broadcast_per_index(self._resolve(index),
                                         lambda svc: svc.flush())

    def force_merge(self, index: str | None = None,
                    max_num_segments: int = 1) -> dict:
        for svc in self._resolve(index):
            svc.force_merge(max_num_segments)
        return {"acknowledged": True}

    def put_mapping(self, index: str | None, mapping: dict,
                    doc_type: str | None = None) -> dict:
        if mapping and "properties" not in mapping and "dynamic" not in mapping:
            tname, first = next(iter(mapping.items()), (None, None))
            if isinstance(first, dict) and ("properties" in first
                                            or "dynamic" in first
                                            or not first):
                doc_type = doc_type or tname
                mapping = first
        for svc in self._resolve(index, metadata_op=True):
            if doc_type and doc_type not in ("_all", "*", "_doc"):
                svc.mapping_types.add(doc_type)
                svc.mappers.put_type_mapping(doc_type, mapping or {})
            else:
                svc.mappers.merge_mapping(mapping or {})
            self._persist_svc_meta(svc)
        return {"acknowledged": True}

    def get_mapping(self, index: str | None = None,
                    doc_type: str | None = None,
                    expand_wildcards: str = "open") -> dict:
        """GET _mapping[/{type}] — per-type rendering with type-name
        filtering; indices with no matching type are omitted (ref:
        RestGetMappingAction + GetMappingsResponse)."""
        import fnmatch
        pats = None
        if doc_type not in (None, "", "_all", "*"):
            pats = [p.strip() for p in str(doc_type).split(",")]
        out = {}
        for svc in self._resolve(index, expand_wildcards,
                                 metadata_op=True):
            types = sorted(svc.mapping_types)
            if not types and svc.mappers.mapping_dict().get("properties"):
                # untyped (modern-style) mapping renders under _doc
                types = ["_doc"]
            sel = {t: (svc.mappers.type_mapping_dict(t) if t != "_doc"
                       else svc.mappers.mapping_dict())
                   for t in types
                   if pats is None
                   or any(fnmatch.fnmatch(t, p) for p in pats)}
            if pats is None or sel:
                out[svc.name] = {"mappings": sel}
        return out

    def get_field_mapping(self, index: str | None, fields: str,
                          doc_type: str | None = None,
                          include_defaults: bool = False) -> dict:
        """GET _mapping[/{type}]/field/{fields} (ref: action/admin/
        indices/mapping/get/TransportGetFieldMappingsAction.java) —
        {index: {mappings: {type: {field: {full_name, mapping}}}}}."""
        import fnmatch
        fpats = [p.strip() for p in str(fields).split(",")]
        tpats = None
        if doc_type not in (None, "", "_all", "*"):
            tpats = [p.strip() for p in str(doc_type).split(",")]
        out: dict = {}
        type_seen = False
        for svc in self._resolve(index, metadata_op=True):
            types = sorted(svc.mapping_types) or ["_doc"]
            tsel: dict = {}
            for t in types:
                if tpats is not None and not any(
                        fnmatch.fnmatch(t, p) for p in tpats):
                    continue
                type_seen = True
                view = (svc.mappers.types.get(t)
                        if t != "_doc" else None) or svc.mappers.mapper
                fsel: dict = {}
                added: set[str] = set()

                def emit(key: str, fname: str, fm) -> None:
                    if key in fsel or fname in added:
                        return
                    spec = fm.to_dict()
                    if include_defaults and fm.type == "text":
                        spec.setdefault("analyzer", "default")
                    fsel[key] = {"full_name": fname,
                                 "mapping": {fname.rsplit(".", 1)[-1]:
                                             spec}}
                    added.add(fname)

                # two resolve rounds with full-name preference (ref:
                # TransportGetFieldMappingsAction full name > short name)
                for pat in fpats:
                    for fname, fm in sorted(view._fields.items()):
                        if fnmatch.fnmatch(fname, pat):
                            emit(fname, fname, fm)
                    for fname, fm in sorted(view._fields.items()):
                        short = fname.rsplit(".", 1)[-1]
                        if fnmatch.fnmatch(short, pat):
                            emit(short, fname, fm)
                if fsel:
                    tsel[t] = fsel
            if tsel:
                out[svc.name] = {"mappings": tsel}
        if tpats is not None and not type_seen and not any(
                "*" in p or "?" in p for p in tpats):
            from .utils.errors import TypeMissingError
            raise TypeMissingError(doc_type)  # ref: TypeMissingException
        return out

    def get_settings(self, index: str | None = None,
                     flat: bool = False,
                     name: str | None = None,
                     expand_wildcards: str = "open") -> dict:
        """GET _settings[/{name}]: nested string-valued tree by default,
        flat dotted keys with ?flat_settings=true, optional setting-name
        filter incl. wildcards (ref: RestGetSettingsAction +
        Settings.toXContent)."""
        import fnmatch
        pats = None
        if name not in (None, "", "_all", "*"):
            pats = [p.strip() for p in str(name).split(",")]
        out = {}
        for svc in self._resolve(index, expand_wildcards,
                                 metadata_op=True):
            entries = {"index.number_of_shards": str(svc.num_shards),
                       "index.number_of_replicas": str(svc.num_replicas),
                       "index.uuid": svc.name,
                       "index.version.created": "2000099"}
            for k, v in svc.settings.as_dict().items():
                if k.startswith("index."):
                    entries[k] = str(v)
            if pats is not None:
                entries = {k: v for k, v in entries.items()
                           if any(fnmatch.fnmatch(k, p) for p in pats)}
            if flat:
                out[svc.name] = {"settings": dict(entries)}
            else:
                nested: dict = {}
                for k, v in entries.items():
                    cur = nested
                    parts = k.split(".")
                    for part in parts[:-1]:
                        nxt = cur.setdefault(part, {})
                        if not isinstance(nxt, dict):
                            nxt = cur[part] = {}
                        cur = nxt
                    cur[parts[-1]] = v
                out[svc.name] = {"settings": nested}
        return out

    def update_index_settings(self, index: str | None, body: dict,
                              ignore_unavailable: bool = False) -> dict:
        """PUT _settings (ref: MetaDataUpdateSettingsService — dynamic
        per-index settings; number_of_replicas is the canonical one)."""
        flat: dict = {}

        def flatten(prefix, obj):
            for k, v in (obj or {}).items():
                key = f"{prefix}{k}"
                if isinstance(v, dict):
                    flatten(key + ".", v)
                else:
                    flat[key] = v
        body = body or {}
        flatten("", body.get("settings", body))
        norm = {}
        for k, v in flat.items():
            if not k.startswith("index."):
                k = "index." + k
            norm[k] = v
        for svc in self._resolve(index,
                                 ignore_unavailable=ignore_unavailable):
            if "index.number_of_replicas" in norm:
                svc.num_replicas = int(norm["index.number_of_replicas"])
            svc.settings = svc.settings.merged_with(norm)
        return {"acknowledged": True}

    def cluster_health(self, level: str | None = None,
                       index: str | None = None) -> dict:
        svcs = self._resolve(index) if index else list(self.indices.values())
        shards = sum(len(s.shards) for s in svcs)
        out = {
            "cluster_name": self.cluster_name,
            "status": "green",
            "timed_out": False,
            "number_of_nodes": 1,
            "number_of_data_nodes": 1,
            "active_primary_shards": shards,
            "active_shards": shards,
            "relocating_shards": 0,
            "initializing_shards": 0,
            "unassigned_shards": 0,
            "delayed_unassigned_shards": 0,
            "number_of_pending_tasks": 0,
            "number_of_in_flight_fetch": 0,
            "task_max_waiting_in_queue_millis": 0,
            "active_shards_percent_as_number": 100.0,
        }
        if level in ("indices", "shards"):
            out["indices"] = {}
            for svc in svcs:
                entry = {
                    "status": "green",
                    "number_of_shards": svc.num_shards,
                    "number_of_replicas": svc.num_replicas,
                    "active_primary_shards": svc.num_shards,
                    "active_shards": svc.num_shards,
                    "relocating_shards": 0,
                    "initializing_shards": 0,
                    "unassigned_shards": 0,
                }
                if level == "shards":
                    entry["shards"] = {
                        str(sid): {"status": "green", "primary_active": True,
                                   "active_shards": 1,
                                   "relocating_shards": 0,
                                   "initializing_shards": 0,
                                   "unassigned_shards": 0}
                        for sid in svc.shards}
                out["indices"][svc.name] = entry
        return out

    def stats(self) -> dict:
        return {
            "cluster_name": self.cluster_name,
            "indices": {name: svc.stats() for name, svc in self.indices.items()},
            "metrics": self.metrics.snapshot(),
        }

    def cat_indices(self) -> list[dict]:
        out = []
        for name, svc in sorted(self.indices.items()):
            size = sum(e.segment_stats()["memory_in_bytes"]
                       for e in svc.shards.values())
            out.append({"health": "green",
                        "status": ("close" if name in self._closed
                                   else "open"),
                        "index": name,
                        "pri": svc.num_shards, "rep": svc.num_replicas,
                        "docs.count": svc.doc_count(),
                        "docs.deleted": 0,
                        "store.size": size, "pri.store.size": size})
        return out

    # -- aliases (ref: MetaDataIndexAliasesService, rest/action/admin/
    # indices/alias/) ------------------------------------------------------
    def update_aliases(self, actions: list[dict]) -> dict:
        import fnmatch
        for entry in actions:
            op, spec = next(iter(entry.items()))
            # index/indices and alias/aliases forms both accepted
            # (ref: IndicesAliasesRequest AliasActions)
            idx_expr = spec.get("index", spec.get("indices"))
            if isinstance(idx_expr, list):
                idx_expr = ",".join(idx_expr)
            aliases = spec.get("aliases", spec.get("alias"))
            if not aliases:
                raise IllegalArgumentError("[aliases] requires [alias]")
            alias_list = (aliases if isinstance(aliases, list)
                          else [aliases])
            if idx_expr is None:
                # ref: IndicesAliasesRequest.validate
                raise IllegalArgumentError(
                    f"[aliases] action [{op}] requires an [index]")
            if op == "add":
                svcs = self._resolve(idx_expr, metadata_op=True)
                if not svcs and idx_expr is not None \
                        and "*" not in str(idx_expr):
                    raise IndexNotFoundError(idx_expr)
                meta: dict = {}
                if spec.get("filter") is not None:
                    meta["filter"] = spec["filter"]
                routing = spec.get("routing")
                ir = spec.get("index_routing",
                              spec.get("index-routing", routing))
                sr = spec.get("search_routing",
                              spec.get("search-routing", routing))
                if ir is not None:
                    meta["index_routing"] = str(ir)
                if sr is not None:
                    meta["search_routing"] = str(sr)
                for alias in alias_list:
                    for svc in svcs:
                        self._aliases.setdefault(alias, set()).add(svc.name)
                        # alias metadata: filter + routing split (ref:
                        # cluster/metadata/AliasMetaData.java — `routing`
                        # sets both index_ and search_routing)
                        self._alias_meta[(alias, svc.name)] = dict(meta)
            elif op == "remove":
                removed = False
                index_names = [s.name for s in
                               self._resolve(idx_expr, metadata_op=True)]
                alias_list = ["*" if p == "_all" else p
                              for p in alias_list]
                for pat in alias_list:
                    for a in list(self._aliases):
                        if not fnmatch.fnmatch(a, pat):
                            continue
                        targets = self._aliases[a]
                        for iname in index_names:
                            if iname in targets:
                                targets.discard(iname)
                                self._alias_meta.pop((a, iname), None)
                                removed = True
                        if not targets:
                            del self._aliases[a]
                if not removed:
                    from .utils.errors import AliasesMissingError
                    raise AliasesMissingError(alias_list)
            else:
                raise IllegalArgumentError(f"unknown alias action [{op}]")
        return {"acknowledged": True}

    def put_alias(self, index: str | None, alias: str,
                  body: dict | None = None) -> dict:
        spec = {"index": index, "alias": alias, **(body or {})}
        return self.update_aliases([{"add": spec}])

    def delete_alias(self, index: str, alias: str) -> dict:
        return self.update_aliases([{"remove": {"index": index,
                                                "alias": alias}}])

    def alias_meta(self, alias: str, index: str) -> dict:
        return self._alias_meta.get((alias, index), {})

    def get_aliases(self, index: str | None = None,
                    name: str | None = None,
                    include_empty: bool = False) -> dict:
        """`include_empty` distinguishes the /_aliases rendering (every
        resolved index appears, possibly with an empty aliases map) from
        /_alias (indices with no matching alias are omitted). Ref:
        RestGetAliasesAction vs RestGetIndicesAliasesAction."""
        import fnmatch
        pats = None
        if name not in (None, "", "_all", "*"):
            pats = [p.strip() for p in str(name).split(",")]
        out: dict = {}
        for svc in self._resolve(index, metadata_op=True):
            aliases = {}
            for a, targets in self._aliases.items():
                if svc.name not in targets:
                    continue
                if pats is not None and not any(
                        fnmatch.fnmatch(a, p) for p in pats):
                    continue
                aliases[a] = self.alias_meta(a, svc.name)
            if pats is None or aliases or include_empty:
                out[svc.name] = {"aliases": aliases}
        return out

    # -- templates (ref: MetaDataIndexTemplateService) ---------------------
    @staticmethod
    def _alias_spec_meta(spec) -> dict:
        """Normalize an alias spec to AliasMetaData rendering (routing
        splits into index_routing/search_routing)."""
        meta: dict = {}
        spec = spec if isinstance(spec, dict) else {}
        if spec.get("filter") is not None:
            meta["filter"] = spec["filter"]
        routing = spec.get("routing")
        ir = spec.get("index_routing", routing)
        sr = spec.get("search_routing", routing)
        if ir is not None:
            meta["index_routing"] = str(ir)
        if sr is not None:
            meta["search_routing"] = str(sr)
        return meta

    def put_template(self, name: str, body: dict,
                     create: bool = False) -> dict:
        if create and name in self._templates:
            raise IllegalArgumentError(
                f"index_template [{name}] already exists")
        patterns = body.get("index_patterns") or body.get("template")
        if patterns is None:
            raise IllegalArgumentError(
                "index template requires [index_patterns]")
        if isinstance(patterns, str):
            patterns = [patterns]
        mappings = body.get("mappings") or {}
        if mappings and "properties" not in mappings:
            first = next(iter(mappings.values()), None)
            if isinstance(first, dict) and "properties" in first:
                mappings = first
        # settings normalize to flat "index."-prefixed string values
        # (ref: IndexTemplateMetaData settings rendering)
        flat = Settings(body.get("settings") or {}).as_dict()
        settings = {(k if k.startswith("index.") else f"index.{k}"):
                    str(v) for k, v in flat.items()}
        self._templates[name] = {
            "patterns": list(patterns),
            "order": int(body.get("order", 0)),
            "settings": settings,
            "mappings": dict(mappings),
            "aliases": dict(body.get("aliases") or {}),
        }
        return {"acknowledged": True}

    def get_templates(self, name: str | None = None,
                      flat: bool = False) -> dict:
        """GET _template[/{name}] in the 2.0 shape: single `template`
        pattern, string-valued settings (nested unless flat_settings),
        AliasMetaData-shaped aliases. A concrete missing name is a 404
        (ref: RestGetIndexTemplateAction)."""
        import fnmatch
        out = {}
        for tname, t in sorted(self._templates.items()):
            if name in (None, "*") or fnmatch.fnmatch(tname, name):
                settings: dict = dict(t["settings"])
                if not flat:
                    nested: dict = {}
                    for k, v in settings.items():
                        cur = nested
                        parts = k.split(".")
                        for part in parts[:-1]:
                            nxt = cur.setdefault(part, {})
                            if not isinstance(nxt, dict):
                                nxt = cur[part] = {}
                            cur = nxt
                        cur[parts[-1]] = v
                    settings = nested
                out[tname] = {"template": t["patterns"][0],
                              "index_patterns": t["patterns"],
                              "order": t["order"],
                              "settings": settings,
                              "mappings": t["mappings"],
                              "aliases": {a: self._alias_spec_meta(sp)
                                          for a, sp in
                                          t["aliases"].items()}}
        if not out and name is not None and "*" not in name:
            raise IndexNotFoundError(f"index_template [{name}]")
        return out

    def delete_template(self, name: str) -> dict:
        if name not in self._templates:
            raise IndexNotFoundError(f"index_template [{name}] missing")
        del self._templates[name]
        return {"acknowledged": True}

    # -- open/close (ref: MetaDataIndexStateService) -----------------------
    def close_index(self, name: str) -> dict:
        for svc in self._resolve(name, expand_wildcards="open",
                                 metadata_op=True):
            self._closed.add(svc.name)
        return {"acknowledged": True}

    def open_index(self, name: str) -> dict:
        for svc in self._resolve(name, expand_wildcards="open,closed",
                                 metadata_op=True):
            self._closed.discard(svc.name)
        return {"acknowledged": True}

    # -- validate / explain ------------------------------------------------
    def validate_query(self, index: str | None, body: dict | None,
                       explain: bool = False) -> dict:
        """Ref: action/admin/indices/validate/query/."""
        from .search.query_dsl import QueryParser
        services = self._resolve(index)
        mapper = services[0].mappers if services else None
        try:
            if mapper is None:
                from .index.mapping import MapperService
                mapper = MapperService()
            q = QueryParser(mapper).parse((body or {}).get("query"))
            out = {"valid": True,
                   "_shards": {"total": 1, "successful": 1, "failed": 0}}
            if explain:
                from .search.query_dsl import lucene_str
                out["explanations"] = [
                    {"index": svc.name, "valid": True,
                     "explanation": lucene_str(q)} for svc in services]
            return out
        except ElasticsearchTpuError as e:
            return {"valid": False,
                    "_shards": {"total": 1, "successful": 1, "failed": 0},
                    "error": str(e)}

    def explain_doc(self, index: str, doc_id: str, body: dict | None) -> dict:
        """Ref: action/explain/TransportExplainAction — score breakdown of
        one doc against a query (matched + value; the per-term Lucene
        explanation tree maps to the eager-impact summary here)."""
        svc = self._index(index)  # resolves aliases; 404 when missing
        query = (body or {}).get("query") or {"match_all": {}}
        restricted = {"bool": {"must": [query],
                               "filter": [{"ids": {"values": [doc_id]}}]}}
        r = self.search(svc.name, {"query": restricted, "size": 1})
        matched = r["hits"]["total"] > 0
        out = {"_index": svc.name, "_type": svc.doc_type_of(doc_id),
               "_id": doc_id, "matched": matched}
        if matched:
            hit = r["hits"]["hits"][0]
            out["explanation"] = {
                "value": hit.get("_score") or 0.0,
                "description": "sum of eager-impact BM25 term scores "
                               "(device batch scorer)",
                "details": []}
        src_spec = (body or {}).get("_source")
        if src_spec is not None:
            # ?_source=... adds a get section with the filtered source
            # (ref: TransportExplainAction fetchSourceContext)
            from .search.shard_searcher import filter_source
            g: dict = {"found": True}
            try:
                doc = self.get_doc(index, doc_id)
                obj = doc.get("_source")
                obj = (json.loads(obj)
                       if isinstance(obj, (bytes, str)) else obj)
                filtered = filter_source(obj or {}, src_spec)
                if filtered is not None:
                    g["_source"] = filtered
            except ElasticsearchTpuError:
                g["found"] = False
            out["get"] = g
        return out

    # -- percolator (ref: percolator/PercolatorService.java; REST 2.0
    # shape: queries registered under the .percolator type, executed via
    # /{index}/_percolate) ------------------------------------------------
    def register_percolator(self, index: str, query_id: str,
                            body: dict | None) -> dict:
        svc = self._ensure_index(index)
        r = svc.percolator.register(query_id, body or {})
        return {"_index": svc.name, "_type": ".percolator", "_id": query_id,
                "created": r["created"], "_version": 1}

    def unregister_percolator(self, index: str, query_id: str) -> dict:
        svc = self._index(index)
        found = svc.percolator.unregister(query_id)
        return {"_index": svc.name, "_type": ".percolator", "_id": query_id,
                "found": found}

    def get_percolator(self, index: str, query_id: str) -> dict:
        svc = self._index(index)
        q = svc.percolator.get(query_id)
        out = {"_index": svc.name, "_type": ".percolator", "_id": query_id,
               "found": q is not None}
        if q is not None:
            out["_source"] = q
        return out

    def percolate(self, index: str, body: dict | None,
                  count_only: bool = False) -> dict:
        body = body or {}
        doc = body.get("doc")
        if doc is None:
            raise IllegalArgumentError("percolate request requires [doc]")
        svc = self._index(index)
        from .index.stats import timed
        with timed() as t:
            res = svc.percolate(doc, body.get("filter"), body.get("size"))
        svc.op_stats.on_percolate(t.ms)
        out = {"took": 0, "_shards": {"total": svc.num_shards,
                                      "successful": svc.num_shards,
                                      "failed": 0},
               "total": res["total"]}
        if not count_only:
            out["matches"] = res["matches"]
        return out

    def mpercolate(self, payload: list[dict]) -> dict:
        """_mpercolate: alternating {percolate: {...}} header / doc lines
        (ref: action/percolate/TransportMultiPercolateAction)."""
        responses = []
        i = 0
        while i + 1 < len(payload) or (i < len(payload) and
                                       "percolate" in payload[i]):
            header = payload[i].get("percolate") or {}
            body = payload[i + 1] if i + 1 < len(payload) else {}
            i += 2
            try:
                responses.append(self.percolate(header.get("index"), body))
            except ElasticsearchTpuError as e:
                responses.append({"error": _legacy_error_string(e)})
        return {"responses": responses}

    def segments(self, index: str | None = None,
                 ignore_unavailable: bool = False,
                 allow_no_indices: bool = True) -> dict:
        """GET _segments (ref: action/admin/indices/segments/
        IndicesSegmentsAction — per-shard copy rows with routing +
        named Lucene-style segment entries)."""
        svcs = self._resolve(index, ignore_unavailable=ignore_unavailable)
        if not svcs and not allow_no_indices:
            raise IndexNotFoundError(index if index else "_all")
        out = {}
        n_shards = 0
        for svc in svcs:
            shards = {}
            for sid, eng in svc.shards.items():
                n_shards += 1
                segs = {}
                for i, seg in enumerate(eng.segments):
                    live = eng.live.get(seg.seg_id)
                    num_live = (int(live.sum()) if live is not None
                                else seg.num_docs)
                    segs[f"_{i}"] = {
                        "generation": i,
                        "num_docs": num_live,
                        "deleted_docs": seg.num_docs - num_live,
                        "size_in_bytes": seg.nbytes(),
                        "memory_in_bytes": seg.nbytes(),
                        "committed": True, "search": True,
                        "version": "tpu-columnar", "compound": False,
                    }
                shards[str(sid)] = [{
                    "routing": {"state": "STARTED", "primary": True,
                                "node": self.name},
                    "num_committed_segments": len(segs),
                    "num_search_segments": len(segs),
                    "segments": segs,
                }]
            out[svc.name] = {"shards": shards}
        return {"_shards": {"total": n_shards, "successful": n_shards,
                            "failed": 0},
                "indices": out}

    # -- cluster settings (ref: ClusterUpdateSettingsAction) ---------------
    def get_cluster_settings(self) -> dict:
        return {"persistent": dict(getattr(self, "_persistent_settings", {})),
                "transient": dict(getattr(self, "_transient_settings", {}))}

    def put_cluster_settings(self, body: dict) -> dict:
        pers = dict(getattr(self, "_persistent_settings", {}))
        trans = dict(getattr(self, "_transient_settings", {}))
        pers.update(body.get("persistent") or {})
        trans.update(body.get("transient") or {})
        self._persistent_settings = pers
        self._transient_settings = trans
        # traffic quotas are DYNAMIC: republish the effective
        # `search.traffic.*` group (node settings layered under
        # persistent under transient) into the controller — counters
        # and in-flight accounting survive, limits change immediately
        merged = self.settings.merged_with(Settings(pers)) \
                     .merged_with(Settings(trans))
        self.traffic.reconfigure(
            merged.by_prefix("search.traffic.").as_dict())
        return {"acknowledged": True, "persistent": pers,
                "transient": trans}

    def cluster_state(self, metrics: str | None = None,
                      index: str | None = None,
                      expand_wildcards: str = "open",
                      ignore_unavailable: bool = False,
                      allow_no_indices: bool = True) -> dict:
        """Full state, or sections selected by the `metrics` path part
        (ref: RestClusterStateAction metric filtering)."""
        if index:
            svcs = self._resolve(index, expand_wildcards,
                                 ignore_unavailable=ignore_unavailable,
                                 metadata_op=True)
            if not svcs and not allow_no_indices:
                raise IndexNotFoundError(index)
            names = [s.name for s in svcs]
        else:
            names = list(self.indices)
        # index-level blocks from index.blocks.* settings (ref:
        # cluster/block/ClusterBlocks + IndexMetaData block settings)
        blocks_idx: dict = {}
        _block_ids = {"read_only": "5", "read": "7", "write": "8",
                      "metadata": "9"}
        for name, svc in self.indices.items():
            entry = {}
            for kind, bid in _block_ids.items():
                if svc.settings.get_bool(f"index.blocks.{kind}", False):
                    entry[bid] = {
                        "description": f"index {kind} (api)",
                        "retryable": False,
                        "levels": ["write"] if kind != "read"
                        else ["read"]}
            if entry:
                blocks_idx[name] = entry
        full = {
            "cluster_name": self.cluster_name,
            "version": 1,
            "master_node": self.name,
            "blocks": ({"indices": blocks_idx} if blocks_idx else {}),
            "nodes": {self.name: {"name": self.name}},
            "routing_table": {"indices": {
                name: {"shards": {}} for name in names}},
            "routing_nodes": {"unassigned": [], "nodes": {self.name: []}},
            "metadata": {"indices": {
                name: {"state": ("close" if name in self._closed
                                 else "open"),
                       "settings": {"index": {
                           "number_of_shards": svc.num_shards,
                           "number_of_replicas": svc.num_replicas}},
                       "mappings": {"_doc": svc.mappers.mapping_dict()},
                       "aliases": [a for a, t in self._aliases.items()
                                   if name in t]}
                for name, svc in self.indices.items() if name in names}},
        }
        if metrics in (None, "_all"):
            return full
        keep = {m.strip() for m in metrics.split(",")}
        out = {"cluster_name": full["cluster_name"]}
        for key in ("version", "master_node", "blocks", "nodes",
                    "routing_table", "routing_nodes", "metadata"):
            if key in keep:
                out[key] = full[key]
        return out

    def cat_shards(self, index: str | None = None) -> list[dict]:
        """One row per shard COPY: primaries STARTED on this node,
        replicas UNASSIGNED (single-node cluster has nowhere to place
        them) — ref: RestShardsAction row shape."""
        out = []
        wanted = ({s.name for s in self._resolve(index)}
                  if index is not None else None)
        for name, svc in sorted(self.indices.items()):
            if wanted is not None and name not in wanted:
                continue
            for sid, eng in svc.shards.items():
                size = eng.segment_stats()["memory_in_bytes"]
                out.append({"index": name, "shard": sid, "prirep": "p",
                            "state": "STARTED", "docs": eng.doc_count(),
                            "store": size, "ip": "127.0.0.1",
                            "node": self.name})
                shadow = svc.settings.get_bool(
                    "index.shadow_replicas", False)
                for _r in range(svc.num_replicas):
                    out.append({"index": name, "shard": sid,
                                "prirep": "s" if shadow else "r",
                                "state": "UNASSIGNED"})
        return out

    def cat_count(self, index: str | None = None) -> list[dict]:
        import datetime
        now = datetime.datetime.now(datetime.timezone.utc)
        total = sum(svc.doc_count() for svc in self._resolve(index))
        return [{"epoch": int(now.timestamp()),
                 "timestamp": now.strftime("%H:%M:%S"), "count": total}]

    # -- persistence of index metadata (gateway analog) --------------------
    def _persist_index_meta(self, svc: IndexService, settings: dict) -> None:
        meta = {"settings": settings,
                "mappings": svc.mappers.mapping_dict(),
                "types": {t: svc.mappers.type_mapping_dict(t)
                          for t in svc.mapping_types},
                "warmers": dict(getattr(svc, "warmers", {}))}
        path = os.path.join(self.data_path, svc.name, "_meta.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    def _load_existing_indices(self) -> None:
        for name in sorted(os.listdir(self.data_path)):
            meta_path = os.path.join(self.data_path, name, "_meta.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = json.load(f)
                svc = IndexService(name, self.settings.merged_with(
                    meta.get("settings") or {}), meta.get("mappings"),
                    data_path=self.data_path,
                    type_mappings=meta.get("types") or None)
                svc.mapping_types = set(meta.get("types") or ())
                if meta.get("warmers"):
                    svc.warmers = dict(meta["warmers"])
                self.indices[name] = svc

    # -- query-driven writes (ref: action/deletebyquery/ in 2.0;
    # update-by-query landed upstream later but completes the surface) ---
    _QUERY_WRITE_PAGE = 1000

    def delete_by_query(self, index: str | None, body: dict | None) -> dict:
        """Per-ENGINE sweep (matches the reference's per-shard
        TransportDeleteByQueryAction): deleting through the owning engine
        sidesteps doc-id re-routing (custom-routed docs delete correctly)
        and gives a natural progress guarantee per shard."""
        query = (body or {}).get("query") or {"match_all": {}}
        deleted = 0
        failures: list[dict] = []
        for svc in self._resolve(index):
            for eng in svc.shards.values():
                while True:
                    reader = eng.acquire_searcher()
                    r = reader.search({"query": query,
                                       "size": self._QUERY_WRITE_PAGE,
                                       "_source": False})
                    ids = [h["_id"] for h in r["hits"]["hits"]]
                    if not ids:
                        break
                    progress = False
                    for did in ids:
                        try:
                            res = eng.delete(did)
                            if res.get("found", True):
                                deleted += 1
                                progress = True
                        except ElasticsearchTpuError as e:
                            failures.append({"index": svc.name, "id": did,
                                             "cause": str(e)})
                    eng.refresh()
                    if not progress:
                        break
        return {"deleted": deleted, "failures": failures,
                "_indices": {"_all": {"deleted": deleted}}}

    def update_by_query(self, index: str | None, body: dict | None) -> dict:
        """Per-engine script update sweep; a seen-set per engine prevents
        both re-updating and window starvation across shards."""
        body = body or {}
        query = body.get("query") or {"match_all": {}}
        script = body.get("script")
        updated = 0
        failures: list[dict] = []
        for svc in self._resolve(index):
            for eng in svc.shards.values():
                seen: set[str] = set()
                while True:
                    reader = eng.acquire_searcher()
                    r = reader.search({"query": query,
                                       "size": self._QUERY_WRITE_PAGE,
                                       "_source": True})
                    fresh = [h for h in r["hits"]["hits"]
                             if h["_id"] not in seen]
                    if not fresh:
                        break
                    for h in fresh:
                        seen.add(h["_id"])
                        try:
                            src = h.get("_source") or {}
                            if script is not None:
                                src = self._run_update_script(script, src)
                            if src is None:
                                continue           # ctx.op = none
                            if src == "__delete__":
                                eng.delete(h["_id"])
                                continue
                            eng.index(h["_id"], src)
                            updated += 1
                        except ElasticsearchTpuError as e:
                            failures.append({"index": svc.name,
                                             "id": h["_id"],
                                             "cause": str(e)})
                    eng.refresh()
        return {"updated": updated, "failures": failures}

    # -- TTL sweep (ref: indices/ttl/IndicesTTLService.java) ---------------
    def purge_expired(self) -> int:
        """Delete docs whose _ttl_expiry has passed. Returns count."""
        now = int(time.time() * 1000)
        total = 0
        for name, svc in list(self.indices.items()):
            if svc.mappers.field("_ttl_expiry") is None:
                continue
            r = self.delete_by_query(name, {"query": {
                "range": {"_ttl_expiry": {"lte": now}}}})
            total += r["deleted"]
        return total

    # -- warmers (ref: indices/IndicesWarmer.java + search/warmer/ —
    # registered searches run after refresh; here they additionally
    # pre-compile the XLA programs the real traffic will hit) -------------
    @staticmethod
    def _warmer_pats(name: str | None) -> list[str] | None:
        if name in (None, ""):
            return None
        return ["*" if p.strip() == "_all" else p.strip()
                for p in str(name).split(",")]

    def _persist_svc_meta(self, svc) -> None:
        if self.data_path:
            self._persist_index_meta(svc, {
                k: v for k, v in svc.settings.as_dict().items()
                if k.startswith("index.")})

    def put_warmer(self, index: str | None, name: str,
                   body: dict | None) -> dict:
        src = body or {"query": {"match_all": {}}}
        for svc in self._resolve(index, metadata_op=True):
            if not hasattr(svc, "warmers"):
                svc.warmers = {}
            svc.warmers[name] = src
            self._persist_svc_meta(svc)
        return {"acknowledged": True}

    def get_warmers(self, index: str | None = None,
                    name: str | None = None) -> dict:
        """Response shape {index: {warmers: {name: {types, source}}}};
        with a name filter, indices with no match are omitted entirely
        (ref: RestGetWarmerAction + GetWarmersResponse rendering)."""
        import fnmatch
        pats = self._warmer_pats(name)
        out: dict = {}
        for svc in self._resolve(index):
            warmers = {
                n: {"types": [], "source": b}
                for n, b in sorted(getattr(svc, "warmers", {}).items())
                if pats is None
                or any(fnmatch.fnmatch(n, p) for p in pats)}
            if pats is None or warmers:
                out[svc.name] = {"warmers": warmers}
        return out

    def delete_warmer(self, index: str, name: str | None = None) -> dict:
        import fnmatch
        from .utils.errors import WarmerMissingError
        pats = self._warmer_pats(name) or ["*"]
        found = False
        for svc in self._resolve(index, metadata_op=True):
            warmers = getattr(svc, "warmers", {})
            changed = False
            for n in [n for n in warmers
                      if any(fnmatch.fnmatch(n, p) for p in pats)]:
                warmers.pop(n)
                found = changed = True
            if changed:
                self._persist_svc_meta(svc)
        if not found:
            # ref: IndexWarmerMissingException -> 404
            raise WarmerMissingError(name if name is not None else "_all")
        return {"acknowledged": True}

    def _run_warmers(self, svc) -> None:
        for wbody in getattr(svc, "warmers", {}).values():
            try:
                self.search(svc.name, dict(wbody))
            except ElasticsearchTpuError:
                pass  # a broken warmer must not fail the refresh

    # -- cache clear (ref: indices/cache/ + RestClearIndicesCacheAction) ---
    def clear_cache(self, index: str | None = None) -> dict:
        n = 0
        for svc in self._resolve(index):
            svc.request_cache.clear()
            for eng in svc.shards.values():
                if eng.failed is not None:
                    continue  # contained shard: nothing resident
                reader = eng.acquire_searcher()
                reader._global_ords.clear()
                for seg in reader.segments:
                    # drop HBM-resident columns + cached live uploads +
                    # pinned resident executables (Segment.drop_device)
                    seg.drop_device()
                n += 1
        return {"_shards": {"total": n, "successful": n, "failed": 0}}

    def recovery_status(self, index: str | None = None) -> dict:
        """Ref: action/admin/indices/recovery/ — per-shard recovery info
        (single-node: every shard recovered from local store/translog)."""
        out = {}
        for svc in self._resolve(index):
            shards = []
            for sid, eng in svc.shards.items():
                if eng.failed is not None:
                    # contained shard: the failure reason and the
                    # on-disk corruption marker are the recovery story
                    # (ref: a corruption-marked store refusing to open)
                    shards.append({
                        "id": sid,
                        "type": "GATEWAY", "stage": "FAILED",
                        "primary": True,
                        "failure": {
                            "reason": eng.failed["reason"],
                            "during": eng.failed["during"],
                            "corruption_marker": eng.failed["marker"],
                        },
                    })
                    continue
                size = eng.segment_stats()["memory_in_bytes"]
                shards.append({
                    "id": sid,
                    # a locally-restored primary is a GATEWAY recovery
                    # in 2.0 terms (RecoveryState.Type.GATEWAY)
                    "type": "GATEWAY", "stage": "DONE",
                    "primary": True,
                    "source": {"name": self.name, "ip": "127.0.0.1",
                               "host": "127.0.0.1"},
                    "target": {"name": self.name, "ip": "127.0.0.1",
                               "host": "127.0.0.1"},
                    "index": {
                        "size": {"total_in_bytes": size,
                                 "reused_in_bytes": size,
                                 "recovered_in_bytes": 0,
                                 "percent": "100.0%"},
                        "files": {"total": len(eng.segments),
                                  "reused": len(eng.segments),
                                  "recovered": 0,
                                  "percent": "100.0%"},
                        "source_throttle_time_in_millis": 0,
                        "target_throttle_time_in_millis": 0,
                        "total_time_in_millis": 0},
                    "translog": {"recovered": 0, "total": -1,
                                 "total_on_start": 0,
                                 "total_time_in_millis": 0},
                    "start": {"check_index_time_in_millis": 0,
                              "total_time_in_millis": 0},
                })
            out[svc.name] = {"shards": shards}
        return out

    def verify_integrity(self, index: str | None = None) -> dict:
        """Per-shard store audit (the `index.shard.check_on_startup`
        pass, callable on demand): commit readability, per-segment
        checksums, corruption markers, live translog tail sanity.
        Pure reads — serving state is untouched. The kill -9 soak's
        post-restart gate: `clean` must hold after ANY crash."""
        out: dict = {"clean": True, "indices": {}}
        for svc in self._resolve(index):
            shards = {}
            for sid, eng in svc.shards.items():
                if eng.store is None:
                    continue
                rep = eng.store.verify_integrity()
                if eng.failed is not None:
                    rep["failed"] = dict(eng.failed)
                    rep["clean"] = False
                shards[str(sid)] = rep
                out["clean"] &= rep["clean"]
            if shards:
                out["indices"][svc.name] = {"shards": shards}
        return out

    # -- monitoring (ref: monitor/MonitorService.java, _nodes APIs) --------
    def nodes_info(self) -> dict:
        import platform
        return {"cluster_name": self.cluster_name, "nodes": {self.name: {
            "name": self.name,
            "version": "0.1.0",
            "build_flavor": "tpu-native",
            "roles": ["master", "data", "ingest"],
            "os": {"name": platform.system(),
                   "arch": platform.machine(),
                   "available_processors": os.cpu_count() or 1},
            "process": {"id": os.getpid()},
            "plugins": self.plugins.info(),
            "thread_pool": {n: {"threads": p.size,
                                "queue_size": p.queue_size}
                            for n, p in self.thread_pool.pools.items()},
            "transport": {"profiles": {},
                          "bound_address": ["local"],
                          "publish_address": "local"},
            "http": {"bound_address": ["127.0.0.1:9200"],
                     "publish_address": "127.0.0.1:9200"},
            "settings": self.settings.as_dict(),
        }}}

    def nodes_stats(self) -> dict:
        from .utils import monitor
        from .search.executor import fused_scoring_stats
        from .index import devbuild
        return {"cluster_name": self.cluster_name, "nodes": {self.name: {
            "name": self.name,
            # per-index stats + the process-wide durability counter
            # block (index/durability.py): salvage/containment events
            # a chaos run asserts on — and a clean recovery asserts
            # are ZERO (the "durability" key shadows a same-named
            # index here; accepted, the stats API still serves it)
            "indices": {**{name: svc.stats()
                           for name, svc in self.indices.items()},
                        "durability": _durability_snapshot()},
            "os": monitor.os_stats(),
            "process": monitor.process_stats(),
            "jvm": monitor.runtime_stats(),   # python runtime, jvm-shaped
            "fs": monitor.fs_stats([self.data_path] if self.data_path
                                   else []),
            "accelerator": monitor.device_stats(),
            "thread_pool": self.thread_pool.stats(),
            "breakers": _breaker_stats(),
            # fused score+top-k autotuner choices + block-prune counters
            # (process-wide: the executor serves every index on the node)
            "fused_scoring": fused_scoring_stats(),
            # dispatch scheduler: cross-request coalescing + pipelining
            # counters (search/dispatch.py)
            "dispatch": self._dispatch.stats.snapshot(),
            # deterministic fault injection (utils/faults.py): active
            # rules + per-rule firing counts, so chaos runs are auditable
            "fault_injection": _fault_snapshot(),
            # device-parallel pack builder (index/devbuild.py):
            # device/fallback/skip counters + derived ingest docs/sec
            # (process-wide — the builder serves every index on the node)
            "indexing": {"device_build": devbuild.stats()},
            "metrics": self.metrics.snapshot(),
        }}}

    # ref: action/admin/indices/stats/ (CommonStats sections, metric
    # selection in RestIndicesStatsAction, level in IndicesStatsResponse)
    _STATS_METRIC_MAP = {
        "docs": "docs", "store": "store", "indexing": "indexing",
        "get": "get", "search": "search", "merge": "merges",
        "refresh": "refresh", "flush": "flush", "warmer": "warmer",
        "filter_cache": "filter_cache", "id_cache": "id_cache",
        "fielddata": "fielddata", "percolate": "percolate",
        "completion": "completion", "segments": "segments",
        "translog": "translog", "suggest": "suggest",
        "recovery": "recovery", "query_cache": "query_cache",
    }

    def indices_stats(self, index: str | None = None,
                      metric: str | None = None,
                      level: str = "indices",
                      types: list[str] | None = None,
                      groups: list[str] | None = None,
                      fields: list[str] | None = None,
                      fielddata_fields: list[str] | None = None,
                      completion_fields: list[str] | None = None) -> dict:
        import fnmatch
        from .index.stats import merge_type_counters, merge_group_counters
        svcs = self._resolve(None if index in ("_all", "*") else index)

        def _match(name: str, pats: list[str]) -> bool:
            return any(fnmatch.fnmatch(name, p) for p in pats)

        def _field_sizes(svc_list) -> tuple[dict, dict]:
            """Per-field fielddata + completion sizes. Columns are loaded
            at segment birth here (columnar-at-refresh design), so every
            mapped column reports its resident bytes — the analog of
            fielddata memory (ref: FieldDataStats / CompletionStats)."""
            fd: dict[str, int] = {}
            comp: dict[str, int] = {}
            for svc in svc_list:
                for eng in svc.shards.values():
                    for seg in eng.segments:
                        cols = [*seg.keywords.values(),
                                *seg.numerics.values(),
                                *seg.vectors.values(),
                                *seg.geos.values()]
                        for col in cols:
                            fd[col.name] = fd.get(col.name, 0) + col.nbytes()
                        for pf in seg.text.values():
                            fd[pf.name] = fd.get(pf.name, 0) + pf.nbytes()
                        for cc in seg.completions.values():
                            comp[cc.name] = (comp.get(cc.name, 0)
                                             + cc.nbytes())
            return fd, comp

        def build(svc_list) -> dict:
            seg = [e.segment_stats() for svc in svc_list
                   for e in svc.shards.values()]
            ops = [svc.op_stats for svc in svc_list]
            fd_sizes, comp_sizes = _field_sizes(svc_list)
            tl_ops = tl_bytes = 0
            for svc in svc_list:
                for eng in svc.shards.values():
                    if eng.translog is not None:
                        # properties, not methods — calling them was a
                        # TypeError on every path-backed _stats call
                        tl_ops += eng.translog.num_ops
                        tl_bytes += eng.translog.size_in_bytes
            # pack-build wall time + docs (refresh rebuilds and
            # compaction folds) so indexing throughput is observable
            build_ms = sum(o.build_time_ms for o in ops)
            build_docs = sum(o.build_docs for o in ops)
            full: dict = {
                "docs": {"count": sum(s.doc_count() for s in svc_list),
                         "deleted": 0},
                "store": {"size_in_bytes":
                          sum(s["memory_in_bytes"] for s in seg),
                          "throttle_time_in_millis": 0},
                "indexing": {
                    "index_total": sum(o.index_total for o in ops),
                    "index_time_in_millis":
                        sum(o.index_time_ms for o in ops),
                    "index_current": 0,
                    "delete_total": sum(o.delete_total for o in ops),
                    "delete_time_in_millis":
                        sum(o.delete_time_ms for o in ops),
                    "delete_current": 0,
                    "noop_update_total":
                        sum(o.noop_update_total for o in ops),
                    "build_total": sum(o.build_total for o in ops),
                    "build_time_in_millis": build_ms,
                    "build_docs": build_docs,
                    "build_docs_per_s":
                        (build_docs / (build_ms / 1000.0)
                         if build_ms > 0 else 0.0),
                    "device_build_total":
                        sum(o.build_device_total for o in ops),
                    "is_throttled": False,
                    "throttle_time_in_millis": 0},
                "get": {"total": sum(o.get_total for o in ops),
                        "time_in_millis": sum(o.get_time_ms for o in ops),
                        "exists_total": sum(o.get_exists for o in ops),
                        "exists_time_in_millis": 0,
                        "missing_total": sum(o.get_missing for o in ops),
                        "missing_time_in_millis": 0, "current": 0},
                "search": {"open_contexts": len(self._scrolls),
                           "query_total": sum(o.query_total for o in ops),
                           "query_time_in_millis":
                               sum(o.query_time_ms for o in ops),
                           "query_current": 0,
                           "fetch_total": sum(o.fetch_total for o in ops),
                           "fetch_time_in_millis":
                               sum(o.fetch_time_ms for o in ops),
                           "fetch_current": 0},
                "merges": {"current": 0, "current_docs": 0,
                           "current_size_in_bytes": 0,
                           "total": sum(o.merge_total for o in ops),
                           "total_time_in_millis":
                               sum(o.merge_time_ms for o in ops),
                           "total_docs": 0, "total_size_in_bytes": 0},
                "refresh": {"total": sum(o.refresh_total for o in ops),
                            "total_time_in_millis":
                                sum(o.refresh_time_ms for o in ops)},
                "flush": {"total": sum(o.flush_total for o in ops),
                          "total_time_in_millis":
                              sum(o.flush_time_ms for o in ops)},
                "warmer": {"current": 0,
                           "total": sum(o.warmer_total for o in ops),
                           "total_time_in_millis":
                               sum(o.warmer_time_ms for o in ops)},
                "filter_cache": {"memory_size_in_bytes": 0, "evictions": 0},
                "query_cache": {
                    "memory_size_in_bytes":
                        sum(s.request_cache.memory_size_in_bytes()
                            for s in svc_list),
                    "evictions": sum(s.request_cache.evictions
                                     for s in svc_list),
                    "hit_count": sum(s.request_cache.hit_count
                                     for s in svc_list),
                    "miss_count": sum(s.request_cache.miss_count
                                      for s in svc_list)},
                "id_cache": {"memory_size_in_bytes": 0},
                "fielddata": {"memory_size_in_bytes":
                              sum(fd_sizes.values()),
                              "evictions": 0},
                "percolate": {"total":
                              sum(o.percolate_total for o in ops),
                              "time_in_millis":
                              sum(o.percolate_time_ms for o in ops),
                              "current": 0, "memory_size_in_bytes": -1,
                              "memory_size": "-1b",
                              "queries": sum(svc.percolator.count()
                                             for svc in svc_list)},
                "completion": {"size_in_bytes":
                               sum(comp_sizes.values())},
                "segments": {"count": sum(s["count"] for s in seg),
                             "memory_in_bytes":
                             sum(s["memory_in_bytes"] for s in seg),
                             "index_writer_memory_in_bytes": 0,
                             "version_map_memory_in_bytes": 0,
                             "fixed_bit_set_memory_in_bytes": 0},
                "translog": {"operations": tl_ops,
                             "size_in_bytes": tl_bytes},
                "suggest": {"total": sum(o.suggest_total for o in ops),
                            "time_in_millis":
                                sum(o.suggest_time_ms for o in ops),
                            "current": 0},
                "recovery": {"current_as_source": 0,
                             "current_as_target": 0,
                             "throttle_time_in_millis": 0},
            }
            # per-field sections, selected by fields/…_fields patterns
            # (ref: CommonStatsFlags fieldDataFields/completionDataFields)
            fd_pats = list(fielddata_fields or []) + list(fields or [])
            if fd_pats:
                sel = {f: {"memory_size_in_bytes": sz}
                       for f, sz in fd_sizes.items() if _match(f, fd_pats)}
                if sel:
                    full["fielddata"]["fields"] = sel
            comp_pats = list(completion_fields or []) + list(fields or [])
            if comp_pats:
                sel = {f: {"size_in_bytes": sz}
                       for f, sz in comp_sizes.items()
                       if _match(f, comp_pats)}
                if sel:
                    full["completion"]["fields"] = sel
            if types:
                matched_types = {
                    t: row for t, row in merge_type_counters(
                        [o.types for o in ops]).items()
                    if _match(t, types)}
                if matched_types:
                    full["indexing"]["types"] = matched_types
            if groups:
                matched = {g: row for g, row in merge_group_counters(
                    [o.groups for o in ops]).items()
                    if _match(g, groups)}
                if matched:
                    full["search"]["groups"] = matched
            if metric in (None, "_all"):
                return full
            keep = {self._STATS_METRIC_MAP.get(m.strip())
                    for m in str(metric).split(",")}
            return {k: v for k, v in full.items() if k in keep}

        total = sum(s.num_shards * (1 + s.num_replicas) for s in svcs)
        ok = sum(s.num_shards for s in svcs)
        all_stats = build(svcs)
        out: dict = {
            "_shards": {"total": total, "successful": ok, "failed": 0},
            "_all": {"primaries": all_stats, "total": all_stats},
        }
        if level in ("indices", "shards"):
            out["indices"] = {}
            for svc in svcs:
                st = build([svc])
                entry = {"primaries": st, "total": st}
                if level == "shards":
                    entry["shards"] = {
                        str(sid): [build([svc])]
                        for sid in svc.shards}
                out["indices"][svc.name] = entry
        return out

    def hot_threads(self, threads: int = 3, interval_ms: int = 500) -> str:
        from .utils import monitor
        return (f"::: [{self.name}]\n"
                + monitor.hot_threads(threads, interval_ms))

    # -- term vectors (ref: action/termvectors/) ---------------------------
    def term_vectors(self, index: str, doc_id: str,
                     body: dict | None = None,
                     fields: list[str] | None = None) -> dict:
        from .search.termvectors import term_vectors as tv
        body = body or {}
        fields = fields or body.get("fields")
        svc = self._index(index)
        out = {"_index": svc.name, "_type": "_doc", "_id": doc_id,
               "found": False}
        for attempt in (0, 1):
            for eng in svc.shards.values():
                reader = eng.acquire_searcher()
                result = tv(reader.segments, reader.live, doc_id,
                            fields=fields,
                            term_statistics=bool(
                                body.get("term_statistics", False)),
                            field_statistics=bool(
                                body.get("field_statistics", True)),
                            positions=bool(body.get("positions", True)),
                            offsets=bool(body.get("offsets", True)),
                            analyzer_for=(
                                lambda f: svc.mappers.analysis.analyzer(
                                    getattr(svc.mappers.field(f),
                                            "analyzer", "standard")
                                    if svc.mappers.field(f) is not None
                                    else "standard")))
                if result is not None:
                    out["found"] = True
                    out["term_vectors"] = result
                    return out
            # realtime semantics: un-refreshed docs become visible after a
            # refresh (ref: ShardTermVectorsService realtime get)
            if attempt == 0 and body.get("realtime", True) is not False:
                try:
                    if svc.get_doc(doc_id).get("found"):
                        svc.refresh()
                        continue
                except ElasticsearchTpuError:
                    pass
            break
        return out

    def mtermvectors(self, index: str | None, body: dict | None) -> dict:
        docs = (body or {}).get("docs") or []
        out = []
        for spec in docs:
            idx = spec.get("_index") or index
            did = spec.get("_id")
            try:
                out.append(self.term_vectors(idx, did, spec,
                                             spec.get("fields")))
            except ElasticsearchTpuError as e:
                out.append({"_index": idx, "_id": did, "error": str(e)})
        return {"docs": out}

    # -- search templates (ref: RestSearchTemplateAction + the Mustache
    # script engine) -------------------------------------------------------
    def search_template(self, index: str | None, body: dict | None) -> dict:
        rendered = self.render_template(body)["template_output"]
        return self.search(index, rendered)

    def render_template(self, body: dict | None) -> dict:
        from .search.templates import render_template
        body = body or {}
        template = body.get("inline") or body.get("template")
        tid = body.get("id")
        # {"template": {"id": "1"}} indirection (ref:
        # TemplateQueryParser stored-template reference)
        if isinstance(template, dict) and template.get("id") \
                and set(template) <= {"id", "params"}:
            tid = template["id"]
            template = None
        if isinstance(template, str) and not template.lstrip(
                ).startswith("{"):
            # a bare name is a disk/indexed script reference (ref:
            # ScriptService file-script lookup error)
            tid, template = template, None
        if template is None and tid is not None:
            from .script import ScriptService
            stored = ScriptService.instance().stored
            template = stored.get(f"__template__{tid}",
                                  stored.get(str(tid)))
            if template is None:
                raise IllegalArgumentError(
                    f"Unable to find on disk script {tid}")
        if template is None:
            raise IllegalArgumentError(
                "search template requires [inline], [template] or [id]")
        return {"template_output": render_template(template,
                                                   body.get("params") or {})}

    def close(self) -> None:
        self._ttl_stop.set()
        if getattr(self, "_process_stats", None) is not None:
            # reset the process-wide failover/eviction counters this
            # node installed — unless a later node installed its own,
            # in which case theirs stands (fault-registry convention)
            from .search import dispatch as _dispatch_mod
            _dispatch_mod.reset_process_stats(
                if_owner=self._process_stats)
            self._process_stats = None
        if getattr(self, "_durability_stats", None) is not None:
            from .index import durability as _durability_mod
            _durability_mod.reset_process_stats(
                if_owner=self._durability_stats)
            self._durability_stats = None
        if getattr(self, "_eviction_cfg", None) is not None:
            # restore eviction defaults only while the installed config
            # is still this node's (a later node's settings stand)
            from .parallel import repack as _repack
            _repack.reset_config(if_current=self._eviction_cfg)
            self._eviction_cfg = None
        if getattr(self, "_tiering_cfg", None) is not None:
            # tiered-residency config + paged tiles: reset only while
            # the installed config is still THIS node's (a later
            # node's settings — and its paged tiles — stand)
            from .index import tiering as _tiering
            _tiering.reset(if_current=self._tiering_cfg)
            self._tiering_cfg = None
        if getattr(self, "_ann_cfg", None) is not None:
            # IVF config: reset only while the installed config is
            # still THIS node's (a later node's settings stand)
            from .index import ann as _ann
            _ann.reset(if_current=self._ann_cfg)
            self._ann_cfg = None
        if getattr(self, "_fault_registry", None) is not None:
            # tear down the fault registry this node installed — unless
            # someone re-configured since, in which case theirs stands
            from .utils import faults
            if faults.active() is self._fault_registry:
                faults.clear()
        self.resource_watcher.close()
        w = getattr(self, "_script_watcher", None)
        if w is not None:
            self.resource_watcher.remove(w)
            self._script_watcher = None
        # persist mappings learned dynamically, then close engines
        for svc in self.indices.values():
            if self.data_path:
                self._persist_index_meta(svc, {
                    "index.number_of_shards": svc.num_shards})
            svc.close()
        self.thread_pool.shutdown()
        if getattr(self, "_autotune_store", None):
            # stop writing autotuner choices into this node's data dir
            # once the node (and its lock) are gone — but only if THIS
            # node owns the process-global store
            from .search.executor import configure_autotune_persistence
            configure_autotune_persistence(None,
                                           if_owner=self._autotune_store)
        if self._node_lock_fh is not None:
            import fcntl
            try:
                fcntl.flock(self._node_lock_fh, fcntl.LOCK_UN)
            finally:
                self._node_lock_fh.close()
                self._node_lock_fh = None


def _breaker_stats() -> dict:
    """Node-stats breakers section (ref: CircuitBreakerStats). The
    fielddata entry additionally splits its estimate into the tiered-
    residency components: permanently-resident tile summaries (part of
    the ordinary column upload hold) vs paged tile bytes (per-tile LRU
    holds, index/tiering.py)."""
    from .utils.breaker import breaker_service
    out = breaker_service().stats()
    from .index import tiering as _tiering
    fd = out.get("fielddata")
    if fd is not None:
        fd["tiering"] = _tiering.breaker_split()
    return out


def _fault_snapshot() -> dict:
    from .utils import faults
    return faults.snapshot()


def _durability_snapshot() -> dict:
    from .index import durability
    return durability.snapshot()


def _legacy_error_string(e: ElasticsearchTpuError) -> str:
    """ES 2.0 wire format for embedded error strings:
    `IndexMissingException[[idx] missing]` (ref: ElasticsearchException
    toString rendering used in multi-item responses)."""
    if isinstance(e, IndexNotFoundError):
        return f"IndexMissingException[[{e.index}] missing]"
    return f"{type(e).__name__}[{e}]"


def _deep_merge(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
