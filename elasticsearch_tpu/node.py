"""Node: the composition root tying indices, search fan-out, and APIs.

Reference analog: node/Node.java (builds the module graph :166-200,
starts services :230-273) — but composition is plain Python. One Node
owns an IndicesService-equivalent registry and exposes the operations the
action layer (action/) implements in the reference: index/bulk/get/
delete/search/count/admin. The distributed fan-out across shards of one
process mirrors TransportSearchAction's QUERY_THEN_FETCH flow with the
SearchPhaseController merge (host path); multi-chip execution of the
same search is parallel/distributed.py.
"""

from __future__ import annotations

import json
import os
import time

from .utils.settings import Settings, parse_time_value as _parse_time_value
from .utils.errors import (IndexNotFoundError, IndexAlreadyExistsError,
                           ElasticsearchTpuError, IllegalArgumentError)
from .utils.metrics import MetricsRegistry
from .index.index_service import IndexService
from .search.controller import merge_shard_results
from .search.aggregations import parse_aggs
from .search.suggest import parse_suggest, merge_suggests
from .search.shard_searcher import ShardReader


def parse_time_value(v, default_ms: int = 60_000) -> int:
    """'5m' / '30s' -> millis; wraps the shared helper with the API error
    type (ref: common/unit/TimeValue)."""
    try:
        return _parse_time_value(v, default_ms)
    except ValueError as e:
        raise IllegalArgumentError(str(e))


class Node:
    def __init__(self, settings: Settings | dict | None = None):
        self.settings = (settings if isinstance(settings, Settings)
                         else Settings(settings or {}))
        self.name = self.settings.get_str("node.name", "node-0")
        self.cluster_name = self.settings.get_str("cluster.name",
                                                  "elasticsearch-tpu")
        self.data_path = self.settings.get_str("path.data")
        if self.data_path:
            os.makedirs(self.data_path, exist_ok=True)
        self.indices: dict[str, IndexService] = {}
        self.metrics = MetricsRegistry()
        self._started_at = time.time()
        # scroll contexts: id -> {"readers", "body", "pos", "expires_at"}
        # (ref: SearchService.activeContexts :138 + keepalive reaper :168)
        self._scrolls: dict[str, dict] = {}
        from .snapshots import SnapshotsService
        self.snapshots = SnapshotsService(self)
        if self.data_path:
            self._load_existing_indices()

    # -- index admin (ref: MetaDataCreateIndexService etc.) ----------------
    def create_index(self, name: str, settings: dict | None = None,
                     mappings: dict | None = None) -> dict:
        if name in self.indices:
            raise IndexAlreadyExistsError(name)
        if not name or name != name.lower() or name.startswith(("_", "-", "+")):
            raise IllegalArgumentError(f"invalid index name [{name}]")
        idx_settings = self.settings.merged_with(settings or {})
        mapping = None
        if mappings:
            # accept both {"properties": ...} and {"<type>": {"properties"...}}
            if "properties" in mappings or not mappings:
                mapping = mappings
            else:
                mapping = next(iter(mappings.values()))
        svc = IndexService(name, idx_settings, mapping, data_path=self.data_path)
        self.indices[name] = svc
        if self.data_path:
            self._persist_index_meta(svc, settings or {})
        return {"acknowledged": True, "index": name}

    def delete_index(self, name: str) -> dict:
        svc = self._index(name)
        svc.close()
        del self.indices[name]
        if self.data_path:
            import shutil
            shutil.rmtree(os.path.join(self.data_path, name), ignore_errors=True)
        return {"acknowledged": True}

    def _index(self, name: str) -> IndexService:
        svc = self.indices.get(name)
        if svc is None:
            raise IndexNotFoundError(name)
        return svc

    def _resolve(self, names: str | None) -> list[IndexService]:
        """Index name resolution incl. _all and comma lists (ref:
        cluster/metadata/IndexNameExpressionResolver)."""
        if names in (None, "_all", "*", ""):
            return list(self.indices.values())
        out = []
        for n in str(names).split(","):
            n = n.strip()
            if "*" in n:
                import fnmatch
                matched = [self.indices[k] for k in sorted(self.indices)
                           if fnmatch.fnmatch(k, n)]
                out.extend(matched)
            else:
                out.append(self._index(n))
        return out

    def _ensure_index(self, name: str) -> IndexService:
        """Auto-create on first write (ref: TransportBulkAction auto-create)."""
        if name not in self.indices:
            if not self.settings.get_bool("action.auto_create_index", True):
                raise IndexNotFoundError(name)
            self.create_index(name)
        return self.indices[name]

    # -- document APIs -----------------------------------------------------
    def index_doc(self, index: str, doc_id: str | None, body,
                  version: int | None = None, routing: str | None = None,
                  refresh: bool = False) -> dict:
        svc = self._ensure_index(index)
        if doc_id is None:
            import uuid
            doc_id = uuid.uuid4().hex[:20]
        r = svc.index_doc(doc_id, body, version, routing)
        if refresh:
            svc.refresh()
        self.metrics.counter("indexing.index_total").inc()
        return r

    def get_doc(self, index: str, doc_id: str, routing: str | None = None) -> dict:
        return self._index(index).get_doc(doc_id, routing)

    def delete_doc(self, index: str, doc_id: str, version: int | None = None,
                   routing: str | None = None, refresh: bool = False) -> dict:
        svc = self._index(index)
        r = svc.delete_doc(doc_id, version, routing)
        if refresh:
            svc.refresh()
        return r

    def update_doc(self, index: str, doc_id: str, body: dict,
                   refresh: bool = False) -> dict:
        """Partial update via doc merge (ref: TransportUpdateAction's
        get+merge+index loop; script updates land with the script module)."""
        svc = self._index(index)
        current = svc.get_doc(doc_id)
        src = json.loads(current["_source"])
        doc_part = body.get("doc")
        if doc_part is None:
            raise IllegalArgumentError("update requires [doc]")
        _deep_merge(src, doc_part)
        r = svc.index_doc(doc_id, src, version=current["_version"])
        if refresh:
            svc.refresh()
        return r

    def bulk(self, operations: list[tuple[str, dict]], refresh: bool = False) -> dict:
        """operations: [(action, payload)] where action in index/create/
        delete/update; payload carries _index/_id/doc. Ref:
        TransportBulkAction.executeBulk grouping by shard."""
        started = time.monotonic()
        items = []
        errors = False
        touched: set[str] = set()
        for action, payload in operations:
            try:
                idx = payload["_index"]
                if action in ("index", "create"):
                    r = self.index_doc(idx, payload.get("_id"), payload["doc"])
                    touched.add(idx)
                    items.append({action: {**r, "status": 201 if r.get("created")
                                           else 200}})
                elif action == "delete":
                    r = self.delete_doc(idx, payload["_id"])
                    touched.add(idx)
                    items.append({"delete": {**r, "status": 200 if r.get("found")
                                             else 404}})
                elif action == "update":
                    r = self.update_doc(idx, payload["_id"], payload["doc"])
                    touched.add(idx)
                    items.append({"update": {**r, "status": 200}})
                else:
                    raise IllegalArgumentError(f"unknown bulk action [{action}]")
            except ElasticsearchTpuError as e:
                errors = True
                items.append({action: {"error": e.to_dict(), "status": e.status}})
        if refresh:
            for idx in touched:
                self.indices[idx].refresh()
        return {"took": int((time.monotonic() - started) * 1000),
                "errors": errors, "items": items}

    # -- search (ref: TransportSearchAction QUERY_THEN_FETCH) --------------
    def search(self, index: str | None, body: dict | None = None,
               scroll: str | None = None) -> dict:
        body = body or {}
        services = self._resolve(index)
        shard_readers: list[tuple[str, ShardReader]] = []
        for svc in services:
            for eng in svc.shards.values():
                shard_readers.append((svc.name, eng.acquire_searcher()))
        result = self._execute_on_readers(shard_readers, body)
        if scroll is not None:
            import uuid
            scroll_id = uuid.uuid4().hex
            self._reap_scrolls()
            self._scrolls[scroll_id] = {
                "readers": shard_readers, "body": dict(body),
                "pos": int(body.get("from", 0)) + int(body.get("size", 10)),
                "keepalive_ms": parse_time_value(scroll, 60_000),
                "expires_at": time.time()
                + parse_time_value(scroll, 60_000) / 1000.0,
            }
            result["_scroll_id"] = scroll_id
        return result

    def scroll(self, scroll_id: str, scroll: str | None = None) -> dict:
        """Next page over the stored point-in-time readers (ref:
        TransportSearchScrollAction + SearchService keepalive)."""
        self._reap_scrolls()
        ctx = self._scrolls.get(scroll_id)
        if ctx is None:
            err = ElasticsearchTpuError(f"No search context found for id [{scroll_id}]")
            err.status = 404
            raise err
        body = dict(ctx["body"])
        size = int(body.get("size", 10))
        body["from"] = ctx["pos"]
        ctx["pos"] += size
        if scroll is not None:
            ctx["keepalive_ms"] = parse_time_value(scroll, 60_000)
        ctx["expires_at"] = time.time() + ctx["keepalive_ms"] / 1000.0
        result = self._execute_on_readers(ctx["readers"], body)
        result["_scroll_id"] = scroll_id
        return result

    def clear_scroll(self, scroll_ids: list[str] | None = None) -> dict:
        if scroll_ids is None or scroll_ids == ["_all"]:
            n = len(self._scrolls)
            self._scrolls.clear()
        else:
            n = 0
            for sid in scroll_ids:
                if self._scrolls.pop(sid, None) is not None:
                    n += 1
        return {"succeeded": True, "num_freed": n}

    def _reap_scrolls(self) -> None:
        now = time.time()
        for sid in [s for s, c in self._scrolls.items()
                    if c["expires_at"] < now]:
            del self._scrolls[sid]

    def _execute_on_readers(self, shard_readers: list[tuple[str, ShardReader]],
                            body: dict) -> dict:
        if not shard_readers:
            # zero shards: empty result (ref: empty SearchResponse)
            return merge_shard_results([], [], [], 0,
                                       int(body.get("size", 10)))
        agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        suggest_specs = parse_suggest(body.get("suggest"))
        frm = int(body.get("from", 0))
        size = int(body.get("size", 10))
        # each shard computes the full from+size window (ref: sortDocs)
        shard_body = dict(body)
        shard_body["from"] = 0
        shard_body["size"] = frm + size
        responses = []
        partials = []
        suggest_parts = []
        for _, reader in shard_readers:
            r = reader.msearch([shard_body], with_partials=True)[0]
            partials.append(r.pop("_agg_partials", {}))
            if "suggest" in r:
                suggest_parts.append(r.pop("suggest"))
            responses.append(r)
        sort = body.get("sort")
        score_sort = sort in (None, [], "_score") or (
            isinstance(sort, list) and sort and sort[0] == "_score")
        descending = True
        if not score_sort:
            entry = sort[0] if isinstance(sort, list) else sort
            if isinstance(entry, dict):
                spec = next(iter(entry.values()))
                order = (spec.get("order", "asc") if isinstance(spec, dict)
                         else str(spec))
                descending = order.lower() == "desc"
            else:
                descending = False
        self.metrics.counter("search.query_total").inc()
        out = merge_shard_results(responses, agg_specs, partials,
                                  frm=frm, size=size, descending=descending,
                                  score_sort=score_sort)
        if suggest_specs:
            out["suggest"] = merge_suggests(suggest_parts, suggest_specs)
        return out

    def msearch(self, requests: list[tuple[str | None, dict]]) -> dict:
        return {"responses": [self.search(i, b) for i, b in requests]}

    def count(self, index: str | None, body: dict | None = None) -> dict:
        r = self.search(index, {"query": (body or {}).get("query"), "size": 0})
        return {"count": r["hits"]["total"], "_shards": r["_shards"]}

    # -- admin -------------------------------------------------------------
    def refresh(self, index: str | None = None) -> dict:
        svcs = self._resolve(index)
        for svc in svcs:
            svc.refresh()
        n = sum(len(s.shards) for s in svcs)
        return {"_shards": {"total": n, "successful": n, "failed": 0}}

    def flush(self, index: str | None = None) -> dict:
        svcs = self._resolve(index)
        for svc in svcs:
            svc.flush()
        n = sum(len(s.shards) for s in svcs)
        return {"_shards": {"total": n, "successful": n, "failed": 0}}

    def force_merge(self, index: str | None = None,
                    max_num_segments: int = 1) -> dict:
        for svc in self._resolve(index):
            svc.force_merge(max_num_segments)
        return {"acknowledged": True}

    def put_mapping(self, index: str, mapping: dict) -> dict:
        svc = self._index(index)
        if mapping and "properties" not in mapping and "dynamic" not in mapping:
            first = next(iter(mapping.values()), None)
            if isinstance(first, dict) and ("properties" in first
                                            or "dynamic" in first):
                mapping = first
        svc.mappers.merge_mapping(mapping)
        return {"acknowledged": True}

    def get_mapping(self, index: str | None = None) -> dict:
        return {svc.name: {"mappings": {"_doc": svc.mappers.mapping_dict()}}
                for svc in self._resolve(index)}

    def get_settings(self, index: str | None = None) -> dict:
        return {svc.name: {"settings": {
            "index": {"number_of_shards": svc.num_shards,
                      "number_of_replicas": svc.num_replicas}}}
            for svc in self._resolve(index)}

    def cluster_health(self) -> dict:
        shards = sum(len(s.shards) for s in self.indices.values())
        return {
            "cluster_name": self.cluster_name,
            "status": "green",
            "timed_out": False,
            "number_of_nodes": 1,
            "number_of_data_nodes": 1,
            "active_primary_shards": shards,
            "active_shards": shards,
            "relocating_shards": 0,
            "initializing_shards": 0,
            "unassigned_shards": 0,
        }

    def stats(self) -> dict:
        return {
            "cluster_name": self.cluster_name,
            "indices": {name: svc.stats() for name, svc in self.indices.items()},
            "metrics": self.metrics.snapshot(),
        }

    def cat_indices(self) -> list[dict]:
        out = []
        for name, svc in sorted(self.indices.items()):
            out.append({"health": "green", "status": "open", "index": name,
                        "pri": svc.num_shards, "rep": svc.num_replicas,
                        "docs.count": svc.doc_count()})
        return out

    # -- persistence of index metadata (gateway analog) --------------------
    def _persist_index_meta(self, svc: IndexService, settings: dict) -> None:
        meta = {"settings": settings,
                "mappings": svc.mappers.mapping_dict()}
        path = os.path.join(self.data_path, svc.name, "_meta.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    def _load_existing_indices(self) -> None:
        for name in sorted(os.listdir(self.data_path)):
            meta_path = os.path.join(self.data_path, name, "_meta.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = json.load(f)
                svc = IndexService(name, self.settings.merged_with(
                    meta.get("settings") or {}), meta.get("mappings"),
                    data_path=self.data_path)
                self.indices[name] = svc

    def close(self) -> None:
        # persist mappings learned dynamically, then close engines
        for svc in self.indices.values():
            if self.data_path:
                self._persist_index_meta(svc, {
                    "index.number_of_shards": svc.num_shards})
            svc.close()


def _deep_merge(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
