"""Python client for the REST API.

Reference analog: the Java Client interface + TransportClient
(client/transport/TransportClient.java with node round-robin). HTTP-based
(like every post-2.x ES client); round-robins over configured hosts and
fails over on connection errors.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .utils.errors import ElasticsearchTpuError


class TransportError(ElasticsearchTpuError):
    status = 503


class Client:
    def __init__(self, hosts: list[str] | str = "http://127.0.0.1:9200",
                 timeout: float = 30.0):
        self.hosts = [hosts] if isinstance(hosts, str) else list(hosts)
        self.timeout = timeout
        self._rr = 0

    # -- transport ---------------------------------------------------------
    def perform(self, method: str, path: str, body=None, params: dict | None = None):
        if params:
            from urllib.parse import urlencode
            path = f"{path}?{urlencode(params)}"
        if isinstance(body, (list, tuple)):  # ndjson (bulk/msearch)
            data = ("\n".join(json.dumps(x) for x in body) + "\n").encode()
            ctype = "application/x-ndjson"
        elif body is not None:
            data = json.dumps(body).encode()
            ctype = "application/json"
        else:
            data, ctype = None, "application/json"
        last_err: Exception | None = None
        for _ in range(len(self.hosts)):
            host = self.hosts[self._rr % len(self.hosts)]
            self._rr += 1
            req = urllib.request.Request(
                f"{host}{path}", data=data, method=method,
                headers={"Content-Type": ctype})
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                payload = e.read()
                try:
                    err = json.loads(payload)
                except json.JSONDecodeError:
                    err = {"error": {"reason": payload.decode(errors="replace")}}
                exc = ElasticsearchTpuError(
                    err.get("error", {}).get("reason", str(e)))
                exc.status = e.code
                exc.info = err.get("error", {})
                raise exc from None
            except (urllib.error.URLError, OSError) as e:
                last_err = e
                continue
        raise TransportError(f"no node reachable: {last_err}")

    # -- API (mirrors the reference Client interface) ----------------------
    def info(self):
        return self.perform("GET", "/")

    def cluster_health(self):
        return self.perform("GET", "/_cluster/health")

    def create_index(self, index: str, settings: dict | None = None,
                     mappings: dict | None = None):
        body = {}
        if settings:
            body["settings"] = settings
        if mappings:
            body["mappings"] = mappings
        return self.perform("PUT", f"/{index}", body or None)

    def delete_index(self, index: str):
        return self.perform("DELETE", f"/{index}")

    def index(self, index: str, body: dict, id: str | None = None,
              refresh: bool = False, **params):
        if refresh:
            params["refresh"] = "true"
        if id is None:
            return self.perform("POST", f"/{index}/_doc", body, params)
        return self.perform("PUT", f"/{index}/_doc/{id}", body, params)

    def get(self, index: str, id: str):
        return self.perform("GET", f"/{index}/_doc/{id}")

    def delete(self, index: str, id: str, refresh: bool = False, **params):
        if refresh:
            params["refresh"] = "true"
        return self.perform("DELETE", f"/{index}/_doc/{id}", None, params)

    def update(self, index: str, id: str, body: dict, refresh: bool = False):
        return self.perform("POST", f"/{index}/_update/{id}", body,
                            {"refresh": "true"} if refresh else None)

    def bulk(self, operations: list[dict], refresh: bool = False):
        return self.perform("POST", "/_bulk", operations,
                            {"refresh": "true"} if refresh else None)

    def search(self, index: str | None = None, body: dict | None = None,
               **params):
        path = f"/{index}/_search" if index else "/_search"
        return self.perform("POST", path, body or {}, params or None)

    def msearch(self, requests: list[tuple[str | None, dict]]):
        lines: list[dict] = []
        for index, body in requests:
            lines.append({"index": index} if index else {})
            lines.append(body)
        return self.perform("POST", "/_msearch", lines)

    def count(self, index: str | None = None, body: dict | None = None):
        path = f"/{index}/_count" if index else "/_count"
        return self.perform("POST", path, body)

    def refresh(self, index: str | None = None):
        return self.perform("POST", f"/{index}/_refresh" if index else "/_refresh")

    def flush(self, index: str | None = None):
        return self.perform("POST", f"/{index}/_flush" if index else "/_flush")

    def put_mapping(self, index: str, mapping: dict):
        return self.perform("PUT", f"/{index}/_mapping", mapping)

    def get_mapping(self, index: str | None = None):
        return self.perform("GET", f"/{index}/_mapping" if index else "/_mapping")

    def cat_indices(self):
        # _cat speaks aligned text by default; ask for json explicitly
        return self.perform("GET", "/_cat/indices", None, {"format": "json"})
