"""Percolator: reverse search — registered queries run against a document.

Reference analog: percolator/PercolatorService.java:88-153 — queries are
stored under the `.percolator` type of an index; a percolate request
builds an in-memory MemoryIndex of the incoming doc and executes every
registered query against it.

TPU-native twist: the incoming doc becomes a one-doc columnar segment and
ALL registered queries run through the batched executor in one shot —
structurally-identical queries (the common case: thousands of term/match
alert queries) collapse into a single device program with leading dim B,
so percolation cost is one scatter-add pass, not Q sequential searches.
"""

from __future__ import annotations

import json
import os
import threading


class PercolatorRegistry:
    """Registered percolation queries of one index, persisted as a JSON
    sidecar under the shard data path (the reference persists them as
    ordinary docs in the index itself; a sidecar keeps the columnar
    segments free of query blobs)."""

    def __init__(self, data_path: str | None = None):
        self._queries: dict[str, dict] = {}
        self._lock = threading.Lock()
        # per-query required-term clauses, computed once per
        # registration (the reference extracts query terms at percolator
        # -doc index time too); keyed by query id, dropped wholesale
        # when the mapping signature changes (analyzers may differ)
        self._clauses: dict[str, list] = {}
        self._clause_sig: str | None = None
        self._path = (os.path.join(data_path, "percolator.json")
                      if data_path else None)
        if self._path and os.path.exists(self._path):
            with open(self._path) as f:
                self._queries = json.load(f)

    def clauses_for(self, query_id: str, body: dict, scratch,
                    mapping_sig: str) -> list:
        with self._lock:
            if mapping_sig != self._clause_sig:
                self._clauses = {}
                self._clause_sig = mapping_sig
            hit = self._clauses.get(query_id)
            if hit is not None:
                return hit
        clauses = _required_clauses(body.get("query") or {}, scratch)
        with self._lock:
            if mapping_sig == self._clause_sig:
                self._clauses[query_id] = clauses
        return clauses

    def register(self, query_id: str, body: dict) -> dict:
        if not isinstance(body, dict) or "query" not in body:
            from .utils.errors import IllegalArgumentError
            raise IllegalArgumentError(
                "percolator document requires a [query] field")
        with self._lock:
            created = query_id not in self._queries
            self._queries[query_id] = body
            self._clauses.pop(query_id, None)  # re-extract on next use
            self._persist()
        return {"created": created}

    def unregister(self, query_id: str) -> bool:
        with self._lock:
            found = self._queries.pop(query_id, None) is not None
            self._clauses.pop(query_id, None)
            if found:
                self._persist()
        return found

    def get(self, query_id: str) -> dict | None:
        with self._lock:
            return self._queries.get(query_id)

    def count(self) -> int:
        with self._lock:
            return len(self._queries)

    def items(self) -> list[tuple[str, dict]]:
        with self._lock:   # snapshot: register/unregister run concurrently
            return sorted(self._queries.items())

    def _persist(self) -> None:
        if self._path is None:
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._queries, f)
        os.replace(tmp, self._path)


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _required_clauses(query, scratch) -> list[set[tuple[str, str]]]:
    """Conservative CNF of terms a query NEEDS in the doc to possibly
    match: each clause is an any-of set of (field, token); a query whose
    clause has no token present in the document cannot match and is
    pruned before execution (ref: the reference's percolator runs
    queries against a one-doc MemoryIndex — its cheap reject IS term
    absence; modern ES extracts query terms the same way). Unknown
    query shapes and non-string fields yield no clauses (never prune)."""

    def text_field(f) -> bool:
        fm = scratch.field(f)
        return fm is not None and getattr(fm, "type", None) in (
            "text", "string", "keyword")

    q = query
    if not isinstance(q, dict) or len(q) != 1:
        return []
    kind, body = next(iter(q.items()))
    if kind == "term" and isinstance(body, dict) and body:
        f, v = next(iter(body.items()))
        if isinstance(v, dict):
            v = v.get("value")
        return [{(f, str(v))}] if text_field(f) else []
    if kind in ("match", "match_phrase") and isinstance(body, dict) \
            and body:
        f, v = next(iter(body.items()))
        operator = "or"
        mtype = "boolean"
        if isinstance(v, dict):
            operator = str(v.get("operator", "or")).lower()
            mtype = str(v.get("type", "boolean")).lower()
            v = v.get("query")
        if not text_field(f):
            return []
        try:
            toks = scratch.search_analyzer_for(f).analyze(str(v))
        except Exception:  # noqa: BLE001 — unanalyzable: no pruning
            return []
        if not toks:
            return []
        if mtype == "phrase_prefix":
            # the trailing token matches by PREFIX — it is not an exact
            # required term; only the leading tokens are
            toks = toks[:-1]
            if not toks:
                return []
            return [{(f, t)} for t in toks]
        if kind == "match_phrase" or mtype == "phrase" \
                or operator == "and":
            return [{(f, t)} for t in toks]
        return [{(f, t) for t in toks}]
    if kind == "bool" and isinstance(body, dict):
        clauses: list[set[tuple[str, str]]] = []
        for grp in ("must", "filter"):
            for sub in _as_list(body.get(grp)):
                clauses.extend(_required_clauses(sub, scratch))
        return clauses
    if kind == "constant_score" and isinstance(body, dict):
        return _required_clauses(body.get("filter")
                                 or body.get("query") or {}, scratch)
    return []


def _doc_terms(seg) -> set[tuple[str, str]]:
    present: set[tuple[str, str]] = set()
    for f, pf in seg.text.items():
        for t in pf.terms:
            present.add((f, t))
    for f, kc in seg.keywords.items():
        for t in kc.terms:
            present.add((f, t))
    return present


def percolate(registry: PercolatorRegistry, mappers, index_name: str,
              doc: dict, percolate_filter: dict | None = None,
              size: int | None = None, index_settings=None) -> dict:
    """Run registered queries against one document.

    Ref: PercolatorService.percolate (:153) — the in-memory one-doc index
    + per-query match loop, here batched through the device executor.
    """
    from .index.segment import SegmentBuilder
    from .search.shard_searcher import ShardReader
    from .utils.errors import ElasticsearchTpuError

    from .utils.errors import IllegalArgumentError

    entries = registry.items()
    if percolate_filter is not None:
        # filter selects which registered queries to even try, by their
        # ids (ref: percolate request "filter" over .percolator docs) —
        # supported form: ids filter; anything else is rejected rather
        # than silently widened
        ids = (percolate_filter.get("ids") or {}).get("values")
        term = percolate_filter.get("term")
        if ids is not None:
            want = set(map(str, ids))
            entries = [(qid, q) for qid, q in entries if qid in want]
        elif isinstance(term, dict) and term:
            # term filter over the registered .percolator docs' metadata
            # fields (ref: PercolatorService percolate filter runs
            # against the percolator index docs, e.g. a "tag" field)
            fld, val = next(iter(term.items()))
            if isinstance(val, dict):
                val = val.get("value")
            entries = [(qid, q) for qid, q in entries
                       if isinstance(q, dict) and q.get(fld) == val]
        else:
            raise IllegalArgumentError(
                "percolate [filter] supports the ids and term filter "
                "forms")
    if not entries:
        return {"total": 0, "matches": []}

    # parse through a throwaway mapper so a percolated doc's dynamic
    # fields never leak into the index's live mapping (the reference's
    # MemoryIndex is equally ephemeral)
    from .index.mapping import MapperService
    from .utils.settings import Settings
    scratch = MapperService(index_settings or Settings.EMPTY,
                            mappers.mapping_dict())
    builder = SegmentBuilder()
    builder.add(scratch.parse("_percolate#doc", doc))
    seg = builder.build("percolate")
    reader = ShardReader(index_name, [seg], {}, scratch)

    # candidate pruning: a query whose required terms are absent from
    # the doc cannot match — with thousands of registered alert queries
    # only the handful sharing the doc's vocabulary reach the device.
    # Clauses come from the registry's per-registration cache, so the
    # per-call work is pure set intersection.
    present = _doc_terms(seg)
    mapping_sig = json.dumps(mappers.mapping_dict(), sort_keys=True,
                             default=str)
    pruned = []
    for qid, q in entries:
        clauses = registry.clauses_for(qid, q, scratch, mapping_sig)
        if all(clause & present for clause in clauses):
            pruned.append((qid, q))
    entries = pruned
    if not entries:
        return {"total": 0, "matches": []}

    bodies = [{"query": q.get("query"), "size": 0} for _, q in entries]
    matches = []
    # queries that fail to parse against this mapping simply don't match
    # (the reference logs and skips broken percolator queries)
    results: list[dict | None] = [None] * len(bodies)
    try:
        results = reader.msearch(bodies)
    except ElasticsearchTpuError:
        for i, b in enumerate(bodies):
            try:
                results[i] = reader.msearch([b])[0]
            except ElasticsearchTpuError:
                results[i] = None
    for (qid, _q), res in zip(entries, results):
        if res is not None and res["hits"]["total"] > 0:
            matches.append({"_index": index_name, "_id": qid})
    total = len(matches)
    if size is not None:
        matches = matches[:size]
    return {"total": total, "matches": matches}
