"""Snapshot/restore: content-addressed blob repository + snapshot service.

Reference analogs:
- repositories/Repository.java SPI + blobstore/BlobStoreRepository.java
  (679 LoC) over common/blobstore/ — here `FsRepository` is the fs
  implementation of the same blob-container idea.
- snapshots/SnapshotsService.java:75-87 — the flow: put snapshot intent
  into cluster state, each shard uploads its files incrementally, master
  finalizes a manifest. Single-process here: the service walks local
  shards directly; the distributed orchestration rides the cluster-state
  machinery once snapshots become cluster-state Customs.
- Incrementality: the reference diffs files by checksum
  (RecoverySourceHandler-style metadata); we content-address every shard
  blob by sha256, so an unchanged shard between snapshots uploads
  nothing and manifests share blobs. Deleting a snapshot garbage-collects
  unreferenced blobs.

Blob layout under the repository root:
    index.json                 {"snapshots": [names...]}
    snap-<name>.json           manifest: indices/shards -> blob hashes
    data/<sha256>              shard doc-stream blobs (npz)
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import time

import numpy as np

from .utils.errors import ElasticsearchTpuError, IllegalArgumentError


class SnapshotMissingError(ElasticsearchTpuError):
    status = 404


class SnapshotExistsError(ElasticsearchTpuError):
    status = 400


class RepositoryMissingError(ElasticsearchTpuError):
    status = 404


class UrlRepository:
    """READ-ONLY URL repository (ref: repositories/uri/
    URLRepository.java): restore/list against blobs served at a base
    URL — typically `file://` over a shared mount (which is also the
    only scheme exercisable on a zero-egress node; http(s) uses the
    same read path). Every write raises, like the reference."""

    readonly = True

    def __init__(self, url: str):
        if "://" not in url:
            url = "file://" + os.path.abspath(url)
        elif url.startswith("file://"):
            # a relative file path would urllib-parse as a HOSTNAME and
            # fail every read as a confusing 404 — absolutize instead
            path = url[len("file://"):]
            if not path.startswith("/"):
                url = "file://" + os.path.abspath(path)
        self.url = url.rstrip("/") + "/"

    def _open(self, name: str):
        import urllib.request
        import urllib.parse
        return urllib.request.urlopen(
            self.url + urllib.parse.quote(name))

    def read_blob(self, name: str) -> bytes:
        import urllib.error
        try:
            with self._open(name) as f:
                return f.read()
        except (urllib.error.URLError, OSError):
            raise SnapshotMissingError(
                f"missing blob [{name}]") from None

    def blob_exists(self, name: str) -> bool:
        import urllib.error
        try:
            with self._open(name):
                return True
        except (urllib.error.URLError, OSError):
            return False

    def list_snapshots(self) -> list:
        if not self.blob_exists("index.json"):
            return []
        return json.loads(self.read_blob("index.json")).get(
            "snapshots", [])

    def _read_only(self, *_a, **_k):
        raise IllegalArgumentError(
            "[url] repository is read-only "
            "(ref: URLRepository — restores only)")

    write_blob = delete_blob = _write_index = _read_only


class FsRepository:
    """Filesystem blob container (ref: common/blobstore/fs/)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.join(path, "data"), exist_ok=True)

    # -- blob primitives ---------------------------------------------------
    def _blob_path(self, name: str) -> str:
        return os.path.join(self.path, name)

    def write_blob(self, name: str, data: bytes) -> None:
        p = self._blob_path(name)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    def read_blob(self, name: str) -> bytes:
        try:
            with open(self._blob_path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise SnapshotMissingError(f"missing blob [{name}]") from None

    def blob_exists(self, name: str) -> bool:
        return os.path.exists(self._blob_path(name))

    def delete_blob(self, name: str) -> None:
        try:
            os.remove(self._blob_path(name))
        except OSError:
            pass

    # -- repo index --------------------------------------------------------
    def list_snapshots(self) -> list[str]:
        if not self.blob_exists("index.json"):
            return []
        return json.loads(self.read_blob("index.json")).get("snapshots", [])

    def _write_index(self, names: list[str]) -> None:
        self.write_blob("index.json", json.dumps(
            {"snapshots": sorted(names)}).encode())


def assert_snapshot_absent(repo, name: str) -> None:
    if name in repo.list_snapshots():
        raise SnapshotExistsError(f"snapshot [{name}] already exists")


class _repo_lock:
    """Exclusive lock over one repository's index mutations —
    concurrent coordinators on a shared fs repo must not lose each
    other's index entries or GC each other's blobs mid-operation (the
    reference serializes snapshot intent through cluster state; a
    shared fs repo gets a file lock instead)."""

    def __init__(self, repo):
        self._path = os.path.join(repo.path, "index.lock") \
            if hasattr(repo, "path") else None
        self._fh = None

    def __enter__(self):
        if self._path is not None:
            import fcntl
            self._fh = open(self._path, "a+")
            fcntl.flock(self._fh, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        if self._fh is not None:
            import fcntl
            fcntl.flock(self._fh, fcntl.LOCK_UN)
            self._fh.close()


def upload_shard(repo, docs) -> tuple[str, bool]:
    """Serialize + content-address one shard's doc stream; upload only
    when the digest is new. Shared by the single-node and cluster
    snapshot paths so their blobs stay interchangeable.
    -> (digest, uploaded)."""
    data = _serialize_shard(docs)
    digest = hashlib.sha256(data).hexdigest()
    blob = f"data/{digest}"
    if repo.blob_exists(blob):
        return digest, False
    repo.write_blob(blob, data)
    return digest, True


def finalize_snapshot(repo, name: str, manifest: dict) -> None:
    """Manifest write + index append, with the duplicate-name check
    INSIDE the critical section (the advisory pre-check callers run is
    not enough when two coordinators race on the same name)."""
    with _repo_lock(repo):
        names = repo.list_snapshots()
        if name in names:
            raise SnapshotExistsError(
                f"snapshot [{name}] already exists")
        repo.write_blob(f"snap-{name}.json",
                        json.dumps(manifest).encode())
        repo._write_index(names + [name])


def _serialize_shard(docs: list[tuple[str, int, bytes]]) -> bytes:
    """Doc stream -> one deterministic npz blob (content-addressable)."""
    docs = sorted(docs)  # determinism => stable hashes for unchanged shards
    ids = [d[0] for d in docs]
    versions = np.asarray([d[1] for d in docs], dtype=np.int64)
    blob = b"".join(d[2] for d in docs)
    offsets = np.zeros(len(docs) + 1, dtype=np.int64)
    np.cumsum([len(d[2]) for d in docs], out=offsets[1:])
    buf = io.BytesIO()
    np.savez(buf, versions=versions, offsets=offsets,
             sources=np.frombuffer(blob, dtype=np.uint8),
             ids=np.asarray(ids, dtype=object))
    return buf.getvalue()


def _deserialize_shard(data: bytes) -> list[tuple[str, int, bytes]]:
    z = np.load(io.BytesIO(data), allow_pickle=True)
    ids = list(z["ids"])
    versions = z["versions"]
    offsets = z["offsets"]
    blob = z["sources"].tobytes()
    return [(str(ids[i]), int(versions[i]),
             blob[offsets[i]: offsets[i + 1]]) for i in range(len(ids))]


class SnapshotsService:
    """Snapshot/restore against a Node's local indices.

    `node` needs: .indices (name -> IndexService with .shards engines,
    .mappers, .num_shards), .create_index, .delete_index.
    """

    def __init__(self, node):
        self.node = node
        self.repositories: dict[str, FsRepository] = {}
        self.repo_meta: dict[str, dict] = {}

    # -- repository admin (ref: RepositoriesService) -----------------------
    def put_repository(self, name: str, type_: str, settings: dict) -> dict:
        if type_ == "fs":
            location = settings.get("location")
            if not location:
                raise IllegalArgumentError(
                    "[fs] repository requires [location]")
            self.repositories[name] = FsRepository(location)
        elif type_ == "url":
            # READ-ONLY repository (ref: repositories/uri/
            # URLRepository.java): list/get/restore against blobs at a
            # base URL (file:// over a shared mount); writes rejected
            url = settings.get("url")
            if not url:
                raise IllegalArgumentError(
                    "[url] repository requires [url]")
            repo = UrlRepository(url)
            self._check_url_allowed(str(url), repo.url)
            self.repositories[name] = repo
        else:
            raise IllegalArgumentError(
                f"unknown repository type [{type_}] (only [fs], [url])")
        self.repo_meta[name] = {"type": type_,
                                "settings": dict(settings)}
        return {"acknowledged": True}

    def _check_url_allowed(self, raw: str, normalized: str) -> None:
        """SSRF guard for PUT _snapshot url repositories (ref:
        URLRepository.java behind `repositories.url.allowed_urls`): a
        REST caller must not turn the node into an arbitrary-fetch
        primitive. With the allowlist setting configured, the URL must
        match one of its entries (`*` wildcards, an entry also covers
        its subtree); with it unset, only file:// URLs (the zero-egress
        shared-mount case) are accepted and every http(s) URL is
        rejected outright. Matching runs on the `..`-RESOLVED canonical
        form only: `file:///mnt/repo/../etc` must not slip past a
        `file:///mnt/repo*` pattern just because the raw string happens
        to match — urllib's handlers resolve the dots at open time,
        outside the allowlisted subtree."""
        import fnmatch
        import posixpath
        import urllib.parse
        sp = urllib.parse.urlsplit(normalized)
        canon = urllib.parse.urlunsplit(
            (sp.scheme, sp.netloc,
             posixpath.normpath(sp.path or "/"), "", "")).rstrip("/")
        node_settings = getattr(self.node, "settings", None)
        allowed = node_settings.get_list(
            "repositories.url.allowed_urls") \
            if node_settings is not None else None
        if allowed:
            pats = []
            for p in allowed:
                p = str(p).rstrip("/")
                if p:
                    pats.extend((p, p + "/*"))
            if any(fnmatch.fnmatch(canon, p) for p in pats):
                return
            raise IllegalArgumentError(
                f"[url] repository [{raw}] doesn't match any of "
                f"repositories.url.allowed_urls {list(allowed)}")
        if canon.startswith("file://"):
            return
        raise IllegalArgumentError(
            "[url] repository with a non-file URL requires the "
            "[repositories.url.allowed_urls] setting (the reference's "
            "URLRepository whitelist)")

    def get_repositories(self, name: str | None = None) -> dict:
        """GET _snapshot[/{repo}] — repository metadata map (ref:
        TransportGetRepositoriesAction)."""
        if name in (None, "", "_all", "*"):
            return dict(self.repo_meta)
        if name not in self.repo_meta:
            raise RepositoryMissingError(f"[{name}] missing repository")
        return {name: self.repo_meta[name]}

    def verify_repository(self, name: str) -> dict:
        self._repo(name)
        node_name = getattr(self.node, "name", "node-0")
        return {"nodes": {node_name: {"name": node_name}}}

    def _repo(self, name: str) -> FsRepository:
        repo = self.repositories.get(name)
        if repo is None:
            raise RepositoryMissingError(f"[{name}] missing repository")
        return repo

    # -- create (ref: SnapshotsService.createSnapshot) ---------------------
    def create_snapshot(self, repo_name: str, snap_name: str,
                        indices: str | None = None) -> dict:
        repo = self._repo(repo_name)
        assert_snapshot_absent(repo, snap_name)
        services = self.node._resolve(indices)
        manifest: dict = {"snapshot": snap_name,
                          "state": "SUCCESS",
                          "start_time_ms": int(time.time() * 1000),
                          "indices": {}}
        n_reused = n_uploaded = 0
        for svc in services:
            entry = {"settings": {
                "index.number_of_shards": svc.num_shards,
                "index.number_of_replicas": svc.num_replicas},
                "mappings": svc.mappers.mapping_dict(),
                "shards": {}}
            for sid, eng in svc.shards.items():
                digest, uploaded = upload_shard(repo,
                                                eng.snapshot_docs())
                if uploaded:
                    n_uploaded += 1
                else:
                    n_reused += 1       # incremental: shard unchanged
                entry["shards"][str(sid)] = digest
            manifest["indices"][svc.name] = entry
        manifest["end_time_ms"] = int(time.time() * 1000)
        finalize_snapshot(repo, snap_name, manifest)
        return {"snapshot": {"snapshot": snap_name, "state": "SUCCESS",
                             "indices": sorted(manifest["indices"]),
                             "shards_uploaded": n_uploaded,
                             "shards_reused": n_reused}}

    # -- get / delete ------------------------------------------------------
    def get_snapshots(self, repo_name: str, names: str | None = None) -> dict:
        repo = self._repo(repo_name)
        all_names = repo.list_snapshots()
        if names in (None, "_all", "*"):
            wanted = all_names
        else:
            wanted = [n.strip() for n in str(names).split(",")]
        out = []
        for n in wanted:
            if n not in all_names:
                raise SnapshotMissingError(f"[{repo_name}:{n}] missing")
            m = json.loads(repo.read_blob(f"snap-{n}.json"))
            out.append({"snapshot": n, "state": m["state"],
                        "indices": sorted(m["indices"]),
                        "start_time_in_millis": m.get("start_time_ms"),
                        "end_time_in_millis": m.get("end_time_ms")})
        return {"snapshots": out}

    def delete_snapshot(self, repo_name: str, snap_name: str) -> dict:
        repo = self._repo(repo_name)
        # the whole delete (index rewrite + GC) holds the repo lock so
        # a concurrent snapshot's finalize cannot interleave; an
        # UNFINALIZED concurrent upload can still lose fresh blobs to
        # the GC (the reference closes that window via cluster-state
        # intent records, which a bare fs repo cannot express)
        with _repo_lock(repo):
            names = repo.list_snapshots()
            if snap_name not in names:
                raise SnapshotMissingError(
                    f"[{repo_name}:{snap_name}] missing")
            names.remove(snap_name)
            repo.delete_blob(f"snap-{snap_name}.json")
            repo._write_index(names)
            # GC blobs referenced by no remaining manifest
            referenced: set[str] = set()
            for n in names:
                m = json.loads(repo.read_blob(f"snap-{n}.json"))
                for entry in m["indices"].values():
                    referenced.update(entry["shards"].values())
            data_dir = os.path.join(repo.path, "data")
            for fname in os.listdir(data_dir):
                if fname not in referenced:
                    repo.delete_blob(f"data/{fname}")
        return {"acknowledged": True}

    # -- restore (ref: snapshots/RestoreService.java) ----------------------
    def restore_snapshot(self, repo_name: str, snap_name: str,
                         indices: str | None = None,
                         rename_pattern: str | None = None,
                         rename_replacement: str | None = None) -> dict:
        repo = self._repo(repo_name)
        if snap_name not in repo.list_snapshots():
            raise SnapshotMissingError(f"[{repo_name}:{snap_name}] missing")
        m = json.loads(repo.read_blob(f"snap-{snap_name}.json"))
        wanted = (sorted(m["indices"]) if indices in (None, "_all", "*")
                  else [n.strip() for n in str(indices).split(",")])
        restored = []
        for name in wanted:
            entry = m["indices"].get(name)
            if entry is None:
                raise SnapshotMissingError(
                    f"index [{name}] not in snapshot [{snap_name}]")
            target = name
            if rename_pattern and rename_replacement is not None:
                import re
                target = re.sub(rename_pattern, rename_replacement, name)
            if target in self.node.indices:
                raise IllegalArgumentError(
                    f"cannot restore index [{target}]: already exists "
                    f"(close or delete it first)")
            self.node.create_index(target, settings=entry["settings"],
                                   mappings=entry["mappings"])
            svc = self.node.indices[target]
            for sid_s, digest in entry["shards"].items():
                eng = svc.shards[int(sid_s)]
                for doc_id, version, source in _deserialize_shard(
                        repo.read_blob(f"data/{digest}")):
                    eng.apply_replicated(doc_id, source, version)
                eng.refresh()
            restored.append(target)
        return {"snapshot": {"snapshot": snap_name, "indices": restored,
                             "shards": {"failed": 0}}}
