"""Exact kNN scoring on the MXU.

Reference analog: dense_vector + kNN search (BASELINE.json config[4]
"dense_vector kNN + BM25 rescore"). The CPU reference needs an ANN graph
(HNSW) because exhaustive scan is slow on scalar cores; on TPU the scan
IS the fast path: a [B,D]x[D,N] bf16 matmul streams the whole shard's
vectors through the systolic array. SCORING is always exhaustive-exact;
candidate SELECTION is exact lax.top_k by default, or approx_max_k at a
declared recall target for large segments (callers overscan + re-sort
exactly, so the final k stays effectively exact — see
shard_searcher._knn_search). Beyond-exhaustive scale (10M+ vectors)
rides the IVF coarse-quantization path instead (index/ann.py +
ops/ann.py), which shares `knn_score_column` so probed-cluster scores
are bit-identical to the exact scan's. Scores use ES's transforms so
hybrid BM25+kNN sums stay sane:
  cosine      -> (1 + cos) / 2
  dot_product -> (1 + dot) / 2
  l2_norm     -> 1 / (1 + ||x - q||^2)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

SIMILARITIES = ("cosine", "dot_product", "l2_norm")


def knn_score_column(vectors: jax.Array, norms: jax.Array,
                     exists: jax.Array, query: jax.Array, *,
                     similarity: str) -> jax.Array:
    """Transformed similarity of every row vector -> [B, N] f32; rows
    without a vector score 0. The ONE definition of the per-doc vector
    score: the exact scan (knn_topk), the IVF probe (ops/ann.py), and
    the fused bundle engine's `knn_vec` clause (search/executor.py) all
    call here, so a hybrid BM25+vector bundle and its sequential
    BM25-then-knn oracle compute bit-identical similarity columns.

    vectors: [N, D] ordinals (any float dtype; cast to bf16 for the
    MXU with f32 accumulation); query: [B, D] f32.
    """
    q = query.astype(jnp.float32)
    v = vectors.astype(jnp.bfloat16)
    if similarity == "l2_norm":
        # ||x-q||^2 = ||x||^2 - 2 x.q + ||q||^2
        dots = jax.lax.dot_general(
            q.astype(jnp.bfloat16), v.T, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [B, N]
        qn = jnp.sum(q * q, axis=1, keepdims=True)
        d2 = jnp.maximum(norms[None, :] ** 2 - 2.0 * dots + qn, 0.0)
        scores = 1.0 / (1.0 + d2)
    else:
        if similarity == "cosine":
            qn = jnp.linalg.norm(q, axis=1, keepdims=True)
            q = q / jnp.maximum(qn, 1e-12)
        dots = jax.lax.dot_general(
            q.astype(jnp.bfloat16), v.T, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [B, N]
        if similarity == "cosine":
            dots = dots / jnp.maximum(norms[None, :], 1e-12)
            dots = jnp.clip(dots, -1.0, 1.0)  # bf16 rounding guard
        scores = (1.0 + dots) / 2.0
    return jnp.where(exists[None, :], scores, 0.0)


@partial(jax.jit, static_argnames=("similarity", "k", "approx_recall"))
def knn_topk(vectors: jax.Array, norms: jax.Array, exists: jax.Array,
             live: jax.Array, query: jax.Array, *, similarity: str,
             k: int, approx_recall: float | None = None
             ) -> tuple[jax.Array, jax.Array]:
    """-> (scores[B,k], idx[B,k]) over one segment.

    vectors: [N, D] f32 or bf16 ordinals; query: [B, D]. Matmul runs in
    bf16 on the MXU with f32 accumulation (preserve_precision via dot
    dtype).

    approx_recall: when set (e.g. 0.99), candidate selection uses the
    TPU-native approx_max_k instead of exact top_k — at 1M docs exact
    top_k costs ~84ms per 256-query batch while approx_max_k costs ~1ms
    at 0.99 recall. This is the analog of the reference's approximate
    HNSW retrieval stage (callers rescore candidates exactly), except
    recall is a declared target, not a graph-tuning side effect.
    """
    valid = exists & live                                  # [N]
    scores = knn_score_column(vectors, norms, exists, query,
                              similarity=similarity)
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    k = min(k, vectors.shape[0])
    if approx_recall is not None and k * 8 < vectors.shape[0]:
        return jax.lax.approx_max_k(scores, k,
                                    recall_target=float(approx_recall))
    return jax.lax.top_k(scores, k)
