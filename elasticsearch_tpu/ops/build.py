"""Device-side pack-build programs: sort / segment / scatter / reduce.

The heavy half of pack build (index/devbuild.py is the host driver):
postings construction over a tokenized batch is a stable sort by
(term-id, doc) followed by segment boundaries, cumulative sums and a
handful of scatters — exactly the shape that parallelizes on the mesh
("The Performance Envelope of Inverted Indexing on Modern Hardware"),
and the eager-impact layout the read path wants is what the scatters
emit directly (the BM25S observation).

Exactness contract — the reason a device-built pack can share
fingerprint-keyed caches, the autotune store and resident entries with
a host-built one: every program here performs only EXACT operations —

  * integer stable argsorts (the two-pass idiom below ≡ np.lexsort),
  * segment boundaries + integer cumulative sums,
  * scatter-set with unique target indices (pads dropped out of
    bounds), scatter-add of integers,
  * scatter-max / min-max reductions of f32 (order-free),
  * gathers.

No float arithmetic whose result could depend on association order or
on the backend's libm runs on device. The one float computation of
pack build — eager BM25 impacts — deliberately stays in the canonical
host path (`segment._flat_impacts`): XLA's exp/log differ from
numpy's in the last ulp, and the identity contract is bit-for-bit.
Consequence: the same programs are byte-identical on EVERY backend,
including the JAX_PLATFORMS=cpu fallback the tier-1 suite runs under.

Shape discipline: callers pad every input to pow2 buckets
(`batch_cap` occurrences, `term_cap`/`vocab_buckets` vocabulary,
`cap` docs, `n_slots` forward lanes) so builder shapes don't thrash
XLA — the same next_pow2 convention as the read path. Pad elements
carry sort keys that order AFTER every real element (INT32_MAX) or
scatter indices that land out of bounds (dropped by mode="drop";
always padded POSITIVE-side — jnp wraps negative indices).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# block lane width — keep in sync with index/segment.BLOCK (not
# imported: ops modules stay import-light so index can lazy-load them)
BLOCK = 128


def lexsort_by_term_doc(tid: jnp.ndarray, doc: jnp.ndarray) -> jnp.ndarray:
    """Permutation sorting occurrences by (term-id, doc), stably.

    Two-pass stable argsort ≡ np.lexsort((doc, tid)) — composing a
    stable sort on the minor key with one on the major key avoids the
    int64 fused key (tid * cap + doc), which would overflow int32 on
    non-x64 jax. Stability preserves token order within each
    (term, doc) group, which is what keeps position lists byte-equal
    to the host builder's per-doc accumulation order.
    """
    order = jnp.argsort(doc, stable=True)
    return order[jnp.argsort(tid[order], stable=True)]


@partial(jax.jit, static_argnames=("batch_cap", "vocab_buckets"))
def sort_segment_postings(tid: jnp.ndarray, doc: jnp.ndarray,
                          pos: jnp.ndarray, *, batch_cap: int,
                          vocab_buckets: int):
    """Sort one field's occurrence stream and segment it into postings.

    Inputs are [batch_cap] int32 (the static pins every shape in the
    program — one compile per pow2 bucket), padded with
    tid = doc = INT32_MAX so pads sort to the tail (they collapse into
    one trailing pseudo posting the host slices off). Returns

      pos_s  [batch_cap] positions in CSR order (== pos_data stream),
      tf     [batch_cap] occurrences per posting (position counts),
      df     [vocab_buckets] postings per term (int, exact),
      p_tid  [batch_cap] term id per posting,
      p_doc  [batch_cap] doc id per posting (== doc_ids stream).

    Postings are numbered by first occurrence in the sorted stream, so
    posting order is (term asc, doc asc) — the host CSR order.
    """
    order = lexsort_by_term_doc(tid, doc)
    tid_s = tid[order]
    doc_s = doc[order]
    pos_s = pos[order]
    idx = jnp.arange(batch_cap, dtype=jnp.int32)
    newseg = (idx == 0) | (tid_s != jnp.roll(tid_s, 1)) \
        | (doc_s != jnp.roll(doc_s, 1))
    seg = newseg.astype(jnp.int32)
    pid = jnp.cumsum(seg) - 1
    tf = jnp.zeros(batch_cap, jnp.int32).at[pid].add(
        jnp.ones_like(pid))
    # pads carry tid INT32_MAX >= vocab_buckets — dropped
    df = jnp.zeros(vocab_buckets, jnp.int32).at[tid_s].add(
        seg, mode="drop")
    # every occurrence of a posting writes the same value: exact
    p_tid = jnp.zeros(batch_cap, jnp.int32).at[pid].set(tid_s)
    p_doc = jnp.zeros(batch_cap, jnp.int32).at[pid].set(doc_s)
    return pos_s, tf, df, p_tid, p_doc


@partial(jax.jit, static_argnames=("nb_cap",))
def pack_block_lanes(slot_idx: jnp.ndarray, docs: jnp.ndarray,
                     imps: jnp.ndarray, fill_doc: jnp.ndarray, *,
                     nb_cap: int):
    """Scatter CSR postings into the flat 128-lane block arrays.

    slot_idx[i] = (block_start[tid] + rank // 128) * 128 + rank % 128
    (host-computed, unique per posting; pads = nb_cap * 128 → dropped).
    Unwritten lanes keep the host pad convention: doc = cap (fill_doc),
    impact = 0.
    """
    bd = jnp.full(nb_cap * BLOCK, fill_doc, jnp.int32)
    bd = bd.at[slot_idx].set(docs, mode="drop")
    bi = jnp.zeros(nb_cap * BLOCK, jnp.float32)
    bi = bi.at[slot_idx].set(imps, mode="drop")
    return bd, bi


@jax.jit
def forward_slots(doc_ids: jnp.ndarray) -> jnp.ndarray:
    """Per-posting forward-index slot: the posting's rank within its
    doc in CSR (term-ascending) order — the order the host builder
    fills slots in. One stable sort by doc groups each doc's postings
    (stability preserves CSR order inside the group), a running
    group-start cummax turns positions into ranks, and the inverse
    permutation carries ranks back to posting order. Pads carry
    doc = INT32_MAX and group at the tail (their slots are garbage;
    the host slices them off).
    """
    n = doc_ids.shape[0]
    order = jnp.argsort(doc_ids, stable=True)
    d_s = doc_ids[order]
    idx = jnp.arange(n, dtype=jnp.int32)
    newgrp = (idx == 0) | (d_s != jnp.roll(d_s, 1))
    start = jax.lax.cummax(jnp.where(newgrp, idx, 0))
    rank = idx - start
    return jnp.zeros(n, jnp.int32).at[order].set(rank)


@partial(jax.jit, static_argnames=("cap", "n_slots"))
def scatter_forward(docs: jnp.ndarray, slots: jnp.ndarray,
                    tids: jnp.ndarray, imps: jnp.ndarray, *,
                    cap: int, n_slots: int):
    """Scatter postings into the [cap, n_slots] forward index.

    (doc, slot) pairs are unique; pads carry doc = cap (row out of
    bounds → dropped). 2-D scatter keeps indices inside int32 even
    when cap * n_slots would overflow a flat int32 index.
    """
    ft = jnp.full((cap, n_slots), -1, jnp.int32)
    ft = ft.at[docs, slots].set(tids, mode="drop")
    fi = jnp.zeros((cap, n_slots), jnp.float32)
    fi = fi.at[docs, slots].set(imps, mode="drop")
    return ft, fi


@partial(jax.jit, static_argnames=("cap", "pos_cols"))
def scatter_positions(docs: jnp.ndarray, cols: jnp.ndarray,
                      deltas: jnp.ndarray, *, cap: int, pos_cols: int):
    """Scatter per-position int16 deltas into the [cap, pos_cols]
    positional pack (pos_cols = n_slots * P, both pow2-bucketed by the
    caller — the pad_delta_shapes convention). (doc, col) pairs are
    unique per position; pads carry doc = cap (row out of bounds →
    dropped). Integer scatter-set with unique targets: byte-identical
    to the host pack_positions fill.
    """
    fp = jnp.full((cap, pos_cols), -1, jnp.int16)
    return fp.at[docs, cols].set(deltas, mode="drop")


@partial(jax.jit, static_argnames=("term_cap", "n_tiles"))
def scatter_tile_max(tids: jnp.ndarray, tiles: jnp.ndarray,
                     imps: jnp.ndarray, *, term_cap: int, n_tiles: int):
    """build_tile_max as one scatter-max: out[t, doc // tile] =
    max impact of t's postings in that tile. Max is order-free, so the
    result is byte-equal to the host's np.maximum.at over the forward
    index (same value multiset per cell, zeros elsewhere). Pads carry
    tid = term_cap → dropped; the host slices rows [:n_terms].
    """
    out = jnp.zeros((term_cap, n_tiles), jnp.float32)
    return out.at[tids, tiles].max(imps, mode="drop")


@partial(jax.jit, static_argnames=("n_tiles",))
def tile_minmax(vals: jnp.ndarray, exists: jnp.ndarray,
                lo_pad: jnp.ndarray, hi_pad: jnp.ndarray, *,
                n_tiles: int):
    """Per-tile min/max of a doc-value column, absent/NaN rows masked
    to the identity sentinels (exists already excludes NaN — the host
    caller masks once for both paths). Min/max reductions are
    order-free: byte-equal to the host build_tile_minmax.
    """
    vt = vals.reshape(n_tiles, -1)
    et = exists.reshape(n_tiles, -1)
    lo = jnp.where(et, vt, lo_pad).min(axis=1)
    hi = jnp.where(et, vt, hi_pad).max(axis=1)
    return lo, hi


@partial(jax.jit, static_argnames=("iters",))
def _kmeans_loop(x: jnp.ndarray, valid: jnp.ndarray,
                 cent0: jnp.ndarray, *, iters: int) -> jnp.ndarray:
    """Jitted Lloyd iterations (index/ann._kmeans promoted whole).

    Mirrors the host loop step-for-step: argmin assignment, mean
    update, then empty clusters reseeded from the farthest points
    (rank-matched: the i-th empty cluster takes the i-th farthest
    point, exactly the host's `cent[empty] = x[far[:n_empty]]`).
    Padded rows (valid == False) are parked on assignment index C
    (dropped by the scatters) and carry dmin = -inf so they are never
    picked as reseed candidates. f32 means/distances run in XLA — this
    path does NOT promise bit-equality with the numpy host k-means
    (it doesn't need to: the byte-identity contract is between
    host-built and device-built SEGMENTS, which share whichever
    k-means path is enabled), only determinism per backend.
    """
    n, _d = x.shape
    c = cent0.shape[0]
    x2 = jnp.einsum("nd,nd->n", x, x)

    def step(_i, cent):
        c2 = jnp.einsum("cd,cd->c", cent, cent)
        d = c2[None, :] - 2.0 * jnp.dot(
            x, cent.T, preferred_element_type=jnp.float32)
        assign = jnp.argmin(d, axis=1).astype(jnp.int32)
        assign = jnp.where(valid, assign, c)
        counts = jnp.zeros(c, jnp.int32).at[assign].add(
            jnp.ones_like(assign), mode="drop")
        sums = jnp.zeros_like(cent).at[assign].add(x, mode="drop")
        nonempty = counts > 0
        mean = sums / jnp.maximum(counts, 1).astype(x.dtype)[:, None]
        dmin = jnp.take_along_axis(
            d, jnp.clip(assign, 0, c - 1)[:, None], axis=1)[:, 0] + x2
        dmin = jnp.where(valid, dmin, -jnp.inf)
        far = jnp.argsort(-dmin)
        ranks = jnp.cumsum((~nonempty).astype(jnp.int32)) - 1
        cand = x[far[jnp.clip(ranks, 0, n - 1)]]
        return jnp.where(nonempty[:, None], mean, cand)

    return jax.lax.fori_loop(0, iters, step, cent0)


def kmeans_device(x: np.ndarray, n_clusters: int, seed: int,
                  iters: int = 10) -> np.ndarray:
    """Device k-means entry: host rng picks the same init sample as the
    host path (np.default_rng(seed).choice without replacement), the
    Lloyd loop runs jitted. Rows are padded to a pow2 batch so builder
    shapes don't thrash XLA (`batch` joins the compile key).
    """
    x = np.ascontiguousarray(x, dtype=np.float32)
    n = x.shape[0]
    rng = np.random.default_rng(seed)
    init = x[rng.choice(n, size=n_clusters, replace=False)].copy()
    batch = _next_pow2(n, floor=BLOCK)
    xp = np.zeros((batch, x.shape[1]), np.float32)
    xp[:n] = x
    valid = np.zeros(batch, bool)
    valid[:n] = True
    cent = _kmeans_loop(jnp.asarray(xp), jnp.asarray(valid),
                        jnp.asarray(init), iters=int(iters))
    return np.asarray(jax.device_get(cent), dtype=np.float32)


def _next_pow2(n: int, floor: int = 1) -> int:
    # mirror of index/segment.next_pow2 (kept local: ops stays
    # import-light)
    n = max(int(n), floor, 1)
    return 1 << (n - 1).bit_length()
