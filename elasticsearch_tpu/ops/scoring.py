"""Batched posting-scatter scoring primitives (pure JAX).

These replace the Lucene hot loop the reference runs per shard
(search/query/QueryPhase.java:153 — BulkScorer iterating postings with
BM25 Similarity into TopScoreDocCollector). The TPU formulation is
BM25S-style eager scoring (PAPERS.md): per-posting BM25 impacts are
precomputed at index time, so a query is

    gather posting blocks -> weight -> scatter-add into dense per-doc scores

which is batched over queries ([B, ...]) and vectorized over the 128-lane
posting blocks. On a real TPU backend the executor dispatches these
clause kinds to the fused Pallas kernels in ops/pallas_scoring.py
(one-hot MXU scatter with sorted-range tile skip; tiled forward-index
compare+FMA); these jnp versions are the reference semantics, the CPU
path, and what the kernels are tested against in interpret mode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..index.segment import BLOCK


def batched_scatter_add(ids: jax.Array, vals: jax.Array, cap: int) -> jax.Array:
    """scores[b, ids[b, n]] += vals[b, n]; ids == cap (or any OOB) dropped.

    ids: int32 [B, N], vals: float32 [B, N] -> [B, cap] float32.
    """

    def one(i, v):
        return jnp.zeros((cap,), jnp.float32).at[i].add(v, mode="drop")

    return jax.vmap(one)(ids, vals)


def gather_term_blocks(block_docs: jax.Array, block_imps: jax.Array,
                       block_lo: jax.Array, nb_valid: jax.Array,
                       nb_pad: int, cap: int) -> tuple[jax.Array, jax.Array]:
    """Gather a term's posting blocks per batched query.

    block_docs/block_imps: [NB, 128] segment posting storage.
    block_lo: [B] first block of this term, nb_valid: [B] how many blocks.
    Returns (docs [B, nb_pad*128] padded with `cap`, imps [B, nb_pad*128]).
    """
    iota = jnp.arange(nb_pad, dtype=jnp.int32)
    idx = block_lo[:, None] + iota[None, :]                   # [B, nb_pad]
    ok = iota[None, :] < nb_valid[:, None]
    safe = jnp.where(ok, idx, 0)
    docs = block_docs[safe]                                   # [B, nb_pad, 128]
    imps = block_imps[safe]
    docs = jnp.where(ok[..., None], docs, cap)                # padded -> dropped
    b = block_lo.shape[0]
    return docs.reshape(b, nb_pad * BLOCK), imps.reshape(b, nb_pad * BLOCK)


def score_term(block_docs: jax.Array, block_imps: jax.Array,
               block_lo: jax.Array, nb_valid: jax.Array, weight: jax.Array,
               nb_pad: int, cap: int) -> jax.Array:
    """Score one text-term clause for a batch of queries -> [B, cap].

    weight multiplies the precomputed BM25 impact (query boost; the idf is
    already inside the impact). score > 0 wherever the term matched, so
    the same array doubles as the match mask (bind clamps weight > 0).
    """
    docs, imps = gather_term_blocks(block_docs, block_imps, block_lo, nb_valid,
                                    nb_pad, cap)
    return batched_scatter_add(docs, imps * weight[:, None], cap)


def gather_fused_blocks(block_docs: jax.Array, block_imps: jax.Array,
                        gather_idx: jax.Array, weights: jax.Array,
                        cap: int) -> tuple[jax.Array, jax.Array]:
    """Gather + weight the blocks of a fused disjunction group.

    gather_idx: [B, M] absolute block indices (-1 = padding);
    weights: [B, M] per-block clause weight.
    Returns (docs [B, M*128] padded with cap, vals [B, M*128]) — the
    single shared preamble for both the jnp and Pallas scatter backends.
    """
    ok = gather_idx >= 0
    safe = jnp.where(ok, gather_idx, 0)
    docs = block_docs[safe]                                   # [B, M, 128]
    imps = block_imps[safe]
    docs = jnp.where(ok[..., None], docs, cap)
    vals = imps * weights[..., None]
    b, m = gather_idx.shape
    return docs.reshape(b, m * BLOCK), vals.reshape(b, m * BLOCK)


def score_terms_fused(block_docs: jax.Array, block_imps: jax.Array,
                      gather_idx: jax.Array, weights: jax.Array,
                      cap: int) -> jax.Array:
    """Score MANY term clauses of one disjunction group in a single scatter.

    Used for `should`-group fusion (a match query's terms all land in one
    scatter) — the common fast path for the http_logs bench query.
    """
    docs, vals = gather_fused_blocks(block_docs, block_imps, gather_idx,
                                     weights, cap)
    return batched_scatter_add(docs, vals, cap)
