"""Batched posting-scatter scoring primitives (pure JAX).

These replace the Lucene hot loop the reference runs per shard
(search/query/QueryPhase.java:153 — BulkScorer iterating postings with
BM25 Similarity into TopScoreDocCollector). The TPU formulation is
BM25S-style eager scoring (PAPERS.md): per-posting BM25 impacts are
precomputed at index time, so a query is

    gather posting blocks -> weight -> scatter-add into dense per-doc scores

which is batched over queries ([B, ...]) and vectorized over the 128-lane
posting blocks. On a real TPU backend the executor dispatches these
clause kinds to the fused Pallas kernels in ops/pallas_scoring.py
(one-hot MXU scatter with sorted-range tile skip; tiled forward-index
compare+FMA); these jnp versions are the reference semantics, the CPU
path, and what the kernels are tested against in interpret mode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..index.segment import BLOCK
from .topk import NEG_INF, running_topk_init, running_topk_merge


def batched_scatter_add(ids: jax.Array, vals: jax.Array, cap: int) -> jax.Array:
    """scores[b, ids[b, n]] += vals[b, n]; ids == cap (or any OOB) dropped.

    ids: int32 [B, N], vals: float32 [B, N] -> [B, cap] float32.
    """

    def one(i, v):
        return jnp.zeros((cap,), jnp.float32).at[i].add(v, mode="drop")

    return jax.vmap(one)(ids, vals)


def gather_term_blocks(block_docs: jax.Array, block_imps: jax.Array,
                       block_lo: jax.Array, nb_valid: jax.Array,
                       nb_pad: int, cap: int) -> tuple[jax.Array, jax.Array]:
    """Gather a term's posting blocks per batched query.

    block_docs/block_imps: [NB, 128] segment posting storage.
    block_lo: [B] first block of this term, nb_valid: [B] how many blocks.
    Returns (docs [B, nb_pad*128] padded with `cap`, imps [B, nb_pad*128]).
    """
    iota = jnp.arange(nb_pad, dtype=jnp.int32)
    idx = block_lo[:, None] + iota[None, :]                   # [B, nb_pad]
    ok = iota[None, :] < nb_valid[:, None]
    safe = jnp.where(ok, idx, 0)
    docs = block_docs[safe]                                   # [B, nb_pad, 128]
    imps = block_imps[safe]
    docs = jnp.where(ok[..., None], docs, cap)                # padded -> dropped
    b = block_lo.shape[0]
    return docs.reshape(b, nb_pad * BLOCK), imps.reshape(b, nb_pad * BLOCK)


def score_term(block_docs: jax.Array, block_imps: jax.Array,
               block_lo: jax.Array, nb_valid: jax.Array, weight: jax.Array,
               nb_pad: int, cap: int) -> jax.Array:
    """Score one text-term clause for a batch of queries -> [B, cap].

    weight multiplies the precomputed BM25 impact (query boost; the idf is
    already inside the impact). score > 0 wherever the term matched, so
    the same array doubles as the match mask (bind clamps weight > 0).
    """
    docs, imps = gather_term_blocks(block_docs, block_imps, block_lo, nb_valid,
                                    nb_pad, cap)
    return batched_scatter_add(docs, imps * weight[:, None], cap)


def gather_fused_blocks(block_docs: jax.Array, block_imps: jax.Array,
                        gather_idx: jax.Array, weights: jax.Array,
                        cap: int) -> tuple[jax.Array, jax.Array]:
    """Gather + weight the blocks of a fused disjunction group.

    gather_idx: [B, M] absolute block indices (-1 = padding);
    weights: [B, M] per-block clause weight.
    Returns (docs [B, M*128] padded with cap, vals [B, M*128]) — the
    single shared preamble for both the jnp and Pallas scatter backends.
    """
    ok = gather_idx >= 0
    safe = jnp.where(ok, gather_idx, 0)
    docs = block_docs[safe]                                   # [B, M, 128]
    imps = block_imps[safe]
    docs = jnp.where(ok[..., None], docs, cap)
    vals = imps * weights[..., None]
    b, m = gather_idx.shape
    return docs.reshape(b, m * BLOCK), vals.reshape(b, m * BLOCK)


def score_terms_fused(block_docs: jax.Array, block_imps: jax.Array,
                      gather_idx: jax.Array, weights: jax.Array,
                      cap: int) -> jax.Array:
    """Score MANY term clauses of one disjunction group in a single scatter.

    Used for `should`-group fusion (a match query's terms all land in one
    scatter) — the common fast path for the http_logs bench query.
    """
    docs, vals = gather_fused_blocks(block_docs, block_imps, gather_idx,
                                     weights, cap)
    return batched_scatter_add(docs, vals, cap)


# ---------------------------------------------------------------------------
# Fused block-max score + top-k (forward-index path)
#
# The unfused pipeline materializes a full [B, cap] score matrix and runs
# lax.top_k over it. The fused pipeline walks SCORE_TILE-doc tiles with a
# fori_loop carrying a running top-k, and uses the pack-time block-max
# summaries (index/segment.build_tile_max) to skip tiles that cannot
# change the result — the block-max WAND idea (arxiv 1910.11028) mapped
# onto dense tiles. Two prune levels per tile, both decided batch-wide
# (per-lane skipping saves nothing on SIMD hardware):
#
#   hard skip:  no query's bound is > 0 in this tile -> no doc can match;
#               the tile contributes nothing, not even to total hits.
#   threshold:  every query's bound is <= its running k-th best score ->
#               the tile is scored for EXACT hit counting, but the
#               per-tile top-k extraction + merge is skipped.
#
# Tie safety: a tile is threshold-pruned only when each doc's score is
# <= the query's current k-th best, which came from LOWER doc ids
# (tiles run in doc order) — and lax.top_k breaks ties toward the lower
# index, so a tied pruned doc would have lost anyway.
# ---------------------------------------------------------------------------


# relative slack applied to the tile bounds before THRESHOLD compares:
# the bound and the score loops accumulate in the same q order, but the
# compilers (XLA for the bounds, XLA or Mosaic for the scores) may
# contract one side's mul+add into an FMA and not the other's, letting
# a tile's best doc round a few ULPs ABOVE its bound. 32 eps covers any
# realistic query-term count; scores are nonnegative, so scaling the
# bound up only makes pruning more conservative. Hard-skip (ub > 0)
# needs no slack: every per-term product of the bound dominates the
# corresponding per-doc product under monotone f32 rounding, so ub == 0
# forces all doc scores to 0 regardless of contraction.
BOUND_SLACK = 1.0 + 32 * float(jnp.finfo(jnp.float32).eps)


def dense_tile_bounds(tile_max: jax.Array, qt: jax.Array, wq: jax.Array
                      ) -> jax.Array:
    """[T, J] block-max summary x [B, Q] query -> [B, J] score bounds
    (BOUND_SLACK-inflated, see above). Padded/absent terms (qt < 0)
    contribute 0, mirroring their zero-impact matches."""
    b, q_n = qt.shape
    n_tiles = tile_max.shape[1]
    safe = jnp.clip(qt, 0, max(tile_max.shape[0] - 1, 0))
    ub = jnp.zeros((b, n_tiles), jnp.float32)
    for q in range(q_n):
        tm = tile_max[safe[:, q]]                       # [B, J]
        w = jnp.where(qt[:, q] >= 0, wq[:, q], 0.0)
        ub = ub + tm * w[:, None]
    return ub * jnp.float32(BOUND_SLACK)


def _dense_tile_scores(t_tids: jax.Array, t_imps: jax.Array,
                       qt: jax.Array, wq: jax.Array) -> jax.Array:
    """One tile of the forward-index scoring loop: [tile, L] x [B, Q] ->
    [B, tile], with the same reduction order as the unfused jnp path so
    fused and unfused scores are bit-identical."""
    b = qt.shape[0]
    tile = t_tids.shape[0]
    score = jnp.zeros((b, tile), jnp.float32)
    for q in range(qt.shape[1]):
        tq = qt[:, q][:, None, None]                    # [B, 1, 1]
        contrib = jnp.sum(
            jnp.where(t_tids[None] == tq, t_imps[None], 0.0), axis=-1)
        score = score + contrib * wq[:, q][:, None]
    return score


def score_topk_dense_fused(fwd_tids: jax.Array, fwd_imps: jax.Array,
                           tile_max: jax.Array, qt: jax.Array,
                           wq: jax.Array, live: jax.Array, k: int,
                           msm: jax.Array | None = None,
                           boost: jax.Array | None = None
                           ) -> tuple[jax.Array, jax.Array, jax.Array,
                                      jax.Array]:
    """Fused forward-index BM25 score + top-k with block-max pruning.

    Returns (top_scores [B, k], top_idx [B, k], total [B] int32,
    prune_stats int32 [3] = (hard_skipped, thresholded, tiles_examined)).
    Entries past a query's total are -inf with undefined indices — the
    top_k_hits contract. `msm`/`boost` carry the enclosing single-should
    bool node's dynamic params (msm <= 0 matches everything, msm > 1
    matches nothing, boost scales scores and MUST be > 0). Scores are
    bit-identical to the unfused eval_node path: same per-tile reduction
    order, boost applied AFTER selection exactly as eval_node computes
    fl(sum(w*imp)) * boost, and pruning decisions compare against
    monotone upper bounds. CAVEAT: selection happens on PRE-boost
    scores, so a non-unit boost whose f32 rounding creates a post-boost
    tie at the k-th boundary can break that tie differently than the
    unfused path — callers needing exact doc-id identity with the
    unfused path (the production admission rule does) must pass
    boost = 1.

    Correct pruning relies on the forward-index invariant that a doc's
    slots hold DISTINCT term ids (one slot per distinct term).
    """
    cap, _slots = fwd_tids.shape
    b, _q_n = qt.shape
    n_tiles = tile_max.shape[1]
    tile = cap // n_tiles
    k = min(k, cap)
    ck = min(k, tile)
    if msm is None:
        msm = jnp.ones((b,), jnp.int32)
    all_match = msm <= 0
    matchable = msm <= 1
    ub = dense_tile_bounds(tile_max, qt, wq)            # [B, J]

    def body(j, st):
        top_s, top_i, total, pruned = st
        lo = j * tile
        ub_j = jax.lax.dynamic_slice_in_dim(ub, j, 1, axis=1)[:, 0]
        can_hit = (ub_j > 0.0) | all_match

        def hard_skip(st):
            top_s, top_i, total, pruned = st
            return (top_s, top_i, total,
                    pruned + jnp.array([1, 0, 1], jnp.int32))

        def score_tile(st):
            top_s, top_i, total, pruned = st
            t_tids = jax.lax.dynamic_slice(fwd_tids, (lo, 0),
                                           (tile, fwd_tids.shape[1]))
            t_imps = jax.lax.dynamic_slice(fwd_imps, (lo, 0),
                                           (tile, fwd_imps.shape[1]))
            t_live = jax.lax.dynamic_slice(live, (lo,), (tile,))
            score = _dense_tile_scores(t_tids, t_imps, qt, wq)
            match = (((score > 0.0) | all_match[:, None])
                     & matchable[:, None] & t_live[None, :])
            total = total + match.sum(axis=-1, dtype=jnp.int32)
            can_top = can_hit & (ub_j > top_s[:, -1])

            def merge(args):
                ts, ti = args
                cand = jnp.where(match, score, NEG_INF)
                c_s, c_loc = jax.lax.top_k(cand, ck)
                return running_topk_merge(ts, ti, c_s, c_loc + lo)

            any_top = jnp.any(can_top)
            top_s, top_i = jax.lax.cond(any_top, merge, lambda a: a,
                                        (top_s, top_i))
            pruned = pruned + jnp.where(
                any_top, jnp.array([0, 0, 1], jnp.int32),
                jnp.array([0, 1, 1], jnp.int32))
            return top_s, top_i, total, pruned

        return jax.lax.cond(jnp.any(can_hit), score_tile, hard_skip, st)

    top_s0, top_i0 = running_topk_init(b, k)
    top_s, top_i, total, pruned = jax.lax.fori_loop(
        0, n_tiles, body,
        (top_s0, top_i0, jnp.zeros((b,), jnp.int32),
         jnp.zeros((3,), jnp.int32)))
    if boost is not None:
        # post-selection like eval_node (order-preserving: boost > 0,
        # and -inf tail entries stay -inf)
        top_s = top_s * boost[:, None]
    return top_s, top_i, total, pruned
